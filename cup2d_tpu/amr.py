"""AMR simulation: the reference's adaptive solver on the block forest.

Reproduces the reference's adaptive time loop (`/root/reference/main.cpp`
adapt() 4657-5440 + the hot loop 6576-7290) with the TPU split:

host (numpy, per regrid)         device (jit, per step)
------------------------------   --------------------------------------
tagging decisions + 2:1 sweeps   vorticity + chi tags (lab + kernel)
slot alloc/release, SFC order    WENO5 advection-diffusion RK2 over all
halo gather-table rebuild          blocks at once (per-block h arrays)
window block selection           SDF/udef rasterization into blocks,
                                   chi, integrals, penalization solve,
                                   collisions (ops/obstacle, collision)
                                 prolongation / restriction batches
                                 matrix-free BiCGSTAB on the forest
                                   (makeFlux variable-resolution rows +
                                   block-Jacobi GEMM)

Level interfaces are discretely conservative: the Poisson operator uses
the makeFlux variable-resolution closure and the stencil kernels carry
coarse-fine flux correction (both in flux.py).

Obstacles live on the forest exactly as the reference's ongrid() does
(main.cpp:3991-4630): blocks intersecting a body's bounding box are
selected on the host (padded to a static capacity so the moving body
never retriggers compilation), the device rasterizes SDF/udef per block
at that block's own resolution, chi comes from the combined-SDF lab, and
the chi field drives GradChiOnTmp-style refinement (main.cpp:4631-4656)
so the body is always surrounded by finest-level blocks.

Jitted functions take tables/order/h as arguments, so regrids that
reproduce previously-seen shapes hit the XLA compile cache.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .config import SimConfig
from .flux import apply_flux_corr, build_flux_corr, \
    build_poisson_structured, build_poisson_tables, diffusive_deposits, \
    divergence_deposits, gradient_deposits, poisson_apply_structured
from .forest import Forest
from .halo import _TopoIndex, _bucket, assemble_labs, \
    assemble_labs_ordered, build_face_copy, build_tables, \
    make_fast_tables, pad_tables
from . import native
from .ops.collision import merged_overlap_integrals, \
    pairwise_collision_update
from .ops.forces import surface_forces_blocks
from .ops.obstacle import (
    chi_from_sdf,
    midline_udef_packed,
    pack_midline,
    pack_polygon_segments,
    penalization_integrals,
    polygon_sdf_seg,
    shape_integrals,
    solve_rigid_momentum,
)
from .ops.stencil import advect_diffuse_rhs, divergence, dt_from_umax, \
    heun_substage, laplacian5, pressure_gradient_update, vorticity
from .poisson import ForestFASCycle, _down2_mean, _up2_bilinear, \
    apply_block_precond_blocks, bicgstab, block_precond_matrix, \
    coarse_neumann_solve_dct, mg_solve
from .profiling import NULL_TIMERS
from .shapes_host import ShapeHostMixin


class ObstacleForestFields(NamedTuple):
    """Per-step obstacle state on the forest, in SFC-ordered block layout
    (the reference's per-shape Obstacle blocks + global chi/tmp grids,
    main.cpp:3283-3342). Vector fields are component-first so the shared
    penalization/collision kernels (which index [0]/[1]) apply
    unchanged."""

    chi: jnp.ndarray      # [N, BS, BS] combined (max over shapes)
    sdf: jnp.ndarray      # [N, BS, BS] combined signed distance
    chi_s: jnp.ndarray    # [S, N, BS, BS]
    sdf_s: jnp.ndarray    # [S, N, BS, BS]
    udef_s: jnp.ndarray   # [S, 2, N, BS, BS] de-meaned deformation vel
    com: jnp.ndarray      # [S, 2] chi-corrected centers of mass
    mass: jnp.ndarray     # [S]
    inertia: jnp.ndarray  # [S]


def _tiles_img(entry, rp, bs: int):
    """Paint one level's uniform image from ordered block rows by ONE
    block-row gather (the round-5 structured transfer primitive; see
    _build_coarse_maps). Shared by the two-level preconditioner
    transfers (_coarse_transfers) and the forest FAS hierarchy's
    per-level deposits (_fas_transfers)."""
    own, ownm, _, _ = entry
    nty, ntx = own.shape
    img = rp[own.reshape(-1)] * ownm.reshape(-1)[:, None, None]
    return img.reshape(nty, ntx, bs, bs) \
              .transpose(0, 2, 1, 3) \
              .reshape(nty * bs, ntx * bs)


def _extract_tiles(a, entry, e, bs: int):
    """Adjoint of _tiles_img: gather each active block's tile out of a
    level image and add into the ordered-block accumulator ``e``."""
    own, _, tid, selp = entry
    nty, ntx = own.shape
    tiles = a.reshape(nty, bs, ntx, bs) \
             .transpose(0, 2, 1, 3) \
             .reshape(nty * ntx, bs, bs)
    return e + tiles[tid] * selp[:, None, None]


def _raster_neg(cfg, dtype):
    """The "far outside" SDF sentinel, ONE definition: the sharded
    window raster cannot receive it as an argument (shard_map bodies
    must not close over tracers), so both paths construct it from the
    config through this function and provably agree."""
    return jnp.asarray(-float(cfg.extent), dtype)


def _window_sdf_udef(inp, bs: int, dtype):
    """Evaluate one shape's SDF + deformation velocity over its window
    blocks ([P, BS, BS] / [2, P, BS, BS]) from the window-block origins
    shipped in ``inp`` (PutFishOnBlocks, main.cpp:3774-3990). The ONE
    definition shared by the single-device scatter and the shard-local
    scatter (forest_mesh.ShardedAMRSim._window_raster) — the sharded ==
    single-device equality tests assume bit-identical evaluation.
    Consumes the host-packed segment/midline tables (ops.obstacle.pack_*
    — built in body frame, com already subtracted): the op-level trace
    showed the unpacked form spending ~40% of megastep device time
    staging tiny per-field shape arrays through scratch."""
    ar = jnp.arange(bs, dtype=dtype) + 0.5
    wh = inp["wh"][:, None, None]
    xw = inp["wx0"][:, None, None] + ar[None, None, :] * wh
    yw = inp["wy0"][:, None, None] + ar[None, :, None] * wh
    com = inp["com"]
    px = xw - com[0]
    py = yw - com[1]
    d = polygon_sdf_seg(px, py, inp["seg"])
    ud = midline_udef_packed(px, py, inp["mid"])
    return d, ud


class AMRSim(ShapeHostMixin):
    """Adaptive flow solver on the block forest, with or without
    immersed obstacles (the reference's only mode is 'with')."""

    def __init__(self, cfg: SimConfig, shapes: Optional[Sequence] = None,
                 bc=None):
        self.cfg = cfg
        # Per-face BC tables (bc.py, ISSUE 12) are a UNIFORM-FAMILY
        # contract: the forest's gather-table ghost exchange encodes
        # boundary rows as linear sign-flip expressions (flux.py), with
        # no slot for the inhomogeneous (moving-wall / inflow) or
        # state-dependent (convective outflow) ghosts a non-default
        # table needs — and the DCT-II spectral base solve assumes
        # all-Neumann walls. Refuse loudly instead of silently running
        # free-slip physics under a different label.
        if bc is not None and not bc.is_free_slip:
            raise ValueError(
                f"AMRSim does not support non-free-slip BCTables "
                f"({bc.token}): the forest gather-table ghost rows are "
                "linear sign-flips (free-slip/Neumann only). Run this "
                "case on the uniform family (UniformSim / Simulation / "
                "ShardedUniformSim / FleetSim).")
        self.case: Optional[str] = None  # case-registry tag (cases.py)
        # A/B env gates latched ONCE per sim, matching the
        # ShardedAMRSim._exchange pattern (ADVICE r5): a mid-run env
        # mutation must not silently flip the operator/preconditioner
        # form at the next retrace or regrid
        import os
        self._pois_mode = os.environ.get("CUP2D_POIS", "structured")
        self._twolevel_form = os.environ.get("CUP2D_TWOLEVEL")
        # a typo'd A/B gate must not silently fall back and measure
        # the same form on both arms. "fft" (PR 6): the forest-FFT
        # preconditioned production solve — structured operator +
        # ALWAYS-ON two-level coarse correction in the two-grid "mg2"
        # form (pre-smooth, spectral base-level correction, post-
        # smooth; see _pressure_project) instead of waiting for the
        # iters>15 trigger with the weaker additive form.
        # "fas"/"fas-f" (PR 13, formerly uniform-only): multigrid over
        # the forest's OWN refinement levels as the FULL production
        # solver (poisson.ForestFASCycle under mg_solve — block-Jacobi
        # composite smoothing, per-level window-image ladder, exact
        # DCT-II base solve; fas-f opens every solve base-level-first).
        # Exact/escalation solves keep Krylov as the robustness
        # backstop, exactly like the uniform path.
        # "fftd" (ISSUE 20) is a UNIFORM-FAMILY token: the FFT
        # diagonalization needs a periodic single-level box, and the
        # forest refuses every non-free-slip table above anyway —
        # name the token explicitly so a mixed-process env latch
        # fails with the reason, not a generic typo message.
        if self._pois_mode == "fftd":
            raise ValueError(
                "CUP2D_POIS=fftd is a uniform-family solve (FFT "
                "diagonalization over a periodic single-level box); "
                "AMRSim's forest has no periodic gather-table ghosts "
                "— run periodic cases on UniformSim/FleetSim")
        if self._pois_mode not in ("structured", "tables", "fft",
                                   "fas", "fas-f"):
            raise ValueError(
                f"CUP2D_POIS={self._pois_mode!r}: "
                "expected structured|tables|fft|fas|fas-f")
        if self._twolevel_form not in (None, "additive", "mult", "mg2"):
            raise ValueError(
                f"CUP2D_TWOLEVEL={self._twolevel_form!r}: "
                "expected additive|mult|mg2")
        # fused advection-kernel tier latch (PR 9; same construct-once
        # discipline). The forest's fusable unit is lab -> RHS (flux
        # corrections interleave before the Heun update), served by the
        # block-batched ops/pallas_kernels.fused_lab_rhs. f32 only —
        # Mosaic has no f64. The ADVECTION bf16 storage tier stays a
        # uniform/fleet contract (UniformGrid's CUP2D_PREC read); the
        # SOLVER-side read below is the forest's one sanctioned
        # CUP2D_PREC site and touches only the FAS cycle legs.
        self._kernel_tier = "xla"
        if os.environ.get("CUP2D_PALLAS", "") == "1":
            from .ops.pallas_kernels import lab_tier_supported
            if lab_tier_supported(cfg.dtype):
                self._kernel_tier = "pallas-fused"
        # Memory-tiered FAS (ISSUE 19): CUP2D_PREC=bf16 composes with
        # the fas latch as a bf16-storage/f32-accumulate tier on the
        # ForestFASCycle window-image ladder legs ONLY — mg_solve's
        # outer loop keeps the f32 true residual, the block composite
        # smoother and the DCT-II base solve stay at solver precision
        # (BASELINE: bf16 floors a FULL solver at ~2e-4 rel). Any
        # other composition refuses loudly: the forest has no bf16
        # advection tier to fall back to, so an un-routed latch would
        # silently run f32 under a bf16 label.
        prec = os.environ.get("CUP2D_PREC", "") or "f32"
        if prec not in ("f32", "bf16"):
            raise ValueError(
                f"CUP2D_PREC={prec!r}: expected f32|bf16")
        self._fas_leg_dtype = None
        if prec == "bf16":
            if self._pois_mode not in ("fas", "fas-f"):
                raise ValueError(
                    "CUP2D_PREC=bf16 on the forest selects the "
                    "bf16-leg FAS tier and requires CUP2D_POIS=fas|"
                    f"fas-f (got CUP2D_POIS={self._pois_mode!r}): "
                    "the forest has no bf16 advection tier, so the "
                    "latch would otherwise relabel an f32 run.")
            if jnp.dtype(cfg.dtype) != jnp.float32:
                raise ValueError(
                    "CUP2D_PREC=bf16 needs f32 solver state (got "
                    f"{jnp.dtype(cfg.dtype).name}): the bf16 legs "
                    "accumulate in f32; an f64 outer loop would cast "
                    "through f32 silently.")
            self._fas_leg_dtype = jnp.bfloat16
        if shapes is None:
            from .sim import make_shapes
            shapes = make_shapes(cfg)
        self.shapes = list(shapes)
        self.forest = Forest(cfg)
        self.forest.add_field("vel", 2)
        self.forest.add_field("pres", 1)
        if self.shapes:
            self.forest.add_field("chi", 1)
        self.time = 0.0
        self.step_count = 0
        self.p_inv = jnp.asarray(
            block_precond_matrix(cfg.bs), dtype=self.forest.dtype)
        # f64 Krylov-scalar accumulation for f32 fields (same rationale
        # as UniformGrid, uniform.py)
        self.sum_dtype = (
            jnp.float64
            if (self.forest.dtype == jnp.float32
                and jax.config.jax_enable_x64)
            else None
        )
        self._tables_version = -1
        self._tables = {}
        self._order = None
        # SFC-ordered compact working state ([n_pad, dim, BS, BS] per
        # field) — the device-resident truth between regrids. The
        # slot-layout fields dict is synced lazily (sync_fields); _ord_key
        # tracks (topology version, fields write-version) so external
        # slot writes invalidate the cache (forest._FieldsDict.wver)
        self._ord = None
        self._ord_key = None
        self._ord_dirty = False
        self._wcap = [16] * len(self.shapes)
        # sticky block-axis padding (see _refresh_impl)
        self._npad_hwm = 128
        self._npad_floor = 128    # reserve_blocks raises this
        self._npad_quiet = 0
        self.compute_forces_every = 1   # 0 disables the diagnostics pass
        self.force_log = None           # file-like, CSV rows
        self.timers = None              # profiling.PhaseTimers, opt-in
        # cumulative regrid activity + shard comm-volume stats for the
        # telemetry stream (profiling.MetricsRecorder reports per-step
        # deltas; _comm_stats is populated by ShardedAMRSim)
        self._n_refined = 0
        self._n_coarsened = 0
        self._comm_stats = None
        # jitted ONCE; tables/order/h are arguments, so regrids that
        # reproduce previously-seen shapes hit the XLA compile cache
        from . import tracing
        self._step_jit = tracing.named_jit(
            "amr.step", jax.jit(
                self._step_impl, static_argnames=("exact_poisson",)),
            variant=("exact_poisson",))
        self._mega_jit = tracing.named_jit(
            "amr.megastep", jax.jit(
                self._megastep_impl,
                static_argnames=("exact_poisson", "with_forces")),
            variant=("exact_poisson",))
        self._next_dt = None
        self._next_dt_version = -1
        self._dt_jit = None
        self._umax_jit = None
        self._next_umax = None   # survives regrids (see step_once)
        self._next_umax_version = -1
        # production two-level trigger (VERDICT r3 #9): when the last
        # production solve burned > 15 iterations (block-Jacobi's
        # block-count scaling law on near-uniform forests — ~200/step
        # at 1e4 blocks, r4 scale trace), engage the coarse correction
        # and keep it until the next topology change. _last_iters rides
        # host pulls that already happen (the megastep scalar pull /
        # the obstacle-free dt float) — no extra round trip.
        self._last_iters = 0
        self._last_iters_dev = None
        self._coarse_on = False
        # StepGuard's escalation rung forces the exact (tol-0 + coarse
        # correction) Poisson solve on a retried step (resilience.py)
        self._force_exact = False
        # lagged-verdict mode (resilience.StepGuard, lag=True): the
        # obstacle-free branch derives dt on DEVICE from the cached
        # end-state umax, keeps the diag (incl. the dt used) on device
        # and leaves clock settlement + the iters-trigger drain to the
        # guard's lagged pull — zero blocking host syncs per steady
        # step. The former side effect (the two-level iters>15 trigger
        # seeing the count one step LATE) is closed by the guard's
        # trigger-freshness window (resilience.StepGuard.step, PR 6):
        # while the trigger is re-armed-but-off the in-flight verdict
        # resolves BEFORE the next dispatch, so engagement lands at
        # the same step as the eager path. The shaped branch ignores
        # the flag (its uvw/CoM pull feeds the host kinematics).
        self.async_diag = False
        self._raster_jit = tracing.named_jit(
            "amr.rasterize", jax.jit(self._rasterize_impl))
        self._vorticity_jit = tracing.named_jit(
            "amr.vorticity", jax.jit(self._vorticity_impl))
        self._tags_jit = tracing.named_jit(
            "amr.tags", jax.jit(self._tags_impl))
        # fields are dead after _apply_regrid replaces them — donate so
        # XLA aliases the buffers instead of holding old + new field
        # sets live at once during the fused regrid dispatch
        self._regrid_jit = tracing.named_jit(
            "amr.regrid", jax.jit(
                self._regrid_apply_impl, donate_argnums=0))

    def reserve_blocks(self, n: int):
        """Pre-size the padded block axis so every jitted executable
        compiles once for a bucket that already fits ``n`` active blocks
        (call before initialize(); the init climb then never crosses a
        bucket). Padding above the reserve still grows automatically."""
        self._npad_floor = max(
            self._npad_floor, 1 << max(0, int(n)).bit_length())
        self._npad_hwm = max(self._npad_hwm, self._npad_floor)

    # ------------------------------------------------------------------
    # topology-dependent cached state
    # ------------------------------------------------------------------
    def _refresh(self):
        f = self.forest
        if self._tables_version == f.version:
            return
        with (self.timers or NULL_TIMERS).phase("tables"):
            self._refresh_impl()

    def _refresh_impl(self):
        f = self.forest
        self._order = f.order()
        n_real = len(self._order)
        # block axis padded to power-of-two buckets so a regrid that
        # changes n_active reuses the compiled step (SURVEY §7: padded
        # capacity + masking discipline; the r1 per-count retrace made
        # every regrid recompile a Krylov loop). Strictly > n_real so
        # pad-row lab/cell slots exist as dead scatter targets for the
        # shape-stable table padding (halo.pad_tables). Pad rows point
        # at an inactive slot: gathers see stale-but-finite data that
        # the mask zeroes, scatters write garbage only to that slot.
        #
        # The bucket is a sticky HIGH-WATER MARK, not the instantaneous
        # bucket: the levelMax init climb starts from the full uniform
        # levelStart grid and compresses the background away, so the
        # instantaneous bucket would cross several powers of two
        # downward — each crossing a full executable-set recompile
        # (~minutes through the remote-compile tunnel, BASELINE.md).
        # Keeping the peak bucket trades masked-out compute for compile
        # reuse; if the forest stays a quarter of the bucket for 10
        # consecutive rebuilds (a genuinely decayed run, not a
        # transient), the bucket steps down one power of two.
        n_bucket = max(128, 1 << n_real.bit_length())
        if n_bucket >= self._npad_hwm:
            self._npad_hwm = n_bucket
            self._npad_quiet = 0
        elif 4 * n_bucket <= self._npad_hwm \
                and self._npad_hwm > self._npad_floor:
            self._npad_quiet += 1
            if self._npad_quiet >= 10:
                self._npad_hwm //= 2
                self._npad_quiet = 0
        else:
            self._npad_quiet = 0
        n_pad = self._npad_hwm
        if not f._free:
            f._grow()
        pad_slot = f._free[-1]
        order_p = np.concatenate([
            self._order,
            np.full(n_pad - n_real, pad_slot, np.int32)])
        self._n_real = n_real
        self._mask = np.arange(n_pad) < n_real

        tm = self.timers or NULL_TIMERS
        # one dense topology index shared by all 6-8 table builds
        topo = _TopoIndex(f, self._order)
        with tm.phase("tables/build"):
            raw = {
                "vec3": build_tables(f, self._order, 3, True, 2,
                                     topo=topo),
                "vec1": build_tables(f, self._order, 1, False, 2,
                                     topo=topo),
                "sca1": build_tables(f, self._order, 1, False, 1,
                                     topo=topo),
                "vec1t": build_tables(f, self._order, 1, True, 2,
                                      topo=topo),
                "sca1t": build_tables(f, self._order, 1, True, 1,
                                      topo=topo),
            }
            if self.shapes:
                # chi tagging (g=4 scalar) + forces (g=4 vector)
                raw["sca4t"] = build_tables(f, self._order, 4, True, 1,
                                            topo=topo)
                raw["vec4t"] = build_tables(f, self._order, 4, True, 2,
                                            topo=topo)
        # one async transfer for every table leaf (pad_tables returns
        # numpy on purpose; per-leaf jnp.asarray would synchronize per
        # array — ~14 s/regrid through the TPU tunnel, measured)
        with tm.phase("tables/put"):
            fc = build_face_copy(f, self._order, n_pad, topo)
            self._tables = self._finalize_tables(raw, n_pad, fc)
            # makeFlux variable-resolution Poisson operator (flux.py):
            # structured per-face form on a single device; the sharded
            # subclass overrides with the lab-table + ppermute-exchange
            # form (_build_pois)
            self._tables["pois"] = self._build_pois(topo, n_pad)
        with tm.phase("tables/corr"):
            self._corr = self._finalize_corr(topo, n_pad)
        # two-level preconditioner maps: every cell's coarse cell on
        # the uniform level-c grid + its area weight (cells coarser
        # than c deposit into the coarse cell under their center —
        # approximate, but it is only a preconditioner). Built
        # vectorized and passed through the jit boundary as arguments.
        # Startup (steps < 10) always consumes them; production builds
        # them LAZILY on the iters>15 trigger (_use_coarse) — the
        # [cells, 4] arrays are ~50 MB at 1e4-block pads, dead regrid
        # latency for the compressed forests that never trigger.
        # Topology changed: the trigger re-arms from scratch — including
        # the iteration-count evidence, which described the OLD forest
        # (a stale 400-iteration count from a pre-compression topology
        # must not engage the correction on the new one)
        self._coarse_on = False
        self._last_iters = 0
        self._last_iters_dev = None
        if self.step_count >= 10:
            self._coarse_cw = None
        else:
            self._build_coarse_maps(n_pad, n_real)

        h = f.h_per_block(self._order)

        hp = np.concatenate([h, np.ones(n_pad - n_real)])
        hsqp = np.concatenate([h * h, np.zeros(n_pad - n_real)])
        # shape on the HOST (numpy reshapes), transfer once: the eager
        # [:, None] slicing of device arrays compiled a one-op
        # executable per distinct shape — 38 of the 62 warm-init
        # executables were such one-op jits (r5 init_compiles probe),
        # each paying the tunnel's per-executable transport
        fdt = np.dtype(jnp.dtype(f.dtype).name)
        self._h = jnp.asarray(hp.reshape(-1, 1, 1, 1).astype(fdt))
        self._h3 = jnp.asarray(hp.reshape(-1, 1, 1).astype(fdt))
        self._hflat = jnp.asarray(hp.astype(fdt))
        self._hsq_flat = jnp.asarray(hsqp.reshape(-1, 1, 1).astype(fdt))
        self._maskv = jnp.asarray(
            self._mask.reshape(-1, 1, 1, 1).astype(fdt))
        self._order_j = jnp.asarray(order_p)
        # cell centers per active block (device, for obstacle kernels)
        bs = f.bs
        ar = np.arange(bs) + 0.5
        x0 = f.bi[self._order].astype(np.float64) * bs * h
        y0 = f.bj[self._order].astype(np.float64) * bs * h
        xc = np.zeros((n_pad, bs, bs))
        yc = np.zeros((n_pad, bs, bs))
        xc[:n_real] = x0[:, None, None] + ar[None, None, :] * h[:, None, None]
        yc[:n_real] = y0[:, None, None] + ar[None, :, None] * h[:, None, None]
        self._xc = jnp.asarray(xc, f.dtype)
        self._yc = jnp.asarray(yc, f.dtype)
        self._tables_version = f.version
        # charge the async table/constant device_puts to "tables", not
        # to the first step that consumes them
        (self.timers or NULL_TIMERS).fence(
            "tables", self._tables, self._corr)

    def _build_coarse_maps(self, n_pad: int, n_real: int):
        """Host build of the two-level transfer structure (see
        _refresh_impl).

        Round-5 re-design: the round-3/4 form was a generic per-cell
        map ([cells, 4] bilinear indices + weights applied as one
        scatter-add deposit and one gather interpolation). On TPU that
        lowering is the adaptive path's single worst cost: the r5 op
        trace of the 1e4-block probe showed ~36 ms PER 4.2M-row
        scatter-add and ~30 ms per gather — ~630 ms of every 1163 ms
        step inside the Krylov loop. The replacement is structured:
        blocks are tile-aligned at their own level by construction, so
        each level's blocks paint a uniform level-l image via ONE
        block-row gather (embedding-style, 256 B rows), images walk to
        the coarse level by 2x2 mean / bilinear 2x ladder steps (pure
        reshape/slice arithmetic at full lane utilization), and the
        per-level tile extraction on the way back is again one
        block-row gather. No per-cell indices exist anywhere.

        The pytree is a dict keyed by active level, so the jit
        executable is keyed on the LEVEL SET (changes rarely, and only
        at regrids) instead of per-cell map contents.

        Levels FINER than the coarse level (l > c, the O(4^l) cells)
        are CROPPED to one shared active-tile bounding-box window
        (``levf`` + the dynamic ``crop`` origin): a deep refinement
        spot no longer paints a full-domain image at its own
        resolution per M application (the former ROADMAP cliff). The
        window is the union of the fine levels' tile bboxes in
        coarse-cell units, padded by 2 coarse cells (the bilinear
        up-ladder's influence radius is < 2, so every ACTIVE cell's
        dependence set stays inside the window and the cropped
        transfers are BIT-IDENTICAL to the full-domain form —
        tests/test_amr.py::test_two_level_crop_matches_full_domain)
        and snapped to an alignment grid that keeps every fine level's
        window tile-aligned. The window ORIGIN crosses the jit
        boundary as an int32 array (lax.dynamic_slice), so a regrid
        that moves the active spot without resizing the window reuses
        the compiled step. Levels <= c keep full-domain images — they
        are at most coarse-image-sized."""
        import math
        f = self.forest
        c = self._coarse_level = max(0, min(3, f.cfg.level_max - 1))
        bs_ = f.bs
        ncx = f.cfg.bpdx * bs_ << c
        ncy = f.cfg.bpdy * bs_ << c
        self._coarse_shape = (ncy, ncx)
        self._coarse_h2 = float(f.cfg.h_at(c)) ** 2
        fdt = jnp.dtype(f.dtype).name
        lvo = f.level[self._order].astype(np.int64)
        bio = f.bi[self._order].astype(np.int64)
        bjo = f.bj[self._order].astype(np.int64)
        active = sorted(int(v) for v in np.unique(lvo))
        # shared coarse-cell window over the fine levels' active tiles
        fine_act = [l for l in active if l > c]
        crop = None
        if fine_act:
            align = 1
            for l in fine_act:
                align = math.lcm(
                    align, bs_ // math.gcd(bs_, 1 << (l - c)))
            cj0 = ci0 = 1 << 30
            cj1 = ci1 = -1
            for l in fine_act:
                sel = lvo == l
                den = 1 << (l - c)       # level-l cells per coarse cell
                cj0 = min(cj0, int(bjo[sel].min()) * bs_ // den)
                ci0 = min(ci0, int(bio[sel].min()) * bs_ // den)
                cj1 = max(cj1, -(-(int(bjo[sel].max()) + 1) * bs_ // den))
                ci1 = max(ci1, -(-(int(bio[sel].max()) + 1) * bs_ // den))
            # 2-coarse-cell margin: the bilinear chain's dependence
            # reach (see docstring); snap outward to the alignment grid
            # (domain dims are multiples of it, so clamping is safe)
            cj0 = max(0, cj0 - 2) // align * align
            ci0 = max(0, ci0 - 2) // align * align
            cj1 = -(-min(ncy, cj1 + 2) // align) * align
            ci1 = -(-min(ncx, ci1 + 2) // align) * align
            crop = (cj0, cj1, ci0, ci1)
        per_level = {}
        fine = {}
        for l in active:
            ntx = f.cfg.bpdx << l
            nty = f.cfg.bpdy << l
            sel = lvo == l
            if not np.any(sel):
                # empty ladder level: never emit an entry — the
                # _deposit/_interp chains in _pressure_project bound
                # their image ladders by min/max of THESE dicts, so an
                # empty level above the finest active one would force
                # needless ladder steps (ADVICE r5). np.unique of the
                # active levels cannot produce one today; this guard
                # keeps the invariant explicit for future callers.
                continue
            if l <= c:
                tix = bjo[sel] * ntx + bio[sel]
                # tiles owned by no level-l block gather the first pad
                # row (index n_real points into the pad range:
                # n_pad > n_real) and are zeroed by ownm — pad-row
                # data is stale, not NaN
                own = np.full(nty * ntx, n_real, np.int32)
                own[tix] = np.nonzero(sel)[0].astype(np.int32)
                ownm = np.zeros(nty * ntx, fdt)
                ownm[tix] = 1.0
                tid = np.zeros(n_pad, np.int32)
                tid[:n_real][sel] = tix.astype(np.int32)
                selp = np.zeros(n_pad, fdt)
                selp[:n_real][sel] = 1.0
                per_level[l] = (own.reshape(nty, ntx),
                                ownm.reshape(nty, ntx), tid, selp)
            else:
                cj0, cj1, ci0, ci1 = crop
                sc = 1 << (l - c)
                tj0 = cj0 * sc // bs_
                ti0 = ci0 * sc // bs_
                ntyw = (cj1 - cj0) * sc // bs_
                ntxw = (ci1 - ci0) * sc // bs_
                tjr = bjo[sel] - tj0
                tir = bio[sel] - ti0
                tix = tjr * ntxw + tir
                own = np.full(ntyw * ntxw, n_real, np.int32)
                own[tix] = np.nonzero(sel)[0].astype(np.int32)
                ownm = np.zeros(ntyw * ntxw, fdt)
                ownm[tix] = 1.0
                tid = np.zeros(n_pad, np.int32)
                tid[:n_real][sel] = tix.astype(np.int32)
                selp = np.zeros(n_pad, fdt)
                selp[:n_real][sel] = 1.0
                fine[l] = (own.reshape(ntyw, ntxw),
                           ownm.reshape(ntyw, ntxw), tid, selp)
        from .poisson import dct_neumann_operators
        cw = {
            "lev": per_level,
            "dct": dct_neumann_operators(ncy, ncx, dtype=fdt),
        }
        if fine:
            cw["levf"] = fine
            # window ORIGIN (coarse cells) — dynamic, so same-shape
            # windows at different spots share one executable
            cw["crop"] = np.asarray([crop[0], crop[2]], np.int32)
        self._coarse_cw = jax.device_put(cw)


    # the hot-loop table sets that take the same-level face-copy fast
    # path (halo.make_fast_tables); vec1t/sca1t are regrid-only and
    # stay plain. Non-tensorial g=1 sets never fill lab corners, so
    # their paint is face-only.
    _FAST_SETS = {"vec3": True, "vec1": False, "sca1": False,
                  "sca4t": True, "vec4t": True}

    # table placement hooks (ShardedAMRSim splits the hot-loop sets
    # into per-device rows + a surface-exchange plan)
    def _finalize_tables(self, raw: dict, n_pad: int, fc=None) -> dict:
        out = {}
        for k, t in raw.items():
            if fc is not None and k in self._FAST_SETS:
                out[k] = make_fast_tables(t, fc[0], fc[1], n_pad,
                                          corners=self._FAST_SETS[k])
            else:
                out[k] = pad_tables(t, n_pad)
        return jax.device_put(out)

    def _build_pois(self, topo, n_pad: int):
        """Poisson operator build hook: the structured per-face form
        (build_poisson_structured) on a single device — its 2 block-row
        gathers per face replace the lab scatter whose TPU lowering
        serialized inside the Krylov loop (r5 trace). The sharded
        subclass overrides with per-device rows behind the ppermute
        surface-exchange plan. CUP2D_POIS=tables (latched in __init__)
        forces the table form for A/B measurements."""
        if self._pois_mode == "tables":
            t = build_poisson_tables(self.forest, self._order, topo=topo)
            return jax.device_put(pad_tables(t, n_pad))
        return jax.device_put(build_poisson_structured(
            self.forest, self._order, n_pad, topo=topo))

    def _finalize_corr(self, topo, n_pad: int):
        return build_flux_corr(self.forest, self._order, n_pad=n_pad,
                               topo=topo)

    # ------------------------------------------------------------------
    # ordered working state
    # ------------------------------------------------------------------
    # The reference's hot loop reads/writes blocks through per-rank
    # `infos` vectors kept in SFC order (main.cpp:1550-1562); the slot
    # map is bookkeeping. Same inversion here: between regrids the
    # device state IS the ordered compact array set, so no step pays a
    # slot<->ordered permutation (under a device mesh that permutation
    # is a volume-sized collective; the ordered arrays are sharded in
    # contiguous SFC ranges exactly like the reference's rank ranges).
    def _ordered_state(self) -> dict:
        f = self.forest
        self._refresh()
        key = (f.version, f.fields.wver)
        if self._ord_key == key and self._ord is not None:
            # (_ord None with a matching key = checkpoint restore just
            # re-anchored the wver trail; fall through and rebuild)
            return self._ord
        if self._ord_dirty:
            # a hard error (not an assert: must survive python -O) —
            # rebuilding from the stale slot arrays here would silently
            # discard the last completed step's fields
            raise RuntimeError(
                "slot fields were written while the ordered working "
                "state held newer data; call sync_fields() before "
                "writing forest.fields")
        if self._ord_key is not None and self._ord_key[0] == f.version \
                and self._ord_key != key:
            # same topology but the fields dict was rewritten
            # externally (wver moved): the cached end-state umax/dt
            # describe the overwritten field — drop them (a regrid, by
            # contrast, keeps them for the 1.05-guarded branch). The
            # key-inequality guard matters: a checkpoint restore lands
            # here with _ord=None and an UNmoved key, and must keep its
            # restored dt cache (the restart takes the same dt branch
            # as the uninterrupted run).
            self._next_dt = None
            self._next_umax = None
        self._ord = {name: self._put_ordered(fld[self._order_j])
                     for name, fld in f.fields.items()}
        self._ord_key = key
        return self._ord

    def _put_ordered(self, x):
        """Placement hook: ShardedAMRSim pins the ordered block axis to
        the device mesh here."""
        return x

    def sync_fields(self):
        """Write the ordered working state back into the slot-layout
        fields dict (regrid prolongation, dumps, checkpoints and tests
        read slots). No-op when already in sync."""
        if not self._ord_dirty:
            return
        f = self.forest
        order = jnp.asarray(self._order)
        for name, x in self._ord.items():
            f.fields[name] = f.fields[name].at[order].set(
                x[:self._n_real])
        self._ord_key = (f.version, f.fields.wver)
        self._ord_dirty = False

    def fields(self) -> dict:
        """Slot-layout fields, guaranteed current.

        The supported read path for external/analysis consumers: syncs
        the ordered working state back into ``forest.fields`` first, so
        a reader can never observe pre-step data (reading
        ``forest.fields`` directly between steps silently returns the
        state as of the last sync — ADVICE r3)."""
        self.sync_fields()
        return self.forest.fields

    def _set_ordered(self, **updates):
        """Adopt step outputs as the new ordered truth."""
        self._ord = {**self._ord, **updates}
        self._ord_dirty = True

    @staticmethod
    def _pull_blockwise(x) -> np.ndarray:
        """Pull a block-axis-sharded device array to host numpy.

        Multi-host pods can't np.asarray a sharded global array (shards
        live on other processes) — every process must reach the SAME
        host-side regrid decision from the SAME full tag vector (the
        reference's update_boundary contract, main.cpp:1850-1970), so
        the pull becomes an all-gather across processes there. Scalar
        diagnostics stay plain device_get (reduction outputs are fully
        replicated)."""
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            return np.asarray(
                multihost_utils.process_allgather(x, tiled=True))
        return np.asarray(x)

    # ------------------------------------------------------------------
    # shared device stages
    # ------------------------------------------------------------------
    def _advect_rk2(self, vel, h, dt, t3, corr, maskv):
        """Heun RK2 advection-diffusion (per-block h); diffusive face
        fluxes flux-corrected at level interfaces (fillcases after each
        stage, main.cpp:6607-6642). ``vel`` and the result are ordered
        compact [N,2,BS,BS]. ``maskv`` zeroes the padded rows each stage
        (pad-row data is stale, never NaN — see _refresh)."""
        cfg = self.cfg
        ih2 = 1.0 / (h * h)
        vold = vel * maskv               # [N,2,BS,BS]
        v = vold
        for c in (0.5, 1.0):
            lab = assemble_labs_ordered(v if c == 1.0 else vel, t3)
            if self._kernel_tier != "xla":
                # forest-block-batched fused RHS: one HBM read of the
                # lab batch per stage, per-block h rides the kernel's
                # (afac, dfac) scale rows
                from .ops.pallas_kernels import fused_lab_rhs
                rhs = fused_lab_rhs(lab, h, cfg.nu, dt)
            else:
                rhs = advect_diffuse_rhs(lab, 3, h, cfg.nu, dt)
            rhs = apply_flux_corr(
                rhs, diffusive_deposits(lab, 3, cfg.nu * dt), corr)
            v = heun_substage(vold, c, rhs, ih2) * maskv
        return v

    def _pressure_project(self, v, pres, dt, h, hsq,
                          t1v, t1s, tpois, corr, tcoarse,
                          exact_poisson, maskv,
                          chi=None, udef_b=None):
        """deltap Poisson solve + projection (main.cpp:7007-7187). The
        RHS divergence is flux-corrected; the operator (also applied to
        the initial guess p_old) is the makeFlux variable-resolution
        closure — conservative on both sides of every interface.
        ``chi``/``udef_b`` add the -chi div(u_def) obstacle term.
        All operands ordered compact; returns
        (v_new, p_new, res, div_linf)."""
        cfg = self.cfg
        ih2 = 1.0 / (h * h)
        pord = pres[:, 0] * maskv[:, 0]          # [N,BS,BS]
        vlab = assemble_labs_ordered(v, t1v)
        fac = 0.5 * h[:, 0] / dt
        b = fac * divergence(vlab, 1)
        ulab = None
        if udef_b is not None:
            ulab = assemble_labs_ordered(udef_b, t1v)
            b = b - fac * chi * divergence(ulab, 1)
        b = apply_flux_corr(
            b, divergence_deposits(vlab, ulab, chi, fac[:, 0, 0]), corr)
        # physics invariant for the telemetry watchdog: max |∇·u| of
        # the pre-projection velocity, read off the (flux-corrected)
        # Poisson RHS the step already forms — |b| = fac * |undivided
        # div| with fac = h/2dt, physical div = undivided/(2h), so the
        # rescale is dt/h^2 per block. Zero extra lab assemblies (an
        # honest post-projection divergence would cost one more halo
        # exchange per step under the sharded mesh). Pad rows carry
        # stale-but-finite lab data — masked.
        div_linf = jnp.max(
            jnp.abs(b) * maskv[:, 0] * (dt / (h[:, 0] * h[:, 0])))

        if hasattr(tpois, "nba"):
            # structured per-face operator (flux.poisson_apply_structured)
            def A(x):
                return poisson_apply_structured(x, tpois)
        else:
            def A(x):
                lab = assemble_labs_ordered(x[:, None], tpois)
                return laplacian5(lab, 1)[:, 0]

        # initial-guess subtraction via A itself (the reference uses the
        # lab Laplacian + flux correction, pressure_rhs1; using A keeps
        # A(dp + p_old) = div-rhs exactly)
        b = b - A(pord)

        def M(r):
            return apply_block_precond_blocks(r, self.p_inv)

        if tcoarse is not None:
            # two-level preconditioner (VERDICT r2 #6): block-Jacobi
            # leaves the global pressure modes to the Krylov iteration
            # (hundreds of iterations on a cold RHS); a coarse
            # uniform-grid correction (exact Neumann solve) deflates
            # them multiplicatively. Used for the cold startup solves
            # and, since round 4, for PRODUCTION solves behind the
            # driver's iters>15 trigger (step_once). Round-5 re-design
            # of the transfers: per-level images painted by block-row
            # gathers + 2x mean/bilinear ladder steps, and a DCT-matmul
            # coarse solve — the r4 per-cell scatter/gather maps and
            # the FFT's operand staging were ~630 of 1163 ms/step at
            # 1e4 blocks (r5 trace; see _build_coarse_maps).
            dctops = tcoarse["dct"]
            ncy, ncx = self._coarse_shape
            cih2 = jnp.where(hsq > 0,
                             1.0 / jnp.where(hsq > 0, hsq, 1.0), 0.0)
            _deposit, _interp = self._coarse_transfers(tcoarse)

            # form selection: PRODUCTION solves use the ADDITIVE
            # two-level (coarse correction + block-Jacobi on the same
            # residual — no embedded A-apply). The r4 A/B called it a
            # wash (7/896 vs 8/947 ms) because transfers dominated;
            # with the r5 structured operator the saved 2 A-applies
            # per iteration are real: 155.8 -> 128.6 ms/step at 1e4
            # blocks, iterations unchanged at 8 (BASELINE.md r5).
            # STARTUP (exact) solves keep the multiplicative form —
            # their 2-26-iteration convergence pedigree (r4) was
            # established with it, and 10 solves/run don't pay the
            # hot-loop price. CUP2D_TWOLEVEL={additive,mult} (latched
            # in __init__, validated there) forces one form for A/B
            # probes.
            # "mg2" (PR 6, the CUP2D_POIS=fft production form): a full
            # two-grid cycle — block-Jacobi PRE-smooth, spectral
            # base-level correction of the smoothed residual,
            # block-Jacobi POST-smooth — i.e. the multiplicative
            # composition symmetrized. Costs 2 A-applies + 3 GEMM
            # smooths per application where additive pays 0 + 1, but
            # contracts both the local high-frequency error AND the
            # coarse modes each application, which is what cuts the
            # Krylov train itself (additive 10/9/8 -> mg2 4/4/4
            # iters/step at the 1e4-block probe, BASELINE.md round 6)
            # instead of shaving per-iter cost.
            form = self._twolevel_form or (
                "mult" if exact_poisson else
                ("mg2" if self._pois_mode == "fft" else "additive"))
            if form == "additive":
                def M(r):
                    rc = _deposit(r * cih2)
                    ec = coarse_neumann_solve_dct(
                        rc, dctops, self._coarse_h2)
                    return _interp(ec, r) + apply_block_precond_blocks(
                        r, self.p_inv)
            elif form == "mg2":
                def M(r):
                    e = apply_block_precond_blocks(r, self.p_inv)
                    r1 = r - A(e)
                    rc = _deposit(r1 * cih2)
                    ec = coarse_neumann_solve_dct(
                        rc, dctops, self._coarse_h2)
                    e = e + _interp(ec, r)
                    return e + apply_block_precond_blocks(
                        r - A(e), self.p_inv)
            else:
                def M(r):
                    rc = _deposit(r * cih2)
                    ec = coarse_neumann_solve_dct(
                        rc, dctops, self._coarse_h2)
                    e = _interp(ec, r)
                    return e + apply_block_precond_blocks(
                        r - A(e), self.p_inv)

        if self._pois_mode in ("fas", "fas-f") and not exact_poisson:
            # forest-native FAS production solve (PR 13): multigrid
            # over the forest's OWN refinement levels as the FULL
            # solver — mg_solve's true-residual cycle loop (the same
            # result/stall contract every driver already reads) around
            # one ForestFASCycle per cycle. _use_coarse guarantees
            # tcoarse for these modes; exact/escalation solves fall
            # through to the Krylov backstop below, mirroring the
            # uniform path (UniformGrid.pressure_solve).
            paint_fine, base_solve, extract_all = \
                self._fas_transfers(tcoarse)
            mgc = ForestFASCycle(
                A, self._fas_block_smoother(A, tpois),
                paint_fine, base_solve, extract_all, cih2,
                leg_dtype=self._fas_leg_dtype)
            res = mg_solve(
                A, b, mgc,
                tol=cfg.poisson_tol, tol_rel=cfg.poisson_tol_rel,
                max_cycles=cfg.max_poisson_iterations,
                fmg=self._pois_mode == "fas-f",
            )
        else:
            # the cold startup solves start from x0 = M(b): one
            # two-level application removes the global pressure modes
            # from r0 before the Krylov iteration begins — the
            # zero-pressure first solve was the 71-iteration outlier of
            # the round-3 probe precisely because those modes dominated
            # its RHS (VERDICT r3 #9)
            x0 = None
            if exact_poisson and tcoarse is not None:
                x0 = M(b)
            # exact mode converges THREE ORDERS past the case's own
            # production target (max(1e-3*tol, 1e-3*tol_rel*|r0|)) —
            # deep enough that the startup pressure transient is
            # converged for any consumer of the production tolerances,
            # and anchored to the case instead of the r2 builds'
            # grid-dependent empirical f32 floors (VERDICT r2 #8). The
            # stall detector remains the backstop when that target sits
            # below the precision floor. Chasing the literal-0 floor
            # instead spent up to 71 iterations grinding to 1e-8 on the
            # first canonical solve (r3 probe) for depth nothing reads;
            # this exits at <= 40 (measured).
            res = bicgstab(
                A, b, M=M, x0=x0,
                tol=1e-3 * cfg.poisson_tol if exact_poisson
                else cfg.poisson_tol,
                tol_rel=1e-3 * cfg.poisson_tol_rel if exact_poisson
                else cfg.poisson_tol_rel,
                max_iter=cfg.max_poisson_iterations,
                max_restarts=100 if exact_poisson
                else cfg.max_poisson_restarts,
                sum_dtype=self.sum_dtype,
                refresh_every=10 if exact_poisson else 50,
                stall_iters=15 if exact_poisson else 120,
                stall_rtol=0.99 if exact_poisson else 0.999,
            )

        # volume-weighted mean removal (main.cpp:7120-7173)
        wsum = jnp.sum(hsq) * cfg.bs ** 2
        dp = res.x - jnp.sum(res.x * hsq) / wsum
        p_new = dp + pord - jnp.sum(pord * hsq) / wsum

        # projection (shared kernel, per-block h broadcast), gradient
        # fluxes corrected (pressureCorrectionKernel + fillcases,
        # main.cpp:7174-7187)
        plab = assemble_labs_ordered(p_new[:, None], t1s)
        dv = pressure_gradient_update(plab[:, 0], 1, h, dt)
        pfac = -0.5 * dt * h[:, 0, 0, 0]
        dv = apply_flux_corr(
            dv, gradient_deposits(plab[:, 0], pfac), corr)
        v = (v + dv * ih2) * maskv
        return v, p_new[:, None], res, div_linf

    def _coarse_transfers(self, tcoarse):
        """The two-level transfer pair (deposit: ordered blocks ->
        coarse image; interp: coarse image -> ordered blocks) for one
        ``_build_coarse_maps`` pytree. Factored out of
        _pressure_project so the cropped-vs-full-domain equivalence is
        directly testable (tests/test_amr.py).

        Ladder bounds: ``lev``/``levf`` hold ONLY levels with active
        blocks (_build_coarse_maps filters empty ones), so the image
        chains stop at the finest/coarsest ACTIVE level (ADVICE r5).
        Levels FINER than c live in ``levf`` and are CROPPED to the
        shared active-tile window — the former full-domain O(4^level)
        cliff is closed: a fine level pays window-sized images, not
        domain-sized ones, and the 2-coarse-cell margin keeps the
        cropped bilinear chain bit-identical to the full-domain form
        on every active cell (see _build_coarse_maps)."""
        lev = tcoarse["lev"]
        levf = tcoarse.get("levf", {})
        crop = tcoarse.get("crop")
        ncy, ncx = self._coarse_shape
        c = self._coarse_level
        bs = self.cfg.bs
        if levf:
            l0 = min(levf)
            sc0 = 1 << (l0 - c)
            hw, ww = levf[l0][0].shape
            wHc = hw * bs // sc0        # window size, coarse cells
            wWc = ww * bs // sc0
            oy, ox = crop[0], crop[1]   # dynamic origin

        def _deposit(rp):
            rc = jnp.zeros((ncy, ncx), rp.dtype)
            for l in sorted(lev):               # levels <= c
                img = _tiles_img(lev[l], rp, bs)
                # coarser than c: spread the cell's unit deposit
                # uniformly over its coarse footprint
                for _ in range(c - l):
                    img = jnp.repeat(
                        jnp.repeat(img, 2, 0), 2, 1) * 0.25
                rc = rc + img
            for l in sorted(levf):              # levels > c, cropped
                img = _tiles_img(levf[l], rp, bs)
                # mean ladder: each fine cell deposits its area
                # fraction 4^(c-l) (the r4 wq weight)
                for _ in range(l - c):
                    img = _down2_mean(img)
                cur = jax.lax.dynamic_slice(rc, (oy, ox), (wHc, wWc))
                rc = jax.lax.dynamic_update_slice(
                    rc, cur + img, (oy, ox))
            return rc

        def _extract(a, entry, e):
            return _extract_tiles(a, entry, e, bs)

        def _interp(ec, like):
            # images are kept ONLY for levels with active blocks; gap
            # levels still pay their ladder step (the 2x chain is how
            # level l+1 is built from l) but are never stored or
            # extracted
            e = jnp.zeros_like(like)
            if c in lev:
                e = _extract(ec, lev[c], e)
            a = ec
            for l in range(c - 1, (min(lev) if lev else c) - 1, -1):
                a = _down2_mean(a)
                if l in lev:
                    e = _extract(a, lev[l], e)
            if levf:
                a = jax.lax.dynamic_slice(ec, (oy, ox), (wHc, wWc))
                for l in range(c + 1, max(levf) + 1):
                    a = _up2_bilinear(a)
                    if l in levf:
                        e = _extract(a, levf[l], e)
            return e

        return _deposit, _interp

    def _fas_transfers(self, tcoarse):
        """Transfer closures of the forest FAS hierarchy
        (poisson.ForestFASCycle), built from the SAME
        ``_build_coarse_maps`` pytree as the two-level preconditioner —
        per-level block-row paints, 2x ladder steps, the cropped
        active-tile window for levels above c. Returns
        (paint_fine, base_solve, extract_all):

        * ``paint_fine(rdiv)``: the DIVIDED residual painted as one
          UNDIVIDED window image per ladder level above c (finest
          first, gap levels zero) — R_l = rdiv * h_l^2, each block
          depositing at its OWN level (the composite-forest analog of
          per-level FAS restriction, arXiv:2510.11152);
        * ``base_solve(rdiv, racc)``: the full-domain level-c RHS (the
          <= c block deposits of rdiv plus the restricted fine-level
          residual ``racc``, undivided -> divided at the window),
          solved exactly by the DCT-II spectral Neumann solve; returns
          (ec, window slice of ec);
        * ``extract_all(ec, es)``: per-level tile extraction of the
          corrected error back onto the ordered blocks — levels <= c
          down-laddered from ec, fine levels from their own corrected
          window images ``es``."""
        lev = tcoarse["lev"]
        levf = tcoarse.get("levf", {})
        crop = tcoarse.get("crop")
        ncy, ncx = self._coarse_shape
        c = self._coarse_level
        bs = self.cfg.bs
        ch2 = self._coarse_h2
        dctops = tcoarse["dct"]
        lf = max(levf) if levf else c
        if levf:
            l0 = min(levf)
            sc0 = 1 << (l0 - c)
            hw, ww = levf[l0][0].shape
            wHc = hw * bs // sc0        # window size, coarse cells
            wWc = ww * bs // sc0
            oy, ox = crop[0], crop[1]   # dynamic origin

        def paint_fine(rdiv):
            imgs = []
            for l in range(lf, c, -1):  # finest ladder level first
                if l in levf:
                    img = _tiles_img(levf[l], rdiv, bs) \
                        * (ch2 / 4 ** (l - c))
                else:
                    sc = 1 << (l - c)
                    img = jnp.zeros((wHc * sc, wWc * sc), rdiv.dtype)
                imgs.append(img)
            return imgs

        def base_solve(rdiv, racc):
            rc = jnp.zeros((ncy, ncx), rdiv.dtype)
            for l in sorted(lev):       # levels <= c, full domain
                img = _tiles_img(lev[l], rdiv, bs)
                # rdiv is POINTWISE (the divided residual ~ lap e), so
                # a cell coarser than c REPLICATES its value over the
                # footprint — unlike the preconditioner's 0.25-spread
                # (_coarse_transfers), which conserves the integral and
                # underweights sub-base levels by 4^(c-l); Krylov
                # absorbs that miscalibration, a plain cycle cannot
                for _ in range(c - l):
                    img = jnp.repeat(jnp.repeat(img, 2, 0), 2, 1)
                rc = rc + img
            awin = None
            if racc is not None:
                cur = jax.lax.dynamic_slice(rc, (oy, ox), (wHc, wWc))
                rc = jax.lax.dynamic_update_slice(
                    rc, cur + racc / ch2, (oy, ox))
            ec = coarse_neumann_solve_dct(rc, dctops, ch2)
            if levf:
                awin = jax.lax.dynamic_slice(ec, (oy, ox), (wHc, wWc))
            return ec, awin

        def extract_all(ec, es):
            e = None
            for i, l in enumerate(range(lf, c, -1)):
                if l in levf:
                    base = jnp.zeros(
                        (self._npad_hwm, bs, bs), ec.dtype) \
                        if e is None else e
                    e = _extract_tiles(es[i], levf[l], base, bs)
            if e is None:
                e = jnp.zeros((self._npad_hwm, bs, bs), ec.dtype)
            if c in lev:
                e = _extract_tiles(ec, lev[c], e, bs)
            a = ec
            for l in range(c - 1, (min(lev) if lev else c) - 1, -1):
                a = _down2_mean(a)
                if l in lev:
                    e = _extract_tiles(a, lev[l], e, bs)
            return e

        return paint_fine, base_solve, extract_all

    def _fas_block_smoother(self, A, tpois):
        """Composite-level smoother of the forest FAS cycle: damped
        block-Jacobi sweeps e += P_inv (r - A e) with the exact
        single-block inverse (the same GEMM as the Krylov
        preconditioner). The sharded subclass overrides with the
        comm/compute-overlapped block-surface form
        (shard_halo.overlap_block_jacobi_sweeps)."""
        p_inv = self.p_inv
        # strip tier (ISSUE 19): each sweep's residual-precondition-
        # update tail (r - lap, the P_inv GEMM and the add) fuses into
        # one Pallas pass over the block batch; the A-apply stays XLA
        # (it IS the forest operator — gather tables + flux rows). The
        # from_zero head is a bare GEMM (lap = 0) and stays XLA too.
        use_fused = False
        if self._kernel_tier != "xla":
            from .ops import pallas_kernels as pk
            use_fused = pk.block_update_supported(self.forest.dtype)

        def smooth(e, r, n, from_zero=False):
            if from_zero and n > 0:
                e = apply_block_precond_blocks(r, p_inv)
                n -= 1
            if use_fused:
                from .ops.pallas_kernels import fused_block_jacobi_update
                for _ in range(n):
                    e = fused_block_jacobi_update(e, r, A(e), p_inv)
                return e
            for _ in range(n):
                e = e + apply_block_precond_blocks(r - A(e), p_inv)
            return e

        return smooth

    @staticmethod
    def _precond_cycles_static(res, tcoarse, exact_poisson):
        if tcoarse is None:
            return jnp.zeros_like(res.iters)
        return 2 * res.iters + (1 if exact_poisson else 0)

    def _precond_cycles(self, res, tcoarse, exact_poisson):
        """Coarse-correction cycle count of one solve (telemetry schema
        v4): flexible BiCGSTAB applies M twice per iteration, plus the
        one x0 = M(b) application of exact-mode cold starts; solves
        without the two-level operand report 0. Forest-FAS production
        solves (CUP2D_POIS=fas|fas-f) run mg_solve, whose iterations
        ARE cycles — same convention as the uniform FAS path
        (UniformGrid.precond_cycles). ``tcoarse is None`` is a
        trace-time (pytree-structure) branch, so this costs nothing
        on device."""
        if self._pois_mode in ("fas", "fas-f") and not exact_poisson:
            return res.iters
        return self._precond_cycles_static(res, tcoarse, exact_poisson)

    @property
    def poisson_mode(self) -> str:
        """Active production solve-path latch (telemetry schema v4 —
        the value vocabulary grew in PR 13, the KEY set did not): the
        CUP2D_POIS mode plus the two-level trigger state, so an A/B
        run's metrics.jsonl alone says which path each step took.
        Forest values: bicgstab+jacobi | bicgstab+twolevel |
        bicgstab+fft | fas+forest | fas-f+forest (the "+forest" suffix
        keeps the forest FAS hierarchy distinguishable from the
        uniform path's plain "fas"/"fas-f" in merged fleet streams)."""
        if self._pois_mode == "fft":
            return "bicgstab+fft"
        if self._pois_mode in ("fas", "fas-f"):
            return self._pois_mode + "+forest"
        return ("bicgstab+twolevel" if self._coarse_on
                else "bicgstab+jacobi")

    @property
    def kernel_tier(self) -> str:
        """Active advection-kernel tier (telemetry schema v6)."""
        return self._kernel_tier

    @property
    def prec_mode(self) -> str:
        """Hot-loop storage precision (telemetry schema v6). The forest
        has no bf16 ADVECTION storage tier (that CUP2D_PREC reading is
        a uniform/fleet contract), so this is always the field dtype;
        the solver-side bf16-leg tier is carried by smoother_tier."""
        return {"float32": "f32", "float64": "f64"}.get(
            self.forest.dtype.name, self.forest.dtype.name)

    @property
    def smoother_tier(self) -> str:
        """Smoother tier of the FAS pressure hierarchy (telemetry
        schema v11): "xla" | "strip" (fused block-Jacobi update pass) |
        "+bf16" suffix when the window-image ladder legs store bf16.
        Non-FAS poisson modes have no cycle legs and report "xla"."""
        base = "xla"
        if (self._pois_mode in ("fas", "fas-f")
                and self._kernel_tier != "xla"):
            from .ops.pallas_kernels import block_update_supported
            if block_update_supported(self.forest.dtype):
                base = "strip"
        if self._fas_leg_dtype is not None:
            return base + "+bf16"
        return base

    @property
    def bc_table(self) -> str:
        """Per-face BC token string (telemetry schema v8). The forest
        tier is free-slip-only by construction (see __init__'s
        refusal), so this is the constant default token."""
        from .bc import FREE_SLIP
        return FREE_SLIP.token

    def _energy(self, v, hsq):
        """Kinetic energy of the masked ordered velocity — the
        telemetry watchdog's first invariant, one fused reduction
        riding the step's existing diag (pad rows carry hsq = 0).
        Accumulated in sum_dtype like the Krylov dots."""
        vv = v.astype(self.sum_dtype) if self.sum_dtype is not None else v
        return 0.5 * jnp.sum(vv * vv * hsq[:, None].astype(vv.dtype))

    @staticmethod
    def _finite_flag(v, p_new, maskv):
        """Fused isfinite reduction over velocity + pressure — the
        health verdict's NaN/Inf detector (resilience.health_verdict),
        riding the step's existing diag pull. ``v`` is already masked
        (pad rows zeroed by the step); the pressure's pad rows hold
        stale-but-finite garbage by the padding invariant, masked here
        through a where (a multiply would turn a hypothetical pad Inf
        into NaN and false-positive)."""
        return jnp.all(jnp.isfinite(v)) & jnp.all(
            jnp.isfinite(jnp.where(maskv > 0, p_new, 0.0)))

    # ------------------------------------------------------------------
    # device step: obstacle-free (the oracle path)
    # ------------------------------------------------------------------
    def _step_impl(self, vel, pres, dt, h, hsq, maskv,
                   t3, t1v, t1s, tpois, corr, tcoarse,
                   exact_poisson=False):
        v = self._advect_rk2(vel, h, dt, t3, corr, maskv)
        v, p_new, res, div_linf = self._pressure_project(
            v, pres, dt, h, hsq, t1v, t1s, tpois, corr, tcoarse,
            exact_poisson, maskv)
        diag = {
            "poisson_iters": res.iters,
            "poisson_residual": res.residual,
            "poisson_stalled": res.stalled,
            "poisson_converged": res.converged,
            "finite": self._finite_flag(v, p_new, maskv),
            "umax": jnp.max(jnp.abs(v)),
            "energy": self._energy(v, hsq),
            "div_linf": div_linf,
            "precond_cycles": self._precond_cycles(
                res, tcoarse, exact_poisson),
        }
        return v, p_new, diag

    # ------------------------------------------------------------------
    # device step: with obstacles (the reference hot loop 6607-7187)
    # ------------------------------------------------------------------
    def _flow_impl(self, vel, pres, obs, prescribed, dt, h, hsq,
                   maskv, xc, yc, t3, t1v, t1s, tpois, corr, tcoarse,
                   exact_poisson=False):
        cfg = self.cfg
        S = len(self.shapes)
        v = self._advect_rk2(vel, h, dt, t3, corr, maskv)
        v_cf = v.transpose(1, 0, 2, 3)   # component-first [2,N,BS,BS]

        # rigid momentum solve per shape (main.cpp:6643-6704)
        uvw = []
        for k in range(S):
            if self.shapes[k].free:
                xr = xc - obs.com[k, 0]
                yr = yc - obs.com[k, 1]
                sums = penalization_integrals(
                    v_cf, obs.chi_s[k], obs.udef_s[k], xr, yr,
                    cfg.lam * dt, hsq)
                uvw.append(solve_rigid_momentum(*sums))
            else:
                uvw.append(prescribed[k])
        uvw = jnp.stack(uvw)

        # shape-shape collisions (main.cpp:6705-6943): opponent-merged
        # integrals in one field pass, impulses via lax.fori_loop —
        # O(S*N) + O(1)-compile in the pair count (many-body ready)
        if S > 1:
            colls = merged_overlap_integrals(
                obs.chi_s, obs.sdf_s, obs.udef_s, uvw, obs.com, xc, yc)
            lengths = jnp.asarray(
                [s.length for s in self.shapes], v.dtype)
            uvw = pairwise_collision_update(
                colls, uvw, obs.mass, obs.inertia, obs.com, lengths)
            for k in range(S):
                if not self.shapes[k].free:
                    uvw = uvw.at[k].set(prescribed[k])

        # implicit penalization update, winner shape per cell
        # (main.cpp:6944-6979)
        win = jnp.argmax(obs.chi_s, axis=0)
        us = jnp.zeros_like(v_cf)
        for k in range(S):
            xr = xc - obs.com[k, 0]
            yr = yc - obs.com[k, 1]
            usk = jnp.stack([
                uvw[k, 0] - uvw[k, 2] * yr + obs.udef_s[k, 0],
                uvw[k, 1] + uvw[k, 2] * xr + obs.udef_s[k, 1],
            ])
            us = jnp.where(win == k, usk, us)
        alpha = jnp.where(obs.chi > 0.5, 1.0 / (1.0 + cfg.lam * dt), 1.0)
        v_cf = alpha * v_cf + (1.0 - alpha) * us
        v = v_cf.transpose(1, 0, 2, 3)

        udef = self._combined_udef(obs)  # [2,N,BS,BS]
        v, p_new, res, div_linf = self._pressure_project(
            v, pres, dt, h, hsq, t1v, t1s, tpois, corr, tcoarse,
            exact_poisson, maskv,
            chi=obs.chi, udef_b=udef.transpose(1, 0, 2, 3))
        diag = {
            "poisson_iters": res.iters,
            "poisson_residual": res.residual,
            "poisson_stalled": res.stalled,
            "poisson_converged": res.converged,
            "finite": self._finite_flag(v, p_new, maskv),
            "umax": jnp.max(jnp.abs(v)),
            "energy": self._energy(v, hsq),
            "div_linf": div_linf,
            "precond_cycles": self._precond_cycles(
                res, tcoarse, exact_poisson),
        }
        return v, p_new, uvw, diag

    # ------------------------------------------------------------------
    # device: the fused per-step megacall — rasterize + flow (+ forces)
    # + next-dt in ONE dispatch, so a step costs one host->device launch
    # and one batched device->host pull (each round trip is ~100 ms
    # through the TPU tunnel; the unfused chain paid ~6 of them)
    # ------------------------------------------------------------------
    def _megastep_impl(self, vel, pres, inputs, prescribed,
                       dt, hmin, h, hsq, maskv, xc, yc,
                       t3, t1v, t1s, tpois, t4v, t4s, corr, tcoarse,
                       exact_poisson=False, with_forces=False):
        cfg = self.cfg
        obs = self._rasterize_impl(inputs, xc, yc, h[:, 0], hsq, t1s)
        vel, pres, uvw, diag = self._flow_impl(
            vel, pres, obs, prescribed, dt, h, hsq, maskv,
            xc, yc, t3, t1v, t1s, tpois, corr, tcoarse,
            exact_poisson=exact_poisson)
        # next step's dt from THIS step's end-state umax, same shared
        # arithmetic as compute_dt so restarts can't fork the trajectory
        dt_next = self._dt_from_umax(diag["umax"], hmin)
        forces = None
        if with_forces:
            forces = self._forces_impl(
                vel, pres, obs, uvw, t4v, t4s,
                h[:, 0, 0, 0], xc, yc)
        scalars = (uvw, obs.com, obs.mass, obs.inertia, dt_next, diag)
        return vel, pres, obs.chi[:, None], scalars, forces

    @staticmethod
    def _combined_udef(obs: ObstacleForestFields) -> jnp.ndarray:
        """Deformation-velocity field for the pressure RHS and the
        initial blend (main.cpp:6980-7006; ties sum)."""
        return jnp.sum(
            jnp.where((obs.chi_s >= obs.chi)[:, None], obs.udef_s, 0.0),
            axis=0)

    # ------------------------------------------------------------------
    # device: rasterization + chi + integrals (ongrid, main.cpp:4208-4630)
    # ------------------------------------------------------------------
    def _rasterize_impl(self, inputs, xc, yc, h3, hsq, t1s):
        cfg = self.cfg
        bs = cfg.bs
        dtype = self.forest.dtype
        N = xc.shape[0]
        neg = _raster_neg(cfg, dtype)
        S = len(self.shapes)

        # per-shape window rasterization, scattered into block layout
        # (pad rows target the dropped N-th slot)
        sdf = jnp.full((N, bs, bs), neg, dtype)
        per = []
        for k in range(S):
            inp = inputs[k]
            sdf_k, udef_k, wm_k = self._window_raster(inp, N)
            sdf = jnp.maximum(sdf, sdf_k)
            per.append((sdf_k, udef_k, wm_k, inp["com"]))

        # chi from the COMBINED sdf lab at each block's own h
        # (PutChiOnGrid, main.cpp:3911-3969)
        slab = assemble_labs_ordered(sdf[:, None], t1s)[:, 0]
        chi = jnp.zeros((N, bs, bs), dtype)
        chi_s, sdf_s, udef_s = [], [], []
        coms, masses, inertias = [], [], []
        for k in range(S):
            sdf_k, udef_k, wm_k, com = per[k]
            chi_k = chi_from_sdf(slab, sdf_k, h3)

            # CoM correction (main.cpp:4468-4487); zero-mass guard
            m0 = jnp.sum(chi_k * hsq)
            dcx = jnp.sum(chi_k * hsq * (xc - com[0]))
            dcy = jnp.sum(chi_k * hsq * (yc - com[1]))
            safe = jnp.where(m0 > 0, m0, 1.0)
            com_n = com + jnp.where(
                m0 > 0, jnp.stack([dcx, dcy]) / safe, 0.0)

            # integrals + udef de-meaning (main.cpp:4488-4560),
            # window-masked like the uniform path
            xr = xc - com_n[0]
            yr = yc - com_n[1]
            _, _, m, j, iu, iv, ia = shape_integrals(
                chi_k, udef_k, xr, yr, hsq)
            corr = jnp.stack([iu - ia * yr, iv + ia * xr])
            ud = wm_k[None, :, None, None] * (udef_k - corr)

            chi = jnp.maximum(chi, chi_k)
            chi_s.append(chi_k)
            sdf_s.append(sdf_k)
            udef_s.append(ud)
            coms.append(com_n)
            masses.append(m)
            inertias.append(j)

        return ObstacleForestFields(
            chi=chi, sdf=sdf,
            chi_s=jnp.stack(chi_s), sdf_s=jnp.stack(sdf_s),
            udef_s=jnp.stack(udef_s),
            com=jnp.stack(coms), mass=jnp.stack(masses),
            inertia=jnp.stack(inertias),
        )

    def _window_raster(self, inp, N):
        """SDF + deformation velocity of one shape over its window
        blocks, scattered into the ordered block layout (the PutFish-
        OnBlocks gather form, main.cpp:3774-3990). ShardedAMRSim
        overrides the SCATTER with a per-device split (shard-local
        writes); the evaluation itself is the shared _window_sdf_udef,
        so the two paths cannot drift apart numerically."""
        bs = self.cfg.bs
        dtype = self.forest.dtype
        neg = _raster_neg(self.cfg, dtype)
        pos = inp["pos"]                 # [P], -1 = padding
        wmask = pos >= 0
        d, ud = _window_sdf_udef(inp, bs, dtype)
        spos = jnp.where(wmask, pos, N)
        wm3 = wmask[:, None, None]
        sdf_k = jnp.full((N + 1, bs, bs), neg, dtype).at[spos].set(
            jnp.where(wm3, d, neg))[:N]
        udef_k = jnp.zeros((2, N + 1, bs, bs), dtype).at[:, spos].set(
            jnp.where(wm3[None], ud, 0.0))[:, :N]
        wm_k = jnp.zeros((N + 1,), dtype).at[spos].set(
            wmask.astype(dtype))[:N]
        return sdf_k, udef_k, wm_k

    # ------------------------------------------------------------------
    # device: tagging kernels
    # ------------------------------------------------------------------
    def _vorticity_impl(self, vel, h, t1v):
        """Per-block Linf of vorticity (the refinement tag,
        main.cpp:4671-4688). ``vel`` ordered compact."""
        lab = assemble_labs_ordered(vel, t1v)
        w = vorticity(lab, 1, h[:, 0])             # [N, BS, BS]
        return jnp.max(jnp.abs(w), axis=(-1, -2))  # [N]

    def _chi_tag_impl(self, chi_o, t4s, finest):
        """GradChiOnTmp (main.cpp:4631-4656): any positive chi in the
        block's padded window forces refinement (offset 4 at the finest
        level — where it only blocks compression — else 2)."""
        lab = assemble_labs_ordered(chi_o, t4s)[:, 0]      # [N, L, L]
        c = jnp.clip(lab, 0.0, 1.0)
        has4 = jnp.max(c, axis=(-1, -2)) > 0.0
        has2 = jnp.max(c[:, 2:-2, 2:-2], axis=(-1, -2)) > 0.0
        return jnp.where(finest, has4, has2)

    def _tags_impl(self, vel, chi_o, h, t1v, t4s, finest):
        """Fused refinement tags: max of the vorticity Linf and the
        GradChiOnTmp marker (2*Rtol where chi is present) — the two
        computeA passes the reference runs back to back (adapt(),
        main.cpp:4659-4661), one dispatch here."""
        w = self._vorticity_impl(vel, h, t1v)
        has = self._chi_tag_impl(chi_o, t4s, finest)
        return jnp.maximum(w, jnp.where(has, 2.0 * self.cfg.rtol, 0.0))

    def _prolong_impl(self, field, parents, order, t):
        """[R] parent block labs -> [R, 4, dim, BS, BS] children via the
        reference's 2nd-order Taylor prolongation (main.cpp:5002-5028);
        tensorial g=1 labs supply the corner ghosts the xy term needs."""
        labs = assemble_labs(field, order, t)           # [N, dim, L, L]
        plabs = labs[parents]                           # [R, dim, L, L]
        bs = self.cfg.bs

        def children(lab):
            # lab [dim, BS+2, BS+2]; coarse cell (i0, j0) = lab[1+i, 1+j]
            l00 = lab[:, 1:bs + 1, 1:bs + 1]
            lp0 = lab[:, 1:bs + 1, 2:bs + 2]
            lm0 = lab[:, 1:bs + 1, 0:bs]
            l0p = lab[:, 2:bs + 2, 1:bs + 1]
            l0m = lab[:, 0:bs, 1:bs + 1]
            lpp = lab[:, 2:bs + 2, 2:bs + 2]
            lmm = lab[:, 0:bs, 0:bs]
            lpm = lab[:, 0:bs, 2:bs + 2]
            lmp = lab[:, 2:bs + 2, 0:bs]
            x = 0.5 * (lp0 - lm0)
            y = 0.5 * (l0p - l0m)
            x2 = (lp0 + lm0) - 2.0 * l00
            y2 = (l0p + l0m) - 2.0 * l00
            xy = 0.25 * ((lpp + lmm) - (lpm + lmp))
            base = l00 + 0.03125 * (x2 + y2)
            q00 = base - 0.25 * x - 0.25 * y + 0.0625 * xy
            q10 = base + 0.25 * x - 0.25 * y - 0.0625 * xy
            q01 = base - 0.25 * x + 0.25 * y - 0.0625 * xy
            q11 = base + 0.25 * x + 0.25 * y + 0.0625 * xy

            def interleave(a, b, c, d):
                # fine block for child (I, J): rows 2j(+1), cols 2i(+1)
                fine = jnp.zeros(
                    (a.shape[0], 2 * bs, 2 * bs), dtype=a.dtype)
                fine = fine.at[:, 0::2, 0::2].set(a)
                fine = fine.at[:, 0::2, 1::2].set(b)
                fine = fine.at[:, 1::2, 0::2].set(c)
                fine = fine.at[:, 1::2, 1::2].set(d)
                return fine

            fine = interleave(q00, q10, q01, q11)  # [dim, 2BS, 2BS]
            return jnp.stack([
                fine[:, :bs, :bs], fine[:, :bs, bs:],
                fine[:, bs:, :bs], fine[:, bs:, bs:],
            ])  # [4(child J*2+I... ordered (I,J)=(0,0),(1,0),(0,1),(1,1)), dim, BS, BS]

        return jax.vmap(children)(plabs)

    # ------------------------------------------------------------------
    # device: surface force diagnostics (main.cpp:7188-7284)
    # ------------------------------------------------------------------
    def _forces_impl(self, vel, pres, obs, uvw, t4v, t4s,
                     hflat, xc, yc):
        velp = assemble_labs_ordered(vel, t4v)                 # [N,2,L,L]
        chip = assemble_labs_ordered(obs.chi[:, None], t4s)[:, 0]
        sdfp = assemble_labs_ordered(obs.sdf[:, None], t4s)[:, 0]
        pord = pres[:, 0]
        out = []
        for k in range(len(self.shapes)):
            out.append(surface_forces_blocks(
                velp, pord, chip, sdfp,
                obs.udef_s[k].transpose(1, 0, 2, 3), obs.sdf_s[k],
                xc, yc, obs.com[k], uvw[k], self.cfg.nu, hflat, G=4))
        return out

    # ------------------------------------------------------------------
    # host: obstacle bookkeeping
    # ------------------------------------------------------------------
    def _shape_inputs(self):
        """Select blocks intersecting each body's padded bounding box and
        build the device rasterization inputs (the reference's
        AreaSegment-AABB block intersection, main.cpp:4208-4269). Window
        capacities are padded powers of two, so a moving body only
        recompiles when it grows past the current capacity."""
        cfg = self.cfg
        f = self.forest
        order = self._order
        bs = cfg.bs
        h = cfg.h0 / (1 << f.level[order]).astype(np.float64)
        x0 = f.bi[order] * bs * h
        y0 = f.bj[order] * bs * h
        x1 = x0 + bs * h
        y1 = y0 + bs * h
        dt_ = f.dtype
        out = []
        for k, s in enumerate(self.shapes):
            r = self._raster_radius(s)
            cx, cy = s.com
            hit = (x1 > cx - r) & (x0 < cx + r) \
                & (y1 > cy - r) & (y0 < cy + r)
            idx = np.nonzero(hit)[0].astype(np.int32)
            if len(idx) > self._wcap[k]:
                self._wcap[k] = max(
                    16, 1 << int(np.ceil(np.log2(len(idx) * 1.3))))
            pos = np.full(self._wcap[k], -1, np.int32)
            pos[:len(idx)] = idx
            # window-block origins/spacings ride along so the raster
            # kernel computes its cell coordinates instead of gathering
            # them from the (possibly sharded) per-block arrays
            wx0 = np.zeros(self._wcap[k])
            wy0 = np.zeros(self._wcap[k])
            wh = np.ones(self._wcap[k])
            wx0[:len(idx)] = x0[idx]
            wy0[:len(idx)] = y0[idx]
            wh[:len(idx)] = h[idx]
            mid_r, mid_v, mid_nor, mid_vnor = s.midline_comp_frame()
            com = np.asarray(s.com, np.float64)
            # packed body-frame tables (see _window_sdf_udef): com is
            # subtracted host-side in f64 so the device sees two large
            # operands instead of ~13 tiny derived arrays
            seg = pack_polygon_segments(s.surface_polygon() - com)
            mid = pack_midline(mid_r - com, mid_v, mid_nor, mid_vnor,
                               s.width)
            out.append({
                "pos": jnp.asarray(pos),
                "wx0": jnp.asarray(wx0, dtype=dt_),
                "wy0": jnp.asarray(wy0, dtype=dt_),
                "wh": jnp.asarray(wh, dtype=dt_),
                "seg": jnp.asarray(seg, dtype=dt_),
                "mid": jnp.asarray(mid, dtype=dt_),
                "com": jnp.asarray(s.com, dtype=dt_),
            })
        return out

    def _rasterize(self) -> ObstacleForestFields:
        self._refresh()
        return self._raster_jit(
            self._shape_inputs(), self._xc, self._yc, self._h3,
            self._hsq_flat, self._tables["sca1"])

    def _write_chi(self, obs: ObstacleForestFields):
        f = self.forest
        f.fields["chi"] = f.fields["chi"].at[self._order_j].set(
            obs.chi[:, None])

    def _raster_radius(self, s) -> float:
        """Half-extent of a shape's rasterization window (the
        AreaSegment AABB padding policy, main.cpp:4237) — the ONE
        definition shared by window selection and capacity sizing."""
        return 0.625 * s.length + 12.0 * self.cfg.min_h

    @staticmethod
    def _shape_bbox(s):
        """Axis-aligned bbox of the shape's surface polygon (valid after
        advect/midline)."""
        poly = s.surface_polygon()
        return poly.min(axis=0), poly.max(axis=0)

    def _window_blocks_estimate(self, s) -> int:
        """Finest-level blocks covering shape ``s``'s rasterization
        window (sizes the static raster window capacity)."""
        cfg = self.cfg
        h_fin = cfg.h_at(cfg.level_max - 1)
        r = self._raster_radius(s)
        return int(np.ceil(2.0 * r / (cfg.bs * h_fin))) ** 2

    def _body_blocks_estimate(self, s) -> int:
        """Finest-level blocks the chi-tag region around shape ``s``
        actually occupies: the axis-aligned bbox of its surface polygon
        (orientation included — the caller runs advect/midline first)
        padded by the tag's 4-cell ghost window."""
        cfg = self.cfg
        bh = cfg.bs * cfg.h_at(cfg.level_max - 1)
        pad = 8.0 * cfg.min_h
        lo, hi = self._shape_bbox(s)
        lb = int(np.ceil((float(hi[0] - lo[0]) + pad) / bh)) + 1
        wb = int(np.ceil((float(hi[1] - lo[1]) + pad) / bh)) + 1
        return lb * wb

    def _estimate_blocks(self, coarse_start: bool) -> int:
        """Upper-ish estimate of the peak active block count of the init
        climb. Climbing UP from the coarsest grid (coarse_start), the
        peak is near the final adapted count: background + per-shape
        body blocks with a 2.5x margin for the coarser-level pyramid and
        the 2:1 rings. Climbing DOWN from levelStart, the starting
        uniform grid itself is the peak."""
        cfg = self.cfg
        est = cfg.bpdx * cfg.bpdy
        if not coarse_start:
            est += cfg.bpdx * cfg.bpdy << (2 * cfg.level_start)
        for s in self.shapes:
            est += int(2.5 * self._body_blocks_estimate(s))
        return est

    def _refine_toward_shapes(self) -> bool:
        """Bootstrap refinement for the init climb: refine every block
        whose footprint, padded by the chi tag's 4-cell ghost window,
        intersects a shape's bounding box and sits below level_max-1.
        Host-geometric — equivalent to GradChiOnTmp tagging once chi is
        resolvable, but works from grids so coarse the body is thinner
        than one cell (where chi rasterizes to nothing). The normal
        chi-driven adapt() immediately after the climb compresses the
        few bbox-corner blocks the tighter chi window wouldn't keep."""
        f = self.forest
        cfg = self.cfg
        self._refresh()
        order = self._order
        lv = f.level[order].astype(np.int64)
        biv = f.bi[order].astype(np.int64)
        bjv = f.bj[order].astype(np.int64)
        h = cfg.h0 / (1 << lv).astype(np.float64)
        bs = cfg.bs
        pad = 8.0 * h   # 4 ghost cells x one-level-finer margin
        x0 = biv * bs * h - pad
        x1 = (biv + 1) * bs * h + pad
        y0 = bjv * bs * h - pad
        y1 = (bjv + 1) * bs * h + pad
        hit = np.zeros(len(order), bool)
        for s in self.shapes:
            (bx0, by0), (bx1, by1) = self._shape_bbox(s)
            hit |= (x1 > bx0) & (x0 < bx1) & (y1 > by0) & (y0 < by1)
        st = np.where(hit & (lv < cfg.level_max - 1), 1, 0).astype(np.int8)
        return self._commit_states(lv, biv, bjv, st)

    def initialize(self):
        """The reference's startup (main.cpp:6542-6575): levelMax rounds
        of {rasterize; adapt} refine the grid around the bodies, then
        the initial velocity is the chi-blended deformation velocity.

        Two compile/throughput measures (BASELINE.md round-2 notes):
        the padded block axis and raster windows are pre-sized from
        block estimates so the climb compiles one executable set; and
        when the fields are still identically zero, the climb starts
        from the COARSEST grid and refines up toward the bodies
        (host-geometric bootstrap tags) instead of starting from the
        full levelStart grid and compressing the background away — the
        tag rules have the same fixed point (zero fields carry no
        vorticity), but the peak block count is the final adapted count
        rather than 4^levelStart x base, so the pad bucket the whole
        run inherits is several powers of two smaller."""
        if not self.shapes:
            self._initialized = True
            return
        cfg = self.cfg
        f = self.forest
        for s in self.shapes:
            s.advect(0.0, cfg.extents)
            s.midline(0.0)
        # one fused device query + one pull (a per-field pull costs a
        # tunnel round trip each)
        allzero = not bool(jnp.any(jnp.stack(
            [jnp.any(v != 0) for v in f.fields.values()])))
        # ctol <= 0 disables compression: the from-above climb then
        # keeps the levelStart background forever, so coarse start would
        # genuinely change the grid, not just its construction order
        coarse = allzero and cfg.level_start > 0 and cfg.ctol > 0
        self.reserve_blocks(self._estimate_blocks(coarse))
        # pre-size the per-shape rasterization windows the same way:
        # every window-capacity crossing during the climb recompiles the
        # megastep (the biggest executable in the repo)
        for k, s in enumerate(self.shapes):
            want = int(2.6 * self._window_blocks_estimate(s)) + 16
            self._wcap[k] = max(self._wcap[k], _bucket(want, lo=16))
        if coarse:
            for key in list(f.blocks):
                f.release(*key)
            for j in range(cfg.bpdy):
                for i in range(cfg.bpdx):
                    f.allocate(0, i, j)
            for _ in range(cfg.level_max + 2):
                if not self._refine_toward_shapes():
                    break
        for _ in range(cfg.level_max):
            obs = self._rasterize()
            self._write_chi(obs)
            if not self.adapt():
                break
        obs = self._rasterize()
        self._write_chi(obs)
        self._sync_shape_scalars(obs)
        f = self.forest
        vel = f.fields["vel"]
        vord = vel[self._order_j]
        udef = self._combined_udef(obs).transpose(1, 0, 2, 3)
        chi_b = obs.chi[:, None]
        f.fields["vel"] = vel.at[self._order_j].set(
            vord * (1.0 - chi_b) + udef * chi_b)
        self._initialized = True

    # ------------------------------------------------------------------
    # host driver
    # ------------------------------------------------------------------
    def _dt_from_umax(self, umax, hmin):
        """ops.stencil.dt_from_umax in the forest dtype — the device
        path (_megastep_impl's cached next-dt) and the host fallback
        (compute_dt) must agree bit-for-bit or a restart forks the
        trajectory the checkpoint machinery promises to preserve.

        Jitted when called from host driver code (traced callers hit
        the isinstance-of-Tracer branch and inline it): the eager form
        compiled 6+ one-op executables (abs/max/divide/minimum/...)
        whose per-executable tunnel transport is a real slice of warm
        init (r5 init_compiles probe: 38 of 62 init executables were
        such one-op jits)."""
        if isinstance(umax, jax.core.Tracer) or \
                isinstance(hmin, jax.core.Tracer):
            return dt_from_umax(umax, hmin, self.cfg.nu, self.cfg.cfl)
        if self._dt_jit is None:
            from . import tracing
            self._dt_jit = tracing.named_jit(
                "amr.dt", jax.jit(
                    lambda u, h: dt_from_umax(u, h, self.cfg.nu,
                                              self.cfg.cfl)))
        return self._dt_jit(jnp.asarray(umax, self.forest.dtype), hmin)

    def _hmin(self):
        """Finest active spacing as a device scalar — the ONE
        definition every dt path (compute_dt, both cached-umax branches,
        the megastep argument) must share, or the restart/lockstep
        contracts silently desynchronize."""
        return jnp.asarray(
            self.cfg.h_at(int(self.forest.level[self._order].max())),
            self.forest.dtype)

    def compute_dt(self) -> float:
        # masked: ordered pad rows carry stale (finite) data.
        # _float_pull (not float): this is the obstacle-free dt
        # fallback after external field writes — a plain float() here
        # would discard the pending poisson-iters scalar and disarm
        # the two-level trigger exactly on such drivers (code-review r4)
        if self._umax_jit is None:
            from . import tracing
            self._umax_jit = tracing.named_jit(
                "amr.umax", jax.jit(
                    lambda v, m: jnp.max(jnp.abs(v) * m)))
        umax = self._umax_jit(self._ordered_state()["vel"], self._maskv)
        return self._float_pull(self._dt_from_umax(umax, self._hmin()))

    def _use_coarse(self, exact: bool):
        """Coarse-correction operand for the next solve: always for the
        startup (exact) solves; for production, engaged when the last
        solve burned > 15 iterations and sticky until the next topology
        change (block-Jacobi alone follows the uniform path's
        block-count scaling law on near-uniform forests — ~200
        iterations/step at 1e4 blocks, BASELINE.md r4 scale trace).
        Maps build lazily on first engagement. CUP2D_POIS=fft keeps
        the correction ALWAYS on for production solves — cutting
        iterations is the point of that mode, so it never waits for
        the trigger's evidence (``_coarse_on`` is still set, so the
        guard's replay trigger-state record stays truthful). The
        forest-FAS modes (fas/fas-f) likewise: the hierarchy IS the
        solver, so its maps are unconditionally engaged."""
        if not exact:
            if self._pois_mode in ("fft", "fas", "fas-f"):
                self._coarse_on = True
            if not self._coarse_on and self._last_iters > 15:
                self._coarse_on = True
            if not self._coarse_on:
                return None
        if self._coarse_cw is None:
            self._build_coarse_maps(self._npad_hwm, self._n_real)
        return self._coarse_cw

    def _float_pull(self, x) -> float:
        """float(x) that also drains the pending poisson-iters scalar
        in the SAME host transfer (the trigger must not add a tunnel
        round trip to the obstacle-free step)."""
        if self._last_iters_dev is not None:
            v, it = jax.device_get((x, self._last_iters_dev))
            self._last_iters = int(it)
            self._last_iters_dev = None
            return float(v)
        return float(x)

    def step_once(self, dt: Optional[float] = None):
        self._refresh()
        f = self.forest
        if not self.shapes:
            tm = self.timers or NULL_TIMERS
            ordf = self._ordered_state()
            if dt is None:
                # same cached-umax policy as the obstacle path: the
                # previous step's end-state umax (kept ON DEVICE) feeds
                # the shared dt arithmetic — one scalar round trip
                # instead of a full field reduction per step (the
                # obstacle-free driver paid 2.3 s/step for compute_dt
                # at 16k-pad through the tunnel, measured in the
                # round-3 scale proof). Under async_diag even that one
                # scalar round trip goes: dt STAYS a device scalar fed
                # straight into the dispatch (identical arithmetic, so
                # the trajectory is bit-identical to the eager path —
                # float()ing a device scalar and re-putting it is a
                # lossless round trip).
                with tm.phase("dt"):
                    if self._next_umax is not None:
                        # post-regrid: same 1.05 prolongation-overshoot
                        # guard as the obstacle path (ADVICE r2)
                        fac = (1.0 if self._next_umax_version
                               == f.version else 1.05)
                        dt_dev = self._dt_from_umax(
                            fac * jnp.asarray(self._next_umax, f.dtype),
                            self._hmin())
                        dt = (dt_dev if self.async_diag
                              else self._float_pull(dt_dev))
                    else:
                        dt = self.compute_dt()
            elif self._last_iters_dev is not None and not self.async_diag:
                # explicit-dt callers still drain the iters scalar
                # (async mode keeps it on device: the guard's lagged
                # pull IS the drain — replay must not add pulls)
                self._float_pull(jnp.zeros((), f.dtype))
            exact = self.step_count < 10 or self._force_exact
            dt_dev = jnp.asarray(dt, f.dtype)
            with tm.phase("flow"):
                vel, pres, diag = self._step_jit(
                    ordf["vel"], ordf["pres"], dt_dev,
                    self._h, self._hsq_flat, self._maskv,
                    self._tables["vec3"], self._tables["vec1"],
                    self._tables["sca1"], self._tables["pois"],
                    self._corr, self._use_coarse(exact),
                    exact_poisson=exact)
                self._set_ordered(vel=vel, pres=pres)
                # end-state umax stays a DEVICE scalar — the next
                # step's dt derives from it without an extra field
                # reduction, and only its one-scalar pull touches host
                self._next_umax = diag["umax"]
                self._next_umax_version = f.version
                if not exact:
                    # iters ride the NEXT dt pull (see _float_pull).
                    # Exact-startup counts are excluded: they converge
                    # 3 orders deeper with a different M, and would
                    # spuriously trip the production trigger on
                    # compressed forests (code-review r4)
                    self._last_iters_dev = diag["poisson_iters"]
                if self.async_diag:
                    diag = dict(diag)
                    diag["dt"] = dt_dev      # the lagged clock's source
                    self.step_count += 1
                    return diag              # no fence: no host sync
                diag = dict(diag)
                # the EXACT dt used (host float here), for the guard's
                # replay record — a time-difference reconstruction is
                # off by an ulp (review PR 4)
                diag["dt"] = float(dt)
                tm.fence("flow", vel)   # charge flow to "flow"
            self.time += dt
            self.step_count += 1
            return diag

        if not getattr(self, "_initialized", False):
            self.initialize()
            self._refresh()
        tm = self.timers or NULL_TIMERS
        # run the external-write invalidation BEFORE the dt branch: an
        # external forest.fields write between steps (wver moved) must
        # drop the cached _next_dt/_next_umax here exactly as on the
        # obstacle-free path, or one step runs at the stale dt — a
        # silent CFL violation (ADVICE r3 medium)
        self._ordered_state()
        if self._last_iters_dev is not None:
            # a pending obstacle-free iters scalar must be drained on
            # entry to the shaped path (shapes appended mid-run): left
            # pending, a later _float_pull would overwrite the fresher
            # megastep-set _last_iters with this stale count and
            # perturb the two-level trigger (ADVICE r4)
            self._float_pull(jnp.zeros((), f.dtype))
        if dt is None:
            # prefer the dt the PREVIOUS megastep computed on device —
            # a fresh compute_dt() is a full host<->device round trip
            if self._next_dt is not None and \
                    self._next_dt_version == f.version:
                dt = min(self._next_dt, self._kinematic_dt_cap())
            elif self._next_umax is not None:
                # a regrid invalidated the layout, not the physics: the
                # velocity field is the same water, re-gridded (2nd-order
                # prolongation can overshoot umax by a few %, well inside
                # the CFL-0.5 slack). Only hmin can change; recompute dt
                # from the cached end-state umax through the SAME shared
                # arithmetic — one scalar round trip instead of a full
                # field reduction + compile after every adapt (9.5 s/call
                # measured on the canonical case through the tunnel).
                # The 1.05 factor turns the prolongation-overshoot
                # argument from an asserted comment into an enforced
                # bound (ADVICE r2): any overshoot up to 5% now tightens
                # dt instead of silently stretching CFL.
                with tm.phase("dt"):
                    dt = min(float(self._dt_from_umax(
                        jnp.asarray(1.05 * self._next_umax, f.dtype),
                        self._hmin())),
                        self._kinematic_dt_cap())
            else:
                with tm.phase("dt"):
                    dt = min(self.compute_dt(), self._kinematic_dt_cap())

        # ongrid host part (main.cpp:3992-4207)
        cfg = self.cfg
        with tm.phase("kinematics"):
            for s in self.shapes:
                s.advect(dt, cfg.extents)
                s.midline(self.time)
        with tm.phase("rasterize"):
            inputs = self._shape_inputs()

        prescribed = jnp.asarray(
            [[s.u, s.v, s.omega] for s in self.shapes], dtype=f.dtype)
        exact = self.step_count < 10 or self._force_exact
        with_forces = bool(
            self.compute_forces_every
            and self.step_count % self.compute_forces_every == 0)
        hmin = self._hmin()
        ordf = self._ordered_state()
        with tm.phase("flow"):
            vel, pres, chi_new, scalars, forces = self._mega_jit(
                ordf["vel"], ordf["pres"],
                inputs, prescribed, jnp.asarray(dt, f.dtype), hmin,
                self._h, self._hsq_flat, self._maskv,
                self._xc, self._yc,
                self._tables["vec3"], self._tables["vec1"],
                self._tables["sca1"], self._tables["pois"],
                self._tables.get("vec4t"), self._tables.get("sca4t"),
                self._corr, self._use_coarse(exact),
                exact_poisson=exact,
                with_forces=with_forces)
            self._set_ordered(vel=vel, pres=pres, chi=chi_new)
            # the ONE host pull of the step
            uvw, com, mass, inertia, dt_next, diag, forces = \
                jax.device_get((*scalars, forces))
            diag["dt"] = float(dt)    # exact replay record (see above)
            # the scalar pull alone does not prove the fields landed
            tm.fence("flow", vel)
        self._sync_shape_scalars_np(com, mass, inertia)
        uvw_np = np.asarray(uvw, dtype=np.float64)
        for k, s in enumerate(self.shapes):
            if s.free:
                s.u, s.v, s.omega = uvw_np[k]
        self._next_dt = float(dt_next)
        self._next_dt_version = f.version
        self._next_umax = float(diag["umax"])
        self._next_umax_version = f.version
        if not exact:
            # the megastep's single pull already carried the iteration
            # count — feed the production two-level trigger directly
            # (exact-startup counts excluded, see the step_jit path)
            self._last_iters = int(diag["poisson_iters"])
        if with_forces:
            with tm.phase("forces"):
                self._record_forces(forces)

        self.time += dt
        self.step_count += 1
        return diag

    # -- regrid --------------------------------------------------------
    def adapt(self):
        """Tag / 2:1-balance / refine / coarsen (main.cpp:4657-5440)."""
        # refresh BEFORE entering the phase: table time always lands in
        # the top-level "tables" bucket, never nested under "adapt" (so
        # profiling.throughput can sum phases without double counting)
        self._refresh()
        from . import tracing
        with (self.timers or NULL_TIMERS).phase("adapt"), \
                tracing.span("regrid", step=int(self.step_count)):
            return self._adapt_impl()

    def _adapt_impl(self):
        f = self.forest
        cfg = self.cfg
        # one fused dispatch + one pull for both tag kernels (each extra
        # sync costs a full tunnel round trip)
        ordf = self._ordered_state()
        if self.shapes and "chi" in f.fields:
            finest = np.zeros(len(self._mask), bool)
            finest[:self._n_real] = \
                f.level[self._order] == cfg.level_max - 1
            tags = self._pull_blockwise(self._tags_jit(
                ordf["vel"], ordf["chi"],
                self._h, self._tables["vec1"], self._tables["sca4t"],
                jnp.asarray(finest)))[:self._n_real]
        else:
            tags = self._pull_blockwise(self._vorticity_jit(
                ordf["vel"], self._h,
                self._tables["vec1"]))[:self._n_real]
        order = self._order

        # 1 = refine, -1 = compress, 0 = leave — vectorized over the
        # ordered block arrays (a per-block Python dict was O(n) host
        # time per adapt; only the final refine/group LISTS — small —
        # are materialized for the regrid bookkeeping)
        lv = f.level[order].astype(np.int64)
        biv = f.bi[order].astype(np.int64)
        bjv = f.bj[order].astype(np.int64)
        st = np.where(
            (tags > cfg.rtol) & (lv < cfg.level_max - 1), 1,
            np.where((tags < cfg.ctol) & (lv > 0), -1, 0)
        ).astype(np.int8)
        return self._commit_states(lv, biv, bjv, st)

    def _commit_states(self, lv, biv, bjv, st) -> bool:
        """Shared tail of every regrid decision (chi/vorticity adapts
        AND the init-climb bootstrap): 2:1 state fixing, refine/compress
        extraction, one fused regrid dispatch. Returns whether anything
        changed."""
        if not st.any():
            return False
        self._fix_states(lv, biv, bjv, st)
        refine = [(int(lv[k]), int(biv[k]), int(bjv[k]))
                  for k in np.nonzero(st == 1)[0]]
        groups = self._compress_groups(lv, biv, bjv, st)
        if not refine and not groups:
            return False
        self._apply_regrid(refine, groups)
        return True

    def _fix_states(self, lv, biv, bjv, st):
        """2:1 balance sweeps, finest level first (main.cpp:4734-4861):
        a block with a refining finer neighbor must refine; compressing
        next to a finer or refining neighbor must stay. ``st`` is
        mutated in place. Runs the native C kernel when available
        (cup2d_tpu/native — the reference's equivalent bookkeeping is
        C++ inside adapt()); the Python body below is the semantically
        identical fallback, asserted equal by tests/test_native.py."""
        cfg = self.cfg
        # the native wrapper does its own contiguous-int32 conversion
        if native.available() and native.fix_states(
                lv, biv, bjv, st, cfg.level_max, cfg.bpdx, cfg.bpdy):
            return
        state = {(int(lv[k]), int(biv[k]), int(bjv[k])): int(st[k])
                 for k in range(len(st))}
        self._fix_states_py(state)
        for k in range(len(st)):
            st[k] = state[(int(lv[k]), int(biv[k]), int(bjv[k]))]

    def _fix_states_py(self, state):
        f = self.forest
        cfg = self.cfg
        for m in range(cfg.level_max - 1, -1, -1):
            for key in list(state.keys()):
                l, i, j = key
                if l != m or state[key] == 1 or l == cfg.level_max - 1:
                    continue
                nbx, nby = f.nblocks_at(l)
                for cx in (-1, 0, 1):
                    for cy in (-1, 0, 1):
                        if cx == 0 and cy == 0:
                            continue
                        ni, nj = i + cx, j + cy
                        if not (0 <= ni < nbx and 0 <= nj < nby):
                            continue
                        if f.owner_relation(l, ni, nj) != -1:
                            continue
                        if state[key] == -1:
                            state[key] = 0
                        # any refining finer neighbor forces refinement
                        for (a, b) in [(0, 0), (0, 1), (1, 0), (1, 1)]:
                            ck = (l + 1, 2 * ni + a, 2 * nj + b)
                            if state.get(ck, 0) == 1:
                                state[key] = 1
                                break
                        if state[key] == 1:
                            break
                    if state[key] == 1:
                        break
            # compressing next to a same-level refining neighbor
            for key in list(state.keys()):
                l, i, j = key
                if l != m or state[key] != -1:
                    continue
                nbx, nby = f.nblocks_at(l)
                for cx in (-1, 0, 1):
                    for cy in (-1, 0, 1):
                        if cx == 0 and cy == 0:
                            continue
                        nk = (l, i + cx, j + cy)
                        if nk in state and state[nk] == 1:
                            state[key] = 0
                            break
                    if state[key] == 0:
                        break

    def _compress_groups(self, lv, biv, bjv, st):
        """Sibling groups where all 4 children exist and want compression
        (main.cpp:4826-4861). Vectorized: each compressing block hashes
        to its parent key; a parent with FOUR compressing children is a
        group (each (l, i, j) occurs once, and a compressing block is by
        definition active, so count == 4 implies the quad exists)."""
        cand = np.nonzero(st == -1)[0]
        if len(cand) == 0:
            return []
        # row-unique instead of bit-packing: no coordinate-width limit
        parents = np.stack(
            [lv[cand], biv[cand] >> 1, bjv[cand] >> 1], axis=1)
        uniq, counts = np.unique(parents, axis=0, return_counts=True)
        groups = []
        for l, pi, pj in uniq[counts == 4]:
            i0, j0 = 2 * int(pi), 2 * int(pj)
            groups.append([(int(l), i0 + a, j0 + b)
                           for a in (0, 1) for b in (0, 1)])
        return groups

    def _apply_regrid(self, refine_keys, groups):
        """Refinement + compression as ONE device dispatch over ALL
        fields, with the refine/compress counts padded to power-of-two
        buckets. The r2 per-field path issued 2 prolongation calls + 5
        scatters per adapt AND retraced for every distinct refine count
        (a fresh XLA compile nearly every regrid while the vortex
        grows); bucketed counts + one fused executable make steady-state
        regrids pure cache hits. All gathers read the PRE-regrid field
        arrays (functional semantics), so refine writes can't corrupt
        compress reads; pad rows read/write a dead (inactive) slot.
        Reference: refinement main.cpp:4960-5033, compression 5055-5194.
        """
        f = self.forest
        # the prolongation/restriction gathers read the slot-layout
        # fields — flush the ordered working state first (pre-regrid
        # order is still valid here)
        self.sync_fields()
        ordpos = {int(s): k for k, s in enumerate(self._order)}
        R, G = len(refine_keys), len(groups)
        # one executable per pad bucket: padding refine/compress rows to
        # n_pad/4 (G can never exceed it — 4 siblings per group; R can
        # only during mass refinement, which falls back to its own
        # bucket) keeps steady-state regrids on a single compiled
        # executable instead of one per (Rp, Gp) combination — each
        # extra combination cost a full XLA compile of the fused
        # prolong+restrict program (~10-30 s through the remote-compile
        # tunnel, measured on the canonical case)
        cap = max(32, self._npad_hwm // 4)
        Rp = cap if R <= cap else _bucket(R, lo=4)
        Gp = cap if G <= cap else _bucket(G, lo=4)

        # host bookkeeping first: parents/siblings resolved BEFORE any
        # release; all allocations done (possibly growing the slot
        # arrays + device fields) before the jitted call captures them
        parents = np.full(Rp, self._n_real, np.int64)   # pad -> pad lab row
        for n, k in enumerate(refine_keys):
            parents[n] = ordpos[f.blocks[k]]
        sib_slots = np.empty((Gp, 4), np.int32)
        for g, sibs in enumerate(groups):
            l, i0, j0 = sibs[0]
            for ci, (a, b) in enumerate([(0, 0), (1, 0), (0, 1), (1, 1)]):
                sib_slots[g, ci] = f.blocks[(l, i0 + a, j0 + b)]

        child_slots = np.empty((Rp, 4), np.int32)
        for n, (l, i, j) in enumerate(refine_keys):
            f.release(l, i, j)
            for ci, (a, b) in enumerate([(0, 0), (1, 0), (0, 1), (1, 1)]):
                child_slots[n, ci] = f.allocate(l + 1, 2 * i + a, 2 * j + b)
        parent_slots = np.empty(Gp, np.int32)
        for g, sibs in enumerate(groups):
            l, i0, j0 = sibs[0]
            for (a, b) in [(0, 0), (1, 0), (0, 1), (1, 1)]:
                f.release(l, i0 + a, j0 + b)
            parent_slots[g] = f.allocate(l - 1, i0 // 2, j0 // 2)
        if not f._free:
            f._grow()
        dead = f._free[-1]
        child_slots[R:] = dead
        sib_slots[G:] = dead
        parent_slots[G:] = dead

        f.fields.update(self._regrid_jit(
            dict(f.fields), self._order_j,
            jnp.asarray(parents), jnp.asarray(child_slots.reshape(-1)),
            jnp.asarray(sib_slots), jnp.asarray(parent_slots),
            self._tables["vec1t"], self._tables["sca1t"]))
        self._n_refined += R
        self._n_coarsened += G
        (self.timers or NULL_TIMERS).fence("adapt", dict(f.fields))

    def _regrid_apply_impl(self, fields, order, parents, child_slots,
                           sib_slots, parent_slots, tv, ts):
        """Device half of _apply_regrid: per field, Taylor prolongation
        of the refined parents (2nd-order, tensorial g=1 labs) scattered
        to the 4 child slots, then 4->1 averaging restriction of the
        compression groups scattered to the parent slot. Pad rows source
        a pad lab row / the dead slot — finite garbage, never read
        unmasked."""
        out = {}
        for name, field in fields.items():
            t = tv if field.shape[1] == 2 else ts
            p = self._prolong_impl(field, parents, order, t)
            new = field.at[child_slots].set(
                p.reshape((-1,) + p.shape[2:]))
            d = field[sib_slots]   # [G, 4, dim, BS, BS]
            restr = 0.25 * (
                d[..., 0::2, 0::2] + d[..., 1::2, 0::2]
                + d[..., 0::2, 1::2] + d[..., 1::2, 1::2])
            row0 = jnp.concatenate([restr[:, 0], restr[:, 1]], axis=-1)
            row1 = jnp.concatenate([restr[:, 2], restr[:, 3]], axis=-1)
            parent = jnp.concatenate([row0, row1], axis=-2)
            out[name] = new.at[parent_slots].set(parent)
        return out

    def run(self, tend: float, max_steps: int = 10**9):
        diag = {}
        while self.time < tend and self.step_count < max_steps:
            if (self.step_count <= 10
                    or self.step_count % self.cfg.adapt_steps == 0):
                self.adapt()
            diag = self.step_once()
        return diag
