"""AMR simulation: the reference's adaptive solver on the block forest.

Reproduces the reference's adaptive time loop (`/root/reference/main.cpp`
adapt() 4657-5440 + the hot loop 6576-7290) with the TPU split:

host (numpy, per regrid)         device (jit, per step)
------------------------------   --------------------------------------
tagging decisions + 2:1 sweeps   vorticity for tags (lab + kernel)
slot alloc/release, SFC order    WENO5 advection-diffusion RK2 over all
halo gather-table rebuild          blocks at once (per-block h arrays)
                                 prolongation / restriction batches
                                 matrix-free BiCGSTAB on the forest
                                   (lab-assembled variable-resolution
                                   Laplacian + block-Jacobi GEMM)

Jitted functions are keyed by n_active so a regrid that changes the
block count triggers exactly one recompile for the new shape (the
reference rebuilds its MPI synchronizer plans at the same point,
main.cpp:5425-5437).

Level interfaces are discretely conservative: the Poisson operator uses
the makeFlux variable-resolution closure and the stencil kernels carry
coarse-fine flux correction (both in flux.py). Not yet on the forest
path: obstacles (uniform-grid Simulation covers them).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .config import SimConfig
from .flux import apply_flux_corr, build_flux_corr, build_poisson_tables, \
    diffusive_deposits, divergence_deposits, gradient_deposits
from .forest import Forest
from .halo import assemble_labs, assemble_labs_ordered, build_tables
from .ops.stencil import advect_diffuse_rhs, divergence, laplacian5, \
    pressure_gradient_update, vorticity
from .poisson import apply_block_precond_blocks, bicgstab, \
    block_precond_matrix


class AMRSim:
    """Adaptive obstacle-free flow solver on the block forest."""

    def __init__(self, cfg: SimConfig):
        self.cfg = cfg
        self.forest = Forest(cfg)
        self.forest.add_field("vel", 2)
        self.forest.add_field("pres", 1)
        self.time = 0.0
        self.step_count = 0
        self.p_inv = jnp.asarray(
            block_precond_matrix(cfg.bs), dtype=self.forest.dtype)
        # f64 Krylov-scalar accumulation for f32 fields (same rationale
        # as UniformGrid, uniform.py)
        self.sum_dtype = (
            jnp.float64
            if (self.forest.dtype == jnp.float32
                and jax.config.jax_enable_x64)
            else None
        )
        self._tables_version = -1
        self._tables = {}
        self._order = None
        # jitted ONCE; tables/order/h are arguments, so regrids that
        # reproduce previously-seen shapes hit the XLA compile cache
        self._step_jit = jax.jit(
            self._step_impl, static_argnames=("exact_poisson",))
        self._vorticity_jit = jax.jit(self._vorticity_impl)
        self._prolong_jit = jax.jit(self._prolong_impl)

    # ------------------------------------------------------------------
    # topology-dependent cached state
    # ------------------------------------------------------------------
    def _refresh(self):
        f = self.forest
        if self._tables_version == f.version:
            return
        self._order = f.order()
        self._tables = {
            "vec3": build_tables(f, self._order, 3, True, 2),
            "vec1": build_tables(f, self._order, 1, False, 2),
            "sca1": build_tables(f, self._order, 1, False, 1),
            "vec1t": build_tables(f, self._order, 1, True, 2),
            "sca1t": build_tables(f, self._order, 1, True, 1),
            # makeFlux variable-resolution Poisson rows (flux.py)
            "pois": build_poisson_tables(f, self._order),
        }
        self._corr = build_flux_corr(f, self._order)
        h = f.h_per_block(self._order)
        self._h = jnp.asarray(h, f.dtype)[:, None, None, None]
        self._hsq_flat = jnp.asarray(h * h, f.dtype)[:, None, None]
        self._order_j = jnp.asarray(self._order)
        self._tables_version = f.version

    # ------------------------------------------------------------------
    # device step (jitted per topology)
    # ------------------------------------------------------------------
    def _step_impl(self, vel, pres, dt, order, h, hsq, t3, t1v, t1s,
                   tpois, corr, exact_poisson=False):
        cfg = self.cfg
        ih2 = 1.0 / (h * h)

        # Heun RK2 advection-diffusion (per-block h); the diffusive face
        # fluxes are flux-corrected at level interfaces (the reference's
        # fillcases after each stage, main.cpp:6607-6642)
        vold = vel[order]                # [N,2,BS,BS]
        v = vold
        for c in (0.5, 1.0):
            lab = assemble_labs(
                vel.at[order].set(v) if c == 1.0 else vel, order, t3)
            rhs = advect_diffuse_rhs(lab, 3, h, cfg.nu, dt)
            rhs = apply_flux_corr(
                rhs, diffusive_deposits(lab, 3, cfg.nu * dt), corr)
            v = vold + c * rhs * ih2

        # Poisson in deltap form on the forest; the RHS divergence is
        # flux-corrected, and the operator (also applied to the initial
        # guess p_old) is the makeFlux variable-resolution closure —
        # conservative on both sides of every interface
        pord = pres[order][:, 0]         # [N,BS,BS]
        vel_full = vel.at[order].set(v)
        vlab = assemble_labs(vel_full, order, t1v)
        fac = 0.5 * h[:, 0] / dt
        b = fac * divergence(vlab, 1)
        b = apply_flux_corr(
            b, divergence_deposits(vlab, None, None, fac[:, 0, 0]), corr)

        def A(x):
            lab = assemble_labs_ordered(x[:, None], tpois)
            return laplacian5(lab, 1)[:, 0]

        # initial-guess subtraction via A itself (the reference uses the
        # lab Laplacian + flux correction, pressure_rhs1; using A keeps
        # A(dp + p_old) = div-rhs exactly)
        b = b - A(pord)

        def M(r):
            return apply_block_precond_blocks(r, self.p_inv)

        # f32 exact-mode floor: the mixed-forest residual floor sits at
        # ~2e-5 relative (measured on TPU; the makeFlux interface rows
        # amplify f32 rounding slightly vs the uniform path's 1e-5), so
        # 1e-4 converges in tens of iterations instead of burning
        # max_iter for each of the first 10 steps
        exact_rel = 0.0 if self.forest.dtype == jnp.float64 else 1e-4
        res = bicgstab(
            A, b, M=M,
            tol=0.0 if exact_poisson else cfg.poisson_tol,
            tol_rel=exact_rel if exact_poisson else cfg.poisson_tol_rel,
            max_iter=cfg.max_poisson_iterations,
            max_restarts=100 if exact_poisson else cfg.max_poisson_restarts,
            sum_dtype=self.sum_dtype,
        )

        # volume-weighted mean removal (main.cpp:7120-7173)
        wsum = jnp.sum(hsq) * self.cfg.bs ** 2
        dp = res.x - jnp.sum(res.x * hsq) / wsum
        p_new = dp + pord - jnp.sum(pord * hsq) / wsum

        # projection (shared kernel, per-block h broadcast), gradient
        # fluxes corrected (pressureCorrectionKernel + fillcases,
        # main.cpp:7174-7187)
        plab = assemble_labs_ordered(p_new[:, None], t1s)
        dv = pressure_gradient_update(plab[:, 0], 1, h, dt)
        pfac = -0.5 * dt * h[:, 0, 0, 0]
        dv = apply_flux_corr(
            dv, gradient_deposits(plab[:, 0], pfac), corr)
        v = v + dv * ih2

        vel = vel.at[order].set(v)
        pres = pres.at[order].set(p_new[:, None])
        diag = {
            "poisson_iters": res.iters,
            "poisson_residual": res.residual,
            "umax": jnp.max(jnp.abs(v)),
        }
        return vel, pres, diag

    def _vorticity_impl(self, vel, order, h, t1v):
        """Per-block Linf of vorticity (the refinement tag,
        main.cpp:4671-4688)."""
        lab = assemble_labs(vel, order, t1v)
        w = vorticity(lab, 1, h[:, 0])             # [N, BS, BS]
        return jnp.max(jnp.abs(w), axis=(-1, -2))  # [N]

    def _prolong_impl(self, field, parents, order, t):
        """[R] parent block labs -> [R, 4, dim, BS, BS] children via the
        reference's 2nd-order Taylor prolongation (main.cpp:5002-5028);
        tensorial g=1 labs supply the corner ghosts the xy term needs."""
        labs = assemble_labs(field, order, t)           # [N, dim, L, L]
        plabs = labs[parents]                           # [R, dim, L, L]
        bs = self.cfg.bs

        def children(lab):
            # lab [dim, BS+2, BS+2]; coarse cell (i0, j0) = lab[1+i, 1+j]
            l00 = lab[:, 1:bs + 1, 1:bs + 1]
            lp0 = lab[:, 1:bs + 1, 2:bs + 2]
            lm0 = lab[:, 1:bs + 1, 0:bs]
            l0p = lab[:, 2:bs + 2, 1:bs + 1]
            l0m = lab[:, 0:bs, 1:bs + 1]
            lpp = lab[:, 2:bs + 2, 2:bs + 2]
            lmm = lab[:, 0:bs, 0:bs]
            lpm = lab[:, 0:bs, 2:bs + 2]
            lmp = lab[:, 2:bs + 2, 0:bs]
            x = 0.5 * (lp0 - lm0)
            y = 0.5 * (l0p - l0m)
            x2 = (lp0 + lm0) - 2.0 * l00
            y2 = (l0p + l0m) - 2.0 * l00
            xy = 0.25 * ((lpp + lmm) - (lpm + lmp))
            base = l00 + 0.03125 * (x2 + y2)
            q00 = base - 0.25 * x - 0.25 * y + 0.0625 * xy
            q10 = base + 0.25 * x - 0.25 * y - 0.0625 * xy
            q01 = base - 0.25 * x + 0.25 * y - 0.0625 * xy
            q11 = base + 0.25 * x + 0.25 * y + 0.0625 * xy

            def interleave(a, b, c, d):
                # fine block for child (I, J): rows 2j(+1), cols 2i(+1)
                fine = jnp.zeros(
                    (a.shape[0], 2 * bs, 2 * bs), dtype=a.dtype)
                fine = fine.at[:, 0::2, 0::2].set(a)
                fine = fine.at[:, 0::2, 1::2].set(b)
                fine = fine.at[:, 1::2, 0::2].set(c)
                fine = fine.at[:, 1::2, 1::2].set(d)
                return fine

            fine = interleave(q00, q10, q01, q11)  # [dim, 2BS, 2BS]
            return jnp.stack([
                fine[:, :bs, :bs], fine[:, :bs, bs:],
                fine[:, bs:, :bs], fine[:, bs:, bs:],
            ])  # [4(child J*2+I... ordered (I,J)=(0,0),(1,0),(0,1),(1,1)), dim, BS, BS]

        return jax.vmap(children)(plabs)

    # ------------------------------------------------------------------
    # host driver
    # ------------------------------------------------------------------
    def compute_dt(self) -> float:
        self._refresh()
        # active slots only — freed slots keep stale data until reused
        umax = float(jnp.max(jnp.abs(
            self.forest.fields["vel"][self._order_j])))
        hmin = self.cfg.h_at(int(self.forest.level[self._order].max()))
        dt_diff = 0.25 * hmin * hmin / (self.cfg.nu + 0.25 * hmin * umax)
        return float(min(dt_diff, self.cfg.cfl * hmin / (umax + 1e-8)))

    def step_once(self, dt: Optional[float] = None):
        self._refresh()
        if dt is None:
            dt = self.compute_dt()
        f = self.forest
        exact = self.step_count < 10
        vel, pres, diag = self._step_jit(
            f.fields["vel"], f.fields["pres"], jnp.asarray(dt, f.dtype),
            self._order_j, self._h, self._hsq_flat,
            self._tables["vec3"], self._tables["vec1"],
            self._tables["sca1"], self._tables["pois"], self._corr,
            exact_poisson=exact)
        f.fields["vel"] = vel
        f.fields["pres"] = pres
        self.time += dt
        self.step_count += 1
        return diag

    # -- regrid --------------------------------------------------------
    def adapt(self):
        """Tag / 2:1-balance / refine / coarsen (main.cpp:4657-5440)."""
        self._refresh()
        f = self.forest
        cfg = self.cfg
        tags = np.asarray(self._vorticity_jit(
            f.fields["vel"], self._order_j, self._h,
            self._tables["vec1"]))
        order = self._order

        # 1 = refine, -1 = compress, 0 = leave
        state = {}
        for k, s in enumerate(order):
            key = (int(f.level[s]), int(f.bi[s]), int(f.bj[s]))
            if tags[k] > cfg.rtol and key[0] < cfg.level_max - 1:
                state[key] = 1
            elif tags[k] < cfg.ctol and key[0] > 0:
                state[key] = -1
            else:
                state[key] = 0
        if not any(state.values()):
            return False

        self._fix_states(state)

        refine = [k for k, v in state.items() if v == 1]
        groups = self._compress_groups(state)
        if not refine and not groups:
            return False

        self._do_refine(refine)
        self._do_compress(groups)
        return True

    def _fix_states(self, state):
        """2:1 balance sweeps, finest level first (main.cpp:4734-4861):
        a block with a refining finer neighbor must refine; compressing
        next to a finer or refining neighbor must stay."""
        f = self.forest
        cfg = self.cfg
        for m in range(cfg.level_max - 1, -1, -1):
            for key in list(state.keys()):
                l, i, j = key
                if l != m or state[key] == 1 or l == cfg.level_max - 1:
                    continue
                nbx, nby = f.nblocks_at(l)
                for cx in (-1, 0, 1):
                    for cy in (-1, 0, 1):
                        if cx == 0 and cy == 0:
                            continue
                        ni, nj = i + cx, j + cy
                        if not (0 <= ni < nbx and 0 <= nj < nby):
                            continue
                        if f.owner_relation(l, ni, nj) != -1:
                            continue
                        if state[key] == -1:
                            state[key] = 0
                        # any refining finer neighbor forces refinement
                        for (a, b) in [(0, 0), (0, 1), (1, 0), (1, 1)]:
                            ck = (l + 1, 2 * ni + a, 2 * nj + b)
                            if state.get(ck, 0) == 1:
                                state[key] = 1
                                break
                        if state[key] == 1:
                            break
                    if state[key] == 1:
                        break
            # compressing next to a same-level refining neighbor
            for key in list(state.keys()):
                l, i, j = key
                if l != m or state[key] != -1:
                    continue
                nbx, nby = f.nblocks_at(l)
                for cx in (-1, 0, 1):
                    for cy in (-1, 0, 1):
                        if cx == 0 and cy == 0:
                            continue
                        nk = (l, i + cx, j + cy)
                        if nk in state and state[nk] == 1:
                            state[key] = 0
                            break
                    if state[key] == 0:
                        break

    def _compress_groups(self, state):
        """Sibling groups where all 4 children exist and want compression
        (main.cpp:4826-4861)."""
        f = self.forest
        seen = set()
        groups = []
        for key, v in state.items():
            if v != -1:
                continue
            l, i, j = key
            base = (l, 2 * (i // 2), 2 * (j // 2))
            if base in seen:
                continue
            seen.add(base)
            sibs = [(l, base[1] + a, base[2] + b)
                    for a in (0, 1) for b in (0, 1)]
            if all(s in f.blocks and state.get(s, 0) == -1 for s in sibs):
                groups.append(sibs)
        return groups

    def _do_refine(self, keys):
        if not keys:
            return
        f = self.forest
        ordpos = {int(s): k for k, s in enumerate(self._order)}
        parents = jnp.asarray(
            [ordpos[f.blocks[k]] for k in keys], jnp.int32)
        prolonged = {
            name: np.asarray(self._prolong_jit(
                field, parents, self._order_j,
                self._tables["vec1t" if field.shape[1] == 2 else "sca1t"]))
            for name, field in f.fields.items()
        }
        for n, (l, i, j) in enumerate(keys):
            f.release(l, i, j)
            for ci, (a, b) in enumerate([(0, 0), (1, 0), (0, 1), (1, 1)]):
                s = f.allocate(l + 1, 2 * i + a, 2 * j + b)
                for name in f.fields:
                    f.fields[name] = f.fields[name].at[s].set(
                        prolonged[name][n, ci])

    def _do_compress(self, groups):
        if not groups:
            return
        f = self.forest
        for sibs in groups:
            l, i0, j0 = sibs[0]
            vals = {}
            for name, field in f.fields.items():
                quads = []
                for (a, b) in [(0, 0), (1, 0), (0, 1), (1, 1)]:
                    s = f.blocks[(l, i0 + a, j0 + b)]
                    d = field[s]
                    quads.append(((a, b), d))
                dim = field.shape[1]
                bs = self.cfg.bs
                parent = jnp.zeros((dim, bs, bs), field.dtype)
                for (a, b), d in quads:
                    restr = 0.25 * (
                        d[:, 0::2, 0::2] + d[:, 1::2, 0::2]
                        + d[:, 0::2, 1::2] + d[:, 1::2, 1::2])
                    parent = parent.at[
                        :, b * bs // 2:(b + 1) * bs // 2,
                        a * bs // 2:(a + 1) * bs // 2].set(restr)
                vals[name] = parent
            for (a, b) in [(0, 0), (1, 0), (0, 1), (1, 1)]:
                f.release(l, i0 + a, j0 + b)
            s = f.allocate(l - 1, i0 // 2, j0 // 2)
            for name in f.fields:
                f.fields[name] = f.fields[name].at[s].set(vals[name])

    def run(self, tend: float, max_steps: int = 10**9):
        diag = {}
        while self.time < tend and self.step_count < max_steps:
            if (self.step_count <= 10
                    or self.step_count % self.cfg.adapt_steps == 0):
                self.adapt()
            diag = self.step_once()
        return diag
