"""Multi-host launch: the reference's cluster scripts, TPU-style.

The reference ships per-cluster srun recipes (`/root/reference/rc.sh`,
`todi.sh`, `glados.sh`: clone + make + `srun -n2` with one GPU and a
time limit) and bootstraps its communicator with `MPI_Init`
(main.cpp:6307). On TPU pods the launcher is whatever starts one
Python process per host (GKE, `gcloud compute tpus tpu-vm ssh --worker=all`,
or a queued-resource runtime); inside the process the entire "comm
runtime" is `jax.distributed.initialize` + one global device mesh —
XLA routes intra-slice collectives over ICI and cross-slice traffic
over DCN with no code changes here.

Typical pod run (v5e-16, 4 hosts x 4 chips):

    gcloud compute tpus tpu-vm ssh $TPU --worker=all --command='
        cd cup-tpu && python -m cup2d_tpu ... -mesh all'

Each process calls `init_distributed()` (TPU environments autodetect
coordinator/process_id from the pod metadata), then `global_mesh()`
returns the mesh over every chip of every host; `ShardedUniformSim`
/ `ShardedAMRSim` take it unchanged. Single-host runs (and the CPU
virtual-device CI mesh) skip initialize and get the local mesh.

Multi-host AMR determinism (the reference's update_boundary /
update_blocks contract, main.cpp:1410-1970): the host-side regrid
bookkeeping — tag thresholding, 2:1 state fixing, slot allocation, SFC
ordering, gather-table builds — runs INDEPENDENTLY on every process,
and the SPMD program diverges (hangs or corrupts) if any process
reaches a different decision. The design makes that impossible by
construction:

1. every regrid decision derives from ONE tag vector that every
   process holds in full — `AMRSim._pull_blockwise` turns the
   device-side tag pull into a `process_allgather` when
   `jax.process_count() > 1` (single global collective, then identical
   host numpy on every process);
2. everything downstream of the tags is deterministic pure-python/numpy
   on identical inputs (no hash-order iteration on data that differs
   per process: the state machine iterates SFC-sorted arrays);
3. scalar diagnostics (dt, umax, residuals) are outputs of global
   reductions — fully replicated across processes by SPMD semantics,
   so plain device_get agrees everywhere.

`tests/test_multihost.py` enforces this with two real jax.distributed
processes: three regrid+step cycles must produce identical topology +
gather-table digests on both, then the run writes a dump and a
checkpoint, restores, and continues identically.

Pod-safe I/O (io.py, the reference's collective MPI-IO dump
main.cpp:3367-3467): dump_forest/save_checkpoint are COLLECTIVE on
pods — every process joins one field all-gather, process 0 alone
writes (to shared storage, MPI-IO's own assumption), and a barrier
keeps the others from racing past an incomplete save. load_checkpoint
reads the same bytes on every process; everything downstream is the
deterministic replicated-host machinery above.
"""

from __future__ import annotations

import sys
import time
from typing import Callable, Optional

import jax

from .mesh import make_mesh


def _dist_initialized() -> bool:
    """Version-safe, public-API-only check that the distributed runtime
    is up (resilience.dist_initialized): the public
    ``jax.distributed.is_initialized`` accessor where the build has it,
    else the latch ``init_distributed`` sets below. Never touches the
    XLA backend, preserving this module's no-probe contract. (The
    former fallback read ``jax._src.distributed.global_state.client``
    — a private attribute that moves between versions.)"""
    from ..resilience import dist_initialized
    return dist_initialized()


def _connect_with_retry(connect: Callable[[], None],
                        attempts: int = 5,
                        backoff: float = 1.0) -> None:
    """Bounded exponential-backoff retry around the coordinator connect.

    ``jax.distributed.initialize`` makes ONE attempt; on a preemptible
    pod the coordinator process routinely comes up seconds after the
    workers (re-scheduled onto a fresh VM), and a single-shot connect
    kills the whole bring-up for a transient. Retries are bounded
    (``attempts``, delays backoff * 2^k) and LOGGED — to stderr and the
    resilience event log — so a flaky fabric is visible, not silent.
    The final failure propagates: a pod run degrading to independent
    single-host runs computes wrong answers with no error."""
    attempts = max(1, int(attempts))
    for attempt in range(1, attempts + 1):
        try:
            return connect()
        except Exception as e:
            if attempt >= attempts:
                raise
            delay = backoff * (2.0 ** (attempt - 1))
            print(f"cup2d_tpu: coordinator connect failed (attempt "
                  f"{attempt}/{attempts}): {e}; retrying in "
                  f"{delay:.1f}s", file=sys.stderr)
            from ..resilience import record_event
            record_event(event="coordinator_retry", attempt=attempt,
                         max_attempts=attempts, delay_s=delay,
                         error=str(e))
            time.sleep(delay)


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     expected_processes: Optional[int] = None,
                     connect_attempts: int = 5,
                     connect_backoff: float = 1.0) -> int:
    """Bring up the JAX distributed runtime for a multi-host run (the
    reference's MPI_Init moment, main.cpp:6307).

    On TPU pods all three arguments autodetect from the environment;
    pass them explicitly for CPU/GPU clusters. Safe to call on
    single-host runs: with nothing to join (no coordinator argument,
    no pod environment) it returns without touching the backend — the
    decision must not probe jax.process_count(), which would initialize
    XLA and make a later initialize() impossible. Init failures (e.g.
    unreachable coordinator) propagate: a pod run silently degrading to
    independent single-host runs computes wrong answers with no error.

    ``expected_processes`` is the belt-and-braces guard against exactly
    that degradation on launchers whose environment the pod heuristics
    don't recognize (ADVICE r2): pass the known world size (e.g. the
    `-mesh-hosts` flag / slurm's SLURM_NPROCS) and the call aborts
    unless that many processes actually joined. Returns this process's
    index.

    The connect itself retries with bounded exponential backoff
    (``connect_attempts`` tries, ``connect_backoff`` * 2^k seconds
    apart, logged) — see :func:`_connect_with_retry`. Both knobs are
    plumbed from the CLI (``-connectAttempts`` / ``-connectBackoff``,
    latched once from argv at this call — never a scattered env read),
    and the elastic re-init path (:func:`reinit_distributed`) takes its
    OWN budget rather than inheriting this first-launch one.
    """
    from ..resilience import note_distributed_initialized
    if _dist_initialized():
        rank = jax.process_index()
    else:
        explicit = (coordinator_address is not None
                    or num_processes is not None)
        if not explicit and not _in_tpu_pod():
            if expected_processes and expected_processes > 1:
                raise RuntimeError(
                    f"expected {expected_processes} processes but no "
                    "pod environment was detected and no coordinator "
                    "was given — refusing to run single-host silently")
            return 0
        _connect_with_retry(
            lambda: jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id),
            attempts=connect_attempts, backoff=connect_backoff)
        note_distributed_initialized()   # version-safe probe latch
        rank = jax.process_index()
    if expected_processes and jax.process_count() != expected_processes:
        raise RuntimeError(
            f"distributed runtime has {jax.process_count()} processes, "
            f"expected {expected_processes} — partial pod bring-up")
    return rank


def reinit_distributed(coordinator_address: str,
                       num_processes: int,
                       process_id: int,
                       connect_attempts: int = 10,
                       connect_backoff: float = 0.5) -> int:
    """Tear down and re-initialize the distributed runtime over the
    SURVIVOR world after a topology loss (resilience.TopologyGuard) —
    the runtime half of elastic recovery: once a peer is gone, every
    collective of the OLD world hangs, so the survivors must agree on a
    new (smaller) world before the re-meshed step can run.

    The connect budget is deliberately separate from
    :func:`init_distributed`'s first-launch one: a re-init races only
    the other survivors (already up, already agreed on the new world
    from the same beat evidence), so it wants more attempts at shorter
    backoff than a cold pod bring-up waiting on a scheduler. The
    coordinator address must name a SURVIVOR (by the determinism rule
    the new process 0 — survivors renumber by rank order), on a fresh
    port: the old coordinator service may be gone, or its port still
    parked in TIME_WAIT.

    Exercised by the slow-marked 2-process drill
    (tests/_multihost_worker.py); environment-broken in this container
    like the rest of the multi-process harness (ROADMAP)."""
    from ..resilience import note_distributed_initialized, record_event
    if _dist_initialized():
        try:
            jax.distributed.shutdown()
        except Exception as e:
            # a shutdown against a world with a dead member can itself
            # fail — log and proceed: initialize() below is the
            # authority on whether the new world comes up
            print(f"cup2d_tpu: distributed shutdown during re-init "
                  f"failed: {e}", file=sys.stderr)
            record_event(event="reinit_shutdown_failed", error=str(e))
    _connect_with_retry(
        lambda: jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id),
        attempts=connect_attempts, backoff=connect_backoff)
    note_distributed_initialized()
    record_event(event="reinit_distributed",
                 num_processes=num_processes, process_id=process_id)
    return jax.process_index()


def _in_tpu_pod() -> bool:
    """True when this process is one worker of a multi-host TPU slice
    (the autodetection case for jax.distributed.initialize). A
    single-entry TPU_WORKER_HOSTNAMES means a single-host slice — the
    runtime also sets it there, so only a multi-hostname list counts.
    Several launcher generations are covered (ADVICE r2: relying on one
    env var silently degrades on the others): classic TPU_WORKER_
    HOSTNAMES, megascale coordinators, and GKE/queued-resource runtimes
    that export per-worker ids with a >1 worker count."""
    import os
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if "," in hosts:
        return True
    if os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"):
        return True
    for nvar in ("TPU_WORKER_COUNT", "NUM_TPU_WORKERS",
                 "CLOUD_TPU_NUM_WORKERS"):
        try:
            if int(os.environ.get(nvar, "1")) > 1:
                return True
        except ValueError:
            pass
    return False


def global_mesh():
    """1-D mesh over every addressable chip of every host, in process
    order — contiguous SFC/x ranges per host, so halo traffic between
    chips of one host rides ICI and only the two range boundaries per
    host cross DCN (the layout rule from the scaling playbook: shard
    the contiguous spatial axis over the slowest network last)."""
    return make_mesh(devices=jax.devices())
