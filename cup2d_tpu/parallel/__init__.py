"""Distributed execution: device meshes, shardings, halo exchange.

TPU-native replacement for the reference's MPI layer (SURVEY.md §2.2,
§2.8): spatial domain decomposition becomes `jax.sharding.NamedSharding`
over a `Mesh`, halo traffic becomes XLA collective-permutes inserted by
GSPMD (or explicit `lax.ppermute` in the shard_map path), and the ~40
MPI_Allreduce call sites become `psum`/`pmax` reductions that XLA places
on ICI.
"""

from .mesh import (  # noqa: F401
    make_mesh,
    scalar_spec,
    vector_spec,
    shard_state,
    ShardedUniformSim,
)
