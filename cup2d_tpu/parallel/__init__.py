"""Distributed execution: device meshes, shardings, halo exchange.

TPU-native replacement for the reference's MPI layer (SURVEY.md §2.2,
§2.8): spatial domain decomposition becomes `jax.sharding.NamedSharding`
over a `Mesh`; GSPMD inserts the halo collective-permutes for shifted
stencil reads and turns the ~40 MPI_Allreduce call sites into
cross-device all-reduces placed on ICI.
"""

from .mesh import (  # noqa: F401
    make_mesh,
    scalar_spec,
    vector_spec,
    shard_state,
    ShardedUniformSim,
)
from .forest_mesh import ShardedAMRSim  # noqa: F401
from .launch import global_mesh, init_distributed  # noqa: F401
