"""Device mesh + sharded uniform-grid execution.

The reference decomposes space over MPI ranks along the SFC and hand-plans
point-to-point halo messages (`/root/reference/main.cpp:909-2142`). The
TPU-native equivalent is declarative: fields carry a `NamedSharding` that
splits the x-axis of the domain across the mesh, and XLA's SPMD partitioner
inserts the halo collective-permutes for every shifted-slice stencil read,
plus `all-reduce`s for the dt/residual reductions — the entire §2.2 comm
runtime of the reference collapses into sharding annotations.

The mesh axis is named ``"x"``: for a 2-D incompressible flow the natural
"data-parallel" axis is space itself (SURVEY.md §2.8 — spatial domain
decomposition is this code's DP; there is no batch/tensor/pipeline axis in
a single simulation). Multi-host TPU slices extend the same mesh over DCN
transparently.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import SimConfig
from ..uniform import FlowState, UniformSim


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    """1-D device mesh over the spatial x-axis.

    On a real v5e-8 slice this is the 8-chip ICI ring; in tests it is the
    CPU-forced virtual device set (conftest.py).
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), ("x",))


def scalar_spec() -> P:
    """[Ny, Nx] fields: split columns across the mesh."""
    return P(None, "x")


def vector_spec() -> P:
    """[2, Ny, Nx] fields."""
    return P(None, None, "x")


def shard_state(state: FlowState, mesh: Mesh) -> FlowState:
    """Place a FlowState with x-split shardings on the mesh."""
    sv = NamedSharding(mesh, vector_spec())
    ss = NamedSharding(mesh, scalar_spec())
    return FlowState(
        vel=jax.device_put(state.vel, sv),
        pres=jax.device_put(state.pres, ss),
        chi=jax.device_put(state.chi, ss),
        us=jax.device_put(state.us, sv),
        udef=jax.device_put(state.udef, sv),
    )


class ShardedUniformSim(UniformSim):
    """Uniform-grid solver executing SPMD over a device mesh.

    Same numerics and driver loop as `UniformSim`; the only difference is
    placement: the state lives x-split across devices and the jitted step
    is compiled with those shardings, so stencil halos ride ICI
    collective-permutes and reductions are cross-device all-reduces —
    the reference's `sync1` + `MPI_Allreduce` pattern with zero
    hand-written communication code.
    """

    def __init__(self, cfg: SimConfig, mesh: Mesh,
                 level: Optional[int] = None, bc=None):
        # spmd_safe: the sharded axes go through the GSPMD partitioner,
        # which miscompiles the fast pad+slice zero-shift form
        # (ops/stencil._zshift)
        super().__init__(cfg, level, spmd_safe=True, bc=bc)
        self._bind_mesh(mesh)

    def _bind_mesh(self, mesh: Mesh) -> None:
        """Point every mesh-derived artifact at ``mesh`` and rebuild
        the step executable: shared by construction and by the elastic
        :meth:`remesh`."""
        if self.grid.nx % mesh.devices.size != 0:
            raise ValueError(
                f"Nx={self.grid.nx} not divisible by mesh size "
                f"{mesh.devices.size}"
            )
        self.mesh = mesh
        # Point the grid at the mesh BEFORE the step re-jit below so
        # the compiled step captures the mesh-aware forms: the fused
        # advection tier (CUP2D_PALLAS=1) dispatches through the
        # halo-mode megakernel (shard_halo.fused_advect_heun_sharded,
        # edge-column ppermutes issued before the strip pipeline), and
        # the FAS solve path (CUP2D_POIS=fas) rebuilds its MG
        # hierarchy so the finest-level smoothing sweeps run the
        # comm/compute-overlapped shard_map form
        # (shard_halo.overlap_jacobi_sweeps) instead of leaving the
        # halo schedule to GSPMD.
        self.grid.attach_mesh(mesh)
        state_shardings = FlowState(
            vel=NamedSharding(mesh, vector_spec()),
            pres=NamedSharding(mesh, scalar_spec()),
            chi=NamedSharding(mesh, scalar_spec()),
            us=NamedSharding(mesh, vector_spec()),
            udef=NamedSharding(mesh, vector_spec()),
        )
        self.state = shard_state(self.state, mesh)
        self._step = jax.jit(
            self.grid.step,
            donate_argnums=(0,),
            static_argnames=("exact_poisson", "obstacle_terms"),
            out_shardings=(state_shardings, None),
        )

    def remesh(self, mesh: Mesh) -> None:
        """Elastic re-mesh (resilience.StepGuard.elastic_recover):
        rebuild placement + the step executable over a new — typically
        shrunk — device set, in place, without relaunch. The current
        state is re-placed onto the new mesh (an XLA reshard); the
        elastic path immediately overwrites it from the snapshot ring /
        disk checkpoint, so its value never matters there. Cached
        device scalars (the async drivers' ``_next_dt``) are re-placed
        too — a replicated scalar pinned to a LOST device must not leak
        into the rebuilt executable's argument stream.

        Real-loss guard: when the current state's shards are no longer
        fully addressable (a peer process died and took them — the
        disk-rung path), re-sharding would try to READ them; the state
        is zeroed instead, since the restore that follows overwrites it
        wholesale."""
        if not all(getattr(v, "is_fully_addressable", True)
                   for v in self.state):
            self.state = self.grid.zero_state()
            self._next_dt = None
        self._bind_mesh(mesh)
        if isinstance(self._next_dt, jax.Array):
            self._next_dt = jax.device_put(
                self._next_dt, NamedSharding(mesh, P()))

    def set_state(self, state: FlowState):
        self.state = shard_state(state, self.mesh)


# ---------------------------------------------------------------------------
# host-ring mirror exchange (the host-redundant snapshot tier, io.py):
# one collective that sends every host's contiguous shard block to its
# ring neighbor. Same machinery class as the shard_halo surface
# exchange — a shard_map body issuing a single lax.ppermute over sparse
# (src, dst) pairs — but host-granular: with D devices grouped into H
# contiguous simulated/real hosts (D/H devices each), device i sends
# its whole shard to device (i + D/H) % D, so host h's x-columns land
# physically on host h+1. Globally the result is exactly
# roll(x, +Nx/H, axis=-1); the restore side (io.py) relies on that
# identity to realign the mirror.
# ---------------------------------------------------------------------------

try:                                   # stable API (jax >= 0.5)
    from jax import shard_map as _shard_map
except ImportError:                    # this image's 0.4.x line
    from jax.experimental.shard_map import shard_map as _shard_map

# executable cache: one compiled shift per (mesh, host count, rank) —
# the capture path runs per snapshot, so the jit must be reused, never
# rebuilt (a fresh lambda per call would recompile every capture)
_RING_SHIFT_CACHE: dict = {}


def host_ring_shift(x, mesh: Mesh, n_hosts: int):
    """Ring-neighbor mirror of an x-split array: each host's contiguous
    column block moves one host to the right (wrapping), as a single
    per-device ``lax.ppermute``. The output is a FRESH buffer with the
    input's sharding — donation-safe for the snapshot ring by the same
    stream-order argument as :func:`cup2d_tpu.io.device_copy` (the
    permute is enqueued before the next step's jit donates its
    sources). Pure device collective: zero host transfers."""
    n_dev = mesh.devices.size
    if n_hosts < 2 or n_dev % n_hosts != 0:
        raise ValueError(
            f"host ring needs >=2 hosts dividing the mesh "
            f"(n_hosts={n_hosts}, devices={n_dev})")
    key = (mesh, int(n_hosts), x.ndim)
    fn = _RING_SHIFT_CACHE.get(key)
    if fn is None:
        dph = n_dev // n_hosts
        perm = [(i, (i + dph) % n_dev) for i in range(n_dev)]
        spec = P(*([None] * (x.ndim - 1) + ["x"]))

        def _shift(s):
            return jax.lax.ppermute(s, "x", perm=perm)

        fn = jax.jit(_shard_map(_shift, mesh=mesh,
                                in_specs=(spec,), out_specs=spec))
        _RING_SHIFT_CACHE[key] = fn
    return fn(x)
