"""Multi-device execution of the adaptive forest.

The reference partitions SFC-ordered blocks into contiguous per-rank
ranges and hand-plans P2P halo messages + migration transfers
(`/root/reference/main.cpp:5205-5424` load balance, `909-2142` comm).
The TPU-native equivalent keeps the same *policy* — the ordered block
axis is split into contiguous SFC ranges, one per device — with XLA
GSPMD as the *mechanism*: the forest's dense per-block arrays carry a
`NamedSharding` over a 1-D mesh, sharding constraints pin the advected
velocity and the projection input to the block axis (the partitioner
propagates that placement through the lab assembly and the Krylov loop),
and GSPMD inserts the gather/scatter collectives for ghost rows that
cross shard boundaries plus all-reduces for the dt/residual scalars.

Regrid-time "migration" (the reference's MPI_Block transfers) is just
re-placement: `_refresh` re-device_puts the rebuilt tables and the
fields after every topology change, so each device again owns an equal
contiguous range of the new SFC order. Because the ordered axis is
padded to power-of-two buckets (amr._refresh), the per-device range
sizes are always equal and the compiled step is reused across regrids.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..amr import AMRSim
from ..config import SimConfig


class ShardedAMRSim(AMRSim):
    """AMRSim whose block axis is sharded over a device mesh.

    Same numerics and host driver; only placement differs. The mesh axis
    is named "x" like the uniform path (parallel/mesh.py) — for a
    spatial solver the data-parallel axis is space itself, here in
    SFC-block units rather than grid columns.
    """

    def __init__(self, cfg: SimConfig, mesh: Mesh,
                 shapes: Optional[Sequence] = None):
        self.mesh = mesh
        super().__init__(cfg, shapes=shapes)

    def _shard_blocks(self, x):
        """Pin an array's leading (ordered-block) axis to the mesh."""
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, P("x")))

    def _refresh(self):
        f = self.forest
        if self._tables_version == f.version:
            return
        super()._refresh()
        shard = NamedSharding(self.mesh, P("x"))
        repl = NamedSharding(self.mesh, P())
        # fields: shard the slot axis (capacity is a power-of-two-ish
        # multiple of the mesh size); compact per-block arrays: shard
        # the padded ordered axis (n_pad is a power of two >= 128)
        for name, fld in f.fields.items():
            f.fields[name] = jax.device_put(fld, shard)
        self._h = jax.device_put(self._h, shard)
        self._h3 = jax.device_put(self._h3, shard)
        self._hflat = jax.device_put(self._hflat, shard)
        self._hsq_flat = jax.device_put(self._hsq_flat, shard)
        self._maskv = jax.device_put(self._maskv, shard)
        self._xc = jax.device_put(self._xc, shard)
        self._yc = jax.device_put(self._yc, shard)
        self._order_j = jax.device_put(self._order_j, shard)
        # gather tables are index metadata: replicated, like the
        # reference replicating its synchronizer plans per rank
        self._tables = {k: jax.device_put(t, repl)
                        for k, t in self._tables.items()}
        self._corr = jax.device_put(self._corr, repl)

    # -- sharding constraints inside the jitted stages -----------------
    def _advect_rk2(self, vel, order, h, dt, t3, corr, maskv):
        v = super()._advect_rk2(vel, order, h, dt, t3, corr, maskv)
        return self._shard_blocks(v)

    def _pressure_project(self, vel, v, pres, dt, order, h, hsq,
                          t1v, t1s, tpois, corr, exact_poisson, maskv,
                          chi=None, udef_b=None):
        v = self._shard_blocks(v)
        return super()._pressure_project(
            vel, v, pres, dt, order, h, hsq, t1v, t1s, tpois, corr,
            exact_poisson, maskv, chi=chi, udef_b=udef_b)
