"""Multi-device execution of the adaptive forest.

The reference partitions SFC-ordered blocks into contiguous per-rank
ranges and hand-plans P2P halo messages + migration transfers
(`/root/reference/main.cpp:5205-5424` load balance, `909-2142` comm).
The TPU-native equivalent keeps the same *policy* — the ordered block
axis is split into contiguous SFC ranges, one per device — with XLA
GSPMD as the *mechanism*: the forest's dense per-block arrays carry a
`NamedSharding` over a 1-D mesh, sharding constraints pin the advected
velocity and the projection input to the block axis (the partitioner
propagates that placement through the lab assembly and the Krylov loop),
and GSPMD inserts the gather/scatter collectives for ghost rows that
cross shard boundaries plus all-reduces for the dt/residual scalars.

Regrid-time "migration" (the reference's MPI_Block transfers) is just
re-placement: `_refresh` re-device_puts the rebuilt tables and the
fields after every topology change, so each device again owns an equal
contiguous range of the new SFC order. Because the ordered axis is
padded to power-of-two buckets (amr._refresh), the per-device range
sizes are always equal and the compiled step is reused across regrids.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..amr import AMRSim
from ..config import SimConfig
from .shard_halo import _shard_map


def _exchange_mode() -> str:
    """Surface-exchange mode from the environment. Read ONCE per sim
    (ShardedAMRSim.__init__) and stored on the instance: the halo
    gather and the flux-correction deposit exchange are finalized in
    separate calls within one _refresh, and an env mutation between
    them must not build the two in different modes (ADVICE r4)."""
    import os
    return os.environ.get("CUP2D_SHARD_EXCHANGE", "ppermute")


class ShardedAMRSim(AMRSim):
    """AMRSim whose block axis is sharded over a device mesh.

    Same numerics and host driver; only placement differs. The mesh axis
    is named "x" like the uniform path (parallel/mesh.py) — for a
    spatial solver the data-parallel axis is space itself, here in
    SFC-block units rather than grid columns.
    """

    def __init__(self, cfg: SimConfig, mesh: Mesh,
                 shapes: Optional[Sequence] = None):
        self.mesh = mesh
        self._exchange = _exchange_mode()
        super().__init__(cfg, shapes=shapes)

    def _shard_blocks(self, x):
        """Pin an array's leading (ordered-block) axis to the mesh."""
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, P("x")))

    def remesh(self, mesh: Mesh) -> None:
        """Elastic re-mesh (resilience.StepGuard.elastic_recover):
        re-partition the SFC block ranges over a new — typically
        shrunk — device set, in place. The block partition is
        device-count-parametric by construction (each device owns an
        equal contiguous range of the padded ordered axis), so the
        re-mesh is a table rebuild + re-placement, no topology change:
        ``_refresh`` is forced even though ``forest.version`` did not
        move, which rebuilds every per-device table plan / exchange
        plan / Poisson operator against the new mesh (falling back to
        replicated tables when the survivor count no longer divides
        the pad bucket — the same degradation rule as construction),
        re-places the slot fields, and refreshes the comm-volume
        telemetry. The jitted stages retrace automatically: the table
        pytrees carry the mesh as static aux data (shard_halo), so a
        new mesh is a new cache key. The ordered working state — the
        hot-loop truth — is re-placed last.

        Real-loss guard: field shards a dead peer took with it cannot
        be re-placed (the device_put would read them) — they are
        zeroed first; the disk restore that follows a non-covering
        loss overwrites them wholesale, and the ordered-state cache is
        dropped with them."""
        f = self.forest
        if not all(getattr(v, "is_fully_addressable", True)
                   for v in f.fields.values()):
            for name, fld in list(f.fields.items()):
                f.fields[name] = jnp.zeros(fld.shape, fld.dtype)
            self._ord = None
            self._ord_dirty = False
            self._ord_key = None
        self.mesh = mesh
        self._tables_version = -1    # force the rebuild path
        self._refresh()
        if self._ord is not None:
            self._ord = {k: self._put_ordered(v)
                         for k, v in self._ord.items()}
            # re-anchor at the post-re-placement write version: the
            # device_puts above and in _refresh bump fields.wver, but a
            # PLACEMENT move is not a semantic field write — without
            # the re-anchor the next _ordered_state() would take the
            # wver-moved branch, re-gather from the (possibly stale)
            # slot fields and drop the dt cache. _ord_dirty is kept
            # as-is: whether the slots are stale is unchanged by where
            # the ordered arrays live.
            self._ord_key = (self.forest.version, self.forest.fields.wver)

    def _refresh(self):
        f = self.forest
        if self._tables_version == f.version:
            return
        super()._refresh()
        shard = NamedSharding(self.mesh, P("x"))
        # fields: shard the slot axis (capacity is a power-of-two-ish
        # multiple of the mesh size); compact per-block arrays: shard
        # the padded ordered axis (n_pad is a power of two >= 128).
        # Hot-loop gather tables are split per device by the
        # _finalize_tables/_finalize_corr hooks below.
        for name, fld in f.fields.items():
            f.fields[name] = jax.device_put(fld, shard)
        self._h = jax.device_put(self._h, shard)
        self._h3 = jax.device_put(self._h3, shard)
        self._hflat = jax.device_put(self._hflat, shard)
        self._hsq_flat = jax.device_put(self._hsq_flat, shard)
        self._maskv = jax.device_put(self._maskv, shard)
        self._xc = jax.device_put(self._xc, shard)
        self._yc = jax.device_put(self._yc, shard)
        self._order_j = jax.device_put(self._order_j, shard)

    def _finalize_tables(self, raw, n_pad, fc=None):
        """Hot-loop table sets become per-device rows + a surface
        exchange plan (shard_halo) — the reference's per-rank
        synchronizer plans (main.cpp:909-1391). The regrid prolongation
        sets (vec1t/sca1t) read slot-layout fields outside the sharded
        hot loop and stay replicated. The same-level face-copy fast
        path (``fc``) runs SHARD-LOCALLY: pairs living on one shard are
        painted by structured block-row writes inside shard_map (no
        collective at all), and only the faces that actually cross a
        shard boundary keep gather rows riding the surface exchange —
        the round-5 paint at round-4 communication volume."""
        from .shard_halo import exchange_padding_stats, shard_tables
        if n_pad % self.mesh.devices.size:
            self._comm_stats = None
            return super()._finalize_tables(raw, n_pad, fc=None)
        from ..halo import pad_tables
        repl = NamedSharding(self.mesh, P())
        padded = {k: pad_tables(raw[k], n_pad)
                  for k in ("vec1t", "sca1t") if k in raw}
        out = dict(jax.device_put(padded, repl))
        mode = self._exchange
        for k, t in raw.items():
            if k not in padded:
                kw = {}
                if fc is not None and k in self._FAST_SETS:
                    kw = dict(fc=fc, corners=self._FAST_SETS[k])
                out[k] = shard_tables(t, n_pad, self.mesh, mode=mode,
                                      **kw)
        if "vec3" in raw:
            # comm volume for the telemetry stream: real vs on-the-wire
            # bytes of ONE hot-loop vector exchange under the current
            # plan (host-only audit, rebuilt per regrid like the tables
            # themselves; the same numbers test_comm_volume bounds)
            st = exchange_padding_stats(
                raw["vec3"], n_pad, self.mesh.devices.size, mode=mode)
            blk = 2 * self.cfg.bs * self.cfg.bs \
                * np.dtype(jnp.dtype(self.forest.dtype).name).itemsize
            self._comm_stats = {
                "halo_real_bytes": st["real_blocks"] * blk,
                "halo_padded_bytes": st["padded_blocks"] * blk,
            }
        return out

    def _build_pois(self, topo, n_pad):
        """Sharded Poisson operator: the round-5 structured per-face
        form (flux.build_poisson_structured), split into per-device
        block rows whose neighbor gathers read [own ++ received
        surface] behind the same ppermute exchange plan as the halo
        sets (shard_halo.shard_poisson_op) — one closure signature on
        one device and on eight. CUP2D_POIS=tables restores the
        round-4 lab-table + exchange form for A/B measurements."""
        from ..flux import build_poisson_structured, build_poisson_tables
        from .shard_halo import shard_poisson_op, shard_tables
        if n_pad % self.mesh.devices.size:
            return super()._build_pois(topo, n_pad)
        if self._pois_mode == "tables":
            t = build_poisson_tables(self.forest, self._order, topo=topo)
            return shard_tables(t, n_pad, self.mesh, mode=self._exchange)
        op = build_poisson_structured(self.forest, self._order, n_pad,
                                      topo=topo)
        return shard_poisson_op(op, n_pad, self.mesh,
                                mode=self._exchange)

    def _finalize_corr(self, topo, n_pad):
        from ..flux import build_flux_corr
        from .shard_halo import shard_flux_corr
        if n_pad % self.mesh.devices.size:
            return super()._finalize_corr(topo, n_pad)
        raw = build_flux_corr(self.forest, self._order, topo=topo)
        return shard_flux_corr(
            raw, n_pad, self.mesh, self.cfg.bs,
            dtype=np.dtype(self.forest.dtype),
            mode=self._exchange)

    def _window_raster(self, inp, N):
        """Window rasterization with a shard-local scatter: every device
        evaluates the (small, body-sized) window SDF/udef replicated,
        then keeps only the rows landing in its own block range — no
        collective at all, vs the volume-sized scatter all-reduce GSPMD
        emits for the global form (validation/comm_audit.py)."""
        D = self.mesh.devices.size
        if N % D:
            return super()._window_raster(inp, N)
        from functools import partial

        from ..amr import _raster_neg, _window_sdf_udef
        bs = self.cfg.bs
        dtype = self.forest.dtype
        B = N // D

        @partial(_shard_map, mesh=self.mesh,
                 in_specs=(P(),),
                 out_specs=(P("x"), P(None, "x"), P("x")))
        def win(inp_r):
            d0 = jax.lax.axis_index("x")
            d, ud = _window_sdf_udef(inp_r, bs, dtype)
            pos = inp_r["pos"]
            mine = (pos >= d0 * B) & (pos < (d0 + 1) * B)
            lpos = jnp.where(mine, pos - d0 * B, B)
            wm3 = mine[:, None, None]
            # shared constructor (shard_map must not close over tracers)
            negd = _raster_neg(self.cfg, dtype)
            sdf_k = jnp.full((B + 1, bs, bs), negd, dtype).at[lpos].set(
                jnp.where(wm3, d, negd))[:B]
            udef_k = jnp.zeros(
                (2, B + 1, bs, bs), dtype).at[:, lpos].set(
                jnp.where(wm3[None], ud, 0.0))[:, :B]
            wm_k = jnp.zeros((B + 1,), dtype).at[lpos].set(
                mine.astype(dtype))[:B]
            return sdf_k, udef_k, wm_k

        return win(inp)

    def _put_ordered(self, x):
        """The ordered working state owns the hot loop — place it in
        contiguous SFC ranges over the mesh (the reference's rank
        partition, main.cpp:5205-5330)."""
        return jax.device_put(x, NamedSharding(self.mesh, P("x")))

    def _fas_block_smoother(self, A, tpois):
        """Forest-FAS composite smoother on the mesh: the
        comm/compute-overlapped block-surface sweep
        (shard_halo.overlap_block_jacobi_sweeps) — one shard_map whose
        per-sweep body issues the surface ppermutes first and hides
        them behind the shard-local strip/GEMM work. Termwise
        identical to the parent's per-sweep A + P_inv composition, so
        the sharded == single-device FAS equality rides the same bound
        as every other sharded path. Replicated-table fallback (n_pad
        not divisible by the mesh) keeps the parent form."""
        from ..poisson import apply_block_precond_blocks
        from .shard_halo import ShardPoissonOp, \
            overlap_block_jacobi_sweeps
        if not isinstance(tpois, ShardPoissonOp):
            return super()._fas_block_smoother(A, tpois)
        p_inv = self.p_inv
        # tier threading (ISSUE 19): the Pallas latch routes each
        # shard-local update tail through the fused block-Jacobi pass
        # inside the overlapped shard_map (ppermutes still first).
        tier = "strip" if self._kernel_tier != "xla" else "xla"

        def smooth(e, r, n, from_zero=False):
            if from_zero and n > 0:
                e = self._shard_blocks(
                    apply_block_precond_blocks(r, p_inv))
                n -= 1
            if n > 0:
                e = overlap_block_jacobi_sweeps(e, r, p_inv, tpois, n,
                                                tier=tier)
            return e

        return smooth

    # -- sharding constraints inside the jitted stages -----------------
    def _advect_rk2(self, vel, h, dt, t3, corr, maskv):
        v = super()._advect_rk2(vel, h, dt, t3, corr, maskv)
        return self._shard_blocks(v)

    def _pressure_project(self, v, pres, dt, h, hsq,
                          t1v, t1s, tpois, corr, tcoarse,
                          exact_poisson, maskv,
                          chi=None, udef_b=None):
        v = self._shard_blocks(v)
        return super()._pressure_project(
            v, pres, dt, h, hsq, t1v, t1s, tpois, corr, tcoarse,
            exact_poisson, maskv, chi=chi, udef_b=udef_b)
