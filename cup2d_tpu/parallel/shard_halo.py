"""Shard-local ghost assembly: the explicit halo exchange.

GSPMD lowers `halo.assemble_labs_ordered`'s data-dependent gather from
a block-sharded operand to an **all-gather of the entire field** —
traffic proportional to shard volume, several times per step and once
per Krylov iteration (measured: validation/comm_audit.py). The
reference's comm layer exists precisely to avoid that: it ships only
halo slabs between neighbor ranks (/root/reference/main.cpp:909-2142,
Setup/sync1), so per-rank traffic scales with the shard *boundary*.

This module restores that scaling law on the device mesh:

* gather tables are split per device — rows whose destination block
  lives in shard d become d's rows, with every gather source remapped
  into a local index space = [d's own B blocks] ++ [an all-gathered
  SURFACE buffer];
* the surface buffer packs only blocks some OTHER shard references —
  the shard-boundary halo (SFC-contiguous shards keep it thin, the
  same locality argument as the reference's SFC rank ranges);
* assembly runs under `shard_map`: pack own surface blocks, one
  `lax.ppermute` per shard offset over the sparse pairs that actually
  send (or one mesh-wide surface all-gather in audit mode), then purely
  local gathers/scatters — including the shard-local FastHalo paint of
  same-level strips whose neighbor lives on the same shard.

The flux-correction fix-up (fine-face deposits added into coarse rows,
main.cpp:1392-1849) gets the identical treatment with face-deposit rows
as the exchanged payload, and the structured per-face Poisson operator
(flux.PoissonOp) rides the same plan with neighbor block rows as the
payload (ShardPoissonOp).

Per-device row counts and surface sizes are padded to power-of-two
buckets so regrids reuse compiled executables (same rationale as
halo.pad_tables); surface buckets are per offset so pod-scale meshes
don't pay the worst pair's bucket on every pair.

Elastic re-mesh contract (PR 7): every plan here is a pure function of
(raw host tables, n_pad, mesh), and the table pytrees carry the mesh —
with every mesh-derived static (offsets, perms, B, S) — as STATIC aux
data (the register_pytree_node below). A survivor re-mesh after a
topology loss (forest_mesh.ShardedAMRSim.remesh) therefore just
rebuilds the plans against the shrunk mesh and the jitted stages
retrace on the new treedef — no stale-mesh executable can ever be
reused, by construction. When the survivor count no longer divides the
pad bucket, the callers fall back to replicated tables exactly as they
would at construction.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..halo import HaloTables, _bucket, _paint_regions, filter_face_rows

try:                                   # stable API (jax >= 0.5)
    from jax import shard_map as _shard_map
except ImportError:                    # this image's 0.4.x line
    from jax.experimental.shard_map import shard_map as _shard_map


class ShardTables(NamedTuple):
    """Per-device halo tables (leaves stacked [D, ...], sharded on the
    mesh axis so each device reads only its own rows inside shard_map).

    Index spaces (per device d owning ordered blocks [dB, dB+B)):
      gather sources: flat cells of [B own blocks ++ received surface
                      blocks (per-offset ppermute buckets, or the D*S
                      all-gather buffer in mode="allgather")]
      scatter dests:  flat cells of [B labs] ++ 1 trailing scratch cell
                      (pad rows write zeros there; dropped on return)

    Neighbor-wise exchange (default): ``offsets`` lists the nonzero
    consumer-minus-owner shard distances that actually occur;
    ``pack[o]`` holds, per owner device, the own-block indices to send
    to owner+offsets[o] (one ``lax.ppermute`` per offset). Per-device
    traffic is sum_o S_o — the device's own shard boundary — instead of
    the all-gather's D*S_max (the GLOBAL boundary), restoring the
    reference's per-neighbor-send scaling law (main.cpp:1971-2142).
    SFC-contiguous shards keep the offset set small (almost always
    {-1, +1}).

    Rows are SPLIT by surface dependence (comm/compute overlap,
    VERDICT r3 missing #3): the *_l row sets read only the device's own
    x_loc and are scattered while the surface exchange is still in
    flight; the *_r sets (every row with at least one remote source)
    consume the received buffer afterwards. The reference overlaps the
    same way — inner blocks compute while halo messages fly
    (main.cpp:864-893 avail_next + computeA 3024-3061).

    Shard-local FastHalo paint (round-5 fast path on the mesh): when
    built with the face-copy structure, ``fc_nb``/``fc_mask`` carry,
    per device and per neighbor offset, the OWN-shard same-level
    neighbor of each own block; the assembly paints those strips with
    structured block-row gathers + static-slice writes (halo._fast_paint
    on the shard), and the covered rows are filtered out of the tables
    host-side (halo.filter_face_rows). Only faces whose same-level
    neighbor lives on ANOTHER shard keep their gather rows — they ride
    the surface exchange like any remote row. The paint reads x_loc
    only, so it is part of the exchange-independent work the scheduler
    can hide the collective behind. ``n_regions`` is 0 (no paint), 4
    (faces only) or 8 (tensorial sets paint corners too).
    """

    pack: tuple           # per-offset [D, S_o] int32 own blocks to export
    src_l: jnp.ndarray    # [D, Gsl] int32 (local-only simple rows)
    sign_l: jnp.ndarray   # [D, Gsl, dim]
    dest_sl: jnp.ndarray  # [D, Gsl] int32
    idx_l: jnp.ndarray    # [D, Ggl, K] int32 (local-only general rows)
    w_l: jnp.ndarray      # [D, Ggl, K, dim]
    dest_l: jnp.ndarray   # [D, Ggl] int32
    src_r: jnp.ndarray    # [D, Gsr] int32 (surface-dependent rows)
    sign_r: jnp.ndarray   # [D, Gsr, dim]
    dest_sr: jnp.ndarray  # [D, Gsr] int32
    idx_r: jnp.ndarray    # [D, Ggr, K] int32
    w_r: jnp.ndarray      # [D, Ggr, K, dim]
    dest_r: jnp.ndarray   # [D, Ggr] int32
    fc_nb: jnp.ndarray    # [D, n_regions, B] int32 own-shard positions
    fc_mask: jnp.ndarray  # [D, n_regions, B] field-dtype 1.0/0.0
    mesh: Mesh
    B: int                # blocks per device
    S: int                # WORST per-offset surface bucket (metadata)
    L: int
    g: int
    dim: int
    offsets: tuple        # static nonzero shard offsets (ppermute mode)
    mode: str             # "ppermute" | "allgather"
    n_regions: int        # 0 = no paint, 4 = faces, 8 = faces+corners
    perms: tuple          # per-offset static (src, dst) sending pairs

    def assemble(self, x: jnp.ndarray) -> jnp.ndarray:
        return _assemble_sharded(x, self)


jax.tree_util.register_pytree_node(
    ShardTables,
    lambda t: ((t.pack, t.src_l, t.sign_l, t.dest_sl, t.idx_l, t.w_l,
                t.dest_l, t.src_r, t.sign_r, t.dest_sr, t.idx_r, t.w_r,
                t.dest_r, t.fc_nb, t.fc_mask),
               (t.mesh, t.B, t.S, t.L, t.g, t.dim, t.offsets, t.mode,
                t.n_regions, t.perms)),
    lambda aux, ch: ShardTables(*ch, *aux),
)


def _build_exchange_plan(remote_by_d, D: int, B: int, n_pad: int,
                         mode: str):
    """Common surface-exchange plan from the per-consumer remote-block
    sets: returns (offsets, S, pack, perms, g2surf) where ``pack`` is a
    TUPLE of per-offset [D, S_o] own-block index arrays (one element
    [D, S] in allgather mode), ``perms`` the per-offset static
    (src, dst) pair lists restricted to pairs that actually send, and
    g2surf[d, gblk] the position of remote block gblk in consumer d's
    received-surface space (-1 if not received). Shared by the halo
    gather, the flux-correction deposit exchange and the structured
    Poisson operator so the plans can never drift (code-review r4).

    Buckets are PER OFFSET and the ppermute perm lists are sparse
    (round 6, VERDICT r5 weak #5): the earlier plan shipped one shared
    power-of-two bucket for every (device, offset) pair, so pod-scale
    meshes — whose SFC shard adjacency has many rare offsets with 1-2
    blocks each — paid bucket-width wire traffic on all of them
    (measured 2.64 -> 4.05 MB/device over 8 -> 64 devices at 1e4
    blocks; 36x padded/real on the 16x16 probe). Per-offset buckets +
    real-pair perms keep padded bytes within a small factor of the
    payload at any device count (tests/test_comm_volume.py bounds it).
    ``S`` stays the WORST per-offset bucket — the shape metadata the
    boundary-proportionality tests key on."""
    if mode == "allgather":
        # one shared surface set per owner, broadcast to every device
        surf_lists: list[list[int]] = [[] for _ in range(D)]
        surf_pos: dict[int, int] = {}
        for d in range(D):
            for gblk in remote_by_d[d].tolist():
                if gblk not in surf_pos:
                    surf_pos[gblk] = len(surf_lists[gblk // B])
                    surf_lists[gblk // B].append(gblk)
        S = _bucket(max((len(x) for x in surf_lists), default=1), lo=4)
        pack0 = np.zeros((D, S), np.int32)
        for e, lst in enumerate(surf_lists):
            pack0[e, :len(lst)] = np.asarray(lst, np.int64) - e * B
        g2surf = np.full((D, n_pad), -1, np.int64)
        for gblk, p in surf_pos.items():
            g2surf[:, gblk] = (gblk // B) * S + p
        return (), S, (pack0,), (), g2surf
    # per (owner, offset) send lists; offset = consumer - owner
    send: dict = {}
    for d in range(D):
        for gblk in remote_by_d[d].tolist():
            e = gblk // B
            send.setdefault((e, d - e), []).append(gblk)
    offsets = tuple(sorted({o for (_, o) in send}))
    S_per = [_bucket(max((len(v) for (e, o), v in send.items()
                          if o == off), default=1), lo=4)
             for off in offsets]
    off_base = np.concatenate([[0], np.cumsum(S_per)]).astype(np.int64)
    pack = tuple(np.zeros((D, s), np.int32) for s in S_per)
    perms = tuple(
        tuple(sorted(e for (e, o) in send if o == off))
        for off in offsets)
    g2surf = np.full((D, n_pad), -1, np.int64)
    for (e, o), lst in send.items():
        oi = offsets.index(o)
        pack[oi][e, :len(lst)] = np.asarray(lst, np.int64) - e * B
        for p, gblk in enumerate(lst):
            g2surf[e + o, gblk] = off_base[oi] + p
    perms = tuple(tuple((e, e + offsets[oi]) for e in srcs)
                  for oi, srcs in enumerate(perms))
    return offsets, max(S_per, default=0), pack, perms, g2surf


def _halo_remote_by_d(t: HaloTables, n_pad: int, D: int):
    """Per-consumer-device remote-block demand of a halo table set —
    the ONE derivation behind both the shipped exchange plan
    (shard_tables) and the host-only padding audit
    (exchange_padding_stats), so the CI padding guard can never audit
    a different plan than the one in production. Also returns the
    derived row/device arrays so shard_tables reuses them instead of
    recomputing per regrid: (remote_by_d, zmask, dev_s, dev_g,
    src_blk, idx_blk)."""
    B = n_pad // D
    bs = t.L - 2 * t.g
    bs2 = bs * bs
    LL = t.L * t.L
    src = np.asarray(t.src_ord, np.int64)
    idx = np.asarray(t.idx_ord, np.int64)
    # zero-weight K-padding entries must not create surface demand
    zmask = (np.asarray(t.w) == 0).all(axis=2)
    dev_s = (np.asarray(t.dest_s, np.int64) // LL) // B
    dev_g = (np.asarray(t.dest, np.int64) // LL) // B
    src_blk = src // bs2
    idx_blk = idx // bs2
    remote_by_d = []
    for d in range(D):
        ref = np.concatenate([
            src_blk[dev_s == d],
            idx_blk[dev_g == d][~zmask[dev_g == d]],
        ])
        remote_by_d.append(
            np.unique(ref[(ref < d * B) | (ref >= (d + 1) * B)]))
    return remote_by_d, zmask, dev_s, dev_g, src_blk, idx_blk


def shard_tables(t: HaloTables, n_pad: int, mesh: Mesh,
                 mode: str = "ppermute", fc=None,
                 corners: bool = True) -> ShardTables:
    """Split (unpadded, numpy-leaf) tables into per-device rows with a
    surface-buffer exchange plan. ``n_pad`` must divide by the mesh
    size (amr buckets are powers of two >= 128).

    mode="ppermute" (default): per-offset neighbor sends; traffic per
    device scales with its OWN shard boundary. mode="allgather": the
    round-3 mesh-wide surface all-gather, kept for the comm-scaling
    audit (validation/comm_audit.py measures both).

    ``fc`` = (nb, mask) from halo.build_face_copy enables the
    shard-local FastHalo paint: the global same-level face-copy mask is
    restricted to pairs living on the SAME shard, the covered rows are
    dropped from the tables (halo.filter_face_rows), and the per-device
    neighbor positions ride along for the structured strip writes.
    Cross-shard same-level faces keep their gather rows (they need the
    surface exchange anyway). ``corners`` follows the table set's
    tensoriality exactly as on the single-device path."""
    D = mesh.devices.size
    assert n_pad % D == 0, (n_pad, D)
    B = n_pad // D
    L, g, dim = t.L, t.g, t.dim
    bs = L - 2 * g
    bs2 = bs * bs
    LL = L * L

    n_regions = 0
    if fc is not None:
        nb_g, mask_g = np.asarray(fc[0]), np.asarray(fc[1])
        n_regions = 8 if corners else 4
        # paint only pairs whose blocks share a shard; nb rows of
        # masked-out entries are dead (gathered then zeroed), so 0 is a
        # safe in-range index
        own_dev = np.arange(n_pad, dtype=np.int64) // B
        same_shard = (nb_g.astype(np.int64) // B) == own_dev[None, :]
        mask_loc = np.where(same_shard, mask_g, 0)
        t = filter_face_rows(t, mask_loc, corners)
        fc_nb_ = np.where(mask_loc > 0, nb_g - (own_dev * B)[None, :],
                          0).astype(np.int32)
        fc_nb_ = fc_nb_[:n_regions].T.reshape(D, B, n_regions) \
            .transpose(0, 2, 1).copy()
        fc_mask_ = mask_loc[:n_regions].T.reshape(D, B, n_regions) \
            .transpose(0, 2, 1).copy()
    else:
        fdt = np.asarray(t.sign).dtype
        fc_nb_ = np.zeros((D, 0, B), np.int32)
        fc_mask_ = np.zeros((D, 0, B), fdt)

    dest_s = np.asarray(t.dest_s, np.int64)
    src = np.asarray(t.src_ord, np.int64)
    sign = np.asarray(t.sign)
    dest = np.asarray(t.dest, np.int64)
    idx = np.asarray(t.idx_ord, np.int64)
    w = np.asarray(t.w)
    K = idx.shape[1]

    # remote blocks referenced by each consumer device, plus the
    # derived row/device arrays (the shared derivation — the padding
    # audit reads the same one)
    (remote_by_d, zmask, dev_s, dev_g,
     src_blk, idx_blk) = _halo_remote_by_d(t, n_pad, D)

    offsets, S, pack, perms, g2surf = _build_exchange_plan(
        remote_by_d, D, B, n_pad, mode)

    def remap_cells(cells, d, dead_local=None):
        blk = cells // bs2
        off = cells % bs2
        local = (blk >= d * B) & (blk < (d + 1) * B)
        sidx = g2surf[d, np.clip(blk, 0, n_pad - 1)]
        out = np.where(local, (blk - d * B) * bs2 + off,
                       (B + sidx) * bs2 + off)
        if dead_local is not None:
            out = np.where(dead_local, 0, out)
        bad = (~local) & (sidx < 0)
        if dead_local is not None:
            bad &= ~dead_local
        assert not bad.any(), "gather source missing from surface set"
        return out

    # -- per-device rows, split local/remote, bucketed -------------------
    # a row is LOCAL iff every (live) gather source is an own block:
    # local rows scatter while the surface exchange is in flight
    def local_s(rows, d):
        blk = src_blk[rows]
        return (blk >= d * B) & (blk < (d + 1) * B)

    def local_g(rows, d):
        blk = idx_blk[rows]                       # [n, K]
        own = (blk >= d * B) & (blk < (d + 1) * B)
        return (own | zmask[rows]).all(axis=1)

    rs_by_d = [np.nonzero(dev_s == d)[0] for d in range(D)]
    rg_by_d = [np.nonzero(dev_g == d)[0] for d in range(D)]
    rs_l = [r[local_s(r, d)] for d, r in enumerate(rs_by_d)]
    rs_r = [r[~local_s(r, d)] for d, r in enumerate(rs_by_d)]
    rg_l = [r[local_g(r, d)] for d, r in enumerate(rg_by_d)]
    rg_r = [r[~local_g(r, d)] for d, r in enumerate(rg_by_d)]

    scratch = B * LL
    f32 = sign.dtype

    def pack_rows(rows_by_d, kind):
        G = _bucket(max(len(r) for r in rows_by_d), lo=4)
        pk_src = np.zeros((D, G) + ((K,) if kind == "g" else ()),
                          np.int32)
        pk_wgt = np.zeros(
            (D, G) + ((K, dim) if kind == "g" else (dim,)), f32)
        pk_dst = np.full((D, G), scratch, np.int32)
        for d, r in enumerate(rows_by_d):
            n = len(r)
            if kind == "s":
                pk_src[d, :n] = remap_cells(src[r], d)
                pk_wgt[d, :n] = sign[r]
                pk_dst[d, :n] = dest_s[r] - d * B * LL
            else:
                pk_src[d, :n] = remap_cells(
                    idx[r], d, dead_local=zmask[r]).reshape(n, K)
                pk_wgt[d, :n] = w[r]
                pk_dst[d, :n] = dest[r] - d * B * LL
        return pk_src, pk_wgt, pk_dst

    src_l_, sign_l_, dest_sl_ = pack_rows(rs_l, "s")
    src_r_, sign_r_, dest_sr_ = pack_rows(rs_r, "s")
    idx_l_, w_l_, dest_l_ = pack_rows(rg_l, "g")
    idx_r_, w_r_, dest_r_ = pack_rows(rg_r, "g")

    return _put_shard_tables(mesh, ShardTables(
        pack=pack,
        src_l=src_l_, sign_l=sign_l_, dest_sl=dest_sl_,
        idx_l=idx_l_, w_l=w_l_, dest_l=dest_l_,
        src_r=src_r_, sign_r=sign_r_, dest_sr=dest_sr_,
        idx_r=idx_r_, w_r=w_r_, dest_r=dest_r_,
        fc_nb=fc_nb_, fc_mask=fc_mask_,
        mesh=mesh, B=B, S=S, L=L, g=g, dim=dim,
        offsets=offsets, mode=mode, n_regions=n_regions, perms=perms,
    ))


def _put_shard_tables(mesh: Mesh, t):
    leaves, treedef = jax.tree_util.tree_flatten(t)
    put = jax.device_put(
        leaves, [NamedSharding(mesh, P("x"))] * len(leaves))
    return jax.tree_util.tree_unflatten(treedef, put)


def _exchange_surface(x_loc, pack, t):
    """Surface-block exchange inside shard_map: per-offset ppermute
    sends (default) or the mesh-wide all-gather (audit mode). ``pack``
    is the per-device tuple of per-offset send indices ([S_o] each).
    Returns the received surface blocks [R, ...] (R = sum_o S_o) to
    append after the B own blocks. Each offset's perm list names only
    the pairs that actually send (devices outside it receive zeros in
    that slot); the issue order matters for overlap: all sends start
    before any consumer indexes the results, so XLA can overlap them
    with the local lab initialization below."""
    D = t.mesh.devices.size
    if t.mode == "allgather":
        surf = x_loc[pack[0]]                       # [S, dim, bs, bs]
        asurf = jax.lax.all_gather(surf, "x")       # [D, S, ...]
        return asurf.reshape((D * t.S,) + x_loc.shape[1:])
    parts = []
    for oi in range(len(t.offsets)):
        buf = x_loc[pack[oi]]                       # [S_o, ...]
        parts.append(jax.lax.ppermute(buf, "x", perm=list(t.perms[oi])))
    if not parts:
        return jnp.zeros((0,) + x_loc.shape[1:], x_loc.dtype)
    return jnp.concatenate(parts, axis=0)           # [sum S_o, ...]


def _assemble_sharded(x: jnp.ndarray, t: ShardTables) -> jnp.ndarray:
    """[n_pad, dim, BS, BS] ordered field -> [n_pad, dim, L, L] labs,
    sharded on the block axis; comm = per-offset neighbor ppermutes
    (or one surface all-gather in audit mode).

    Overlap structure (main.cpp:864-893): the exchange is ISSUED first;
    the lab initialization and every local-only ghost row (the vast
    majority) depend only on x_loc and sit between the collective's
    start and its first consumer in the dependence graph, so the
    scheduler can hide the exchange latency behind them; only the *_r
    rows wait for the received buffer. validation/overlap_check.py
    verifies the compiled schedule actually interleaves."""
    B, L, g, dim = t.B, t.L, t.g, t.dim
    bs = L - 2 * g

    @partial(_shard_map, mesh=t.mesh,
             in_specs=(P("x"),) * 16, out_specs=P("x"))
    def run(x_loc, pack, src_l, sign_l, dest_sl, idx_l, w_l, dest_l,
            src_r, sign_r, dest_sr, idx_r, w_r, dest_r, fc_nb, fc_mask):
        pack = tuple(p[0] for p in pack)
        (src_l, sign_l, dest_sl, idx_l, w_l, dest_l,
         src_r, sign_r, dest_sr, idx_r, w_r, dest_r,
         fc_nb, fc_mask) = (
            a[0] for a in (src_l, sign_l, dest_sl, idx_l, w_l,
                           dest_l, src_r, sign_r, dest_sr, idx_r, w_r,
                           dest_r, fc_nb, fc_mask))
        # 1. exchange in flight
        recv = _exchange_surface(x_loc, pack, t)
        # 2. local work: lab init + the shard-local face-copy paint +
        #    all local-only rows (everything here reads x_loc only, so
        #    it all sits in the exchange's latency-hiding window)
        flat_l = x_loc.transpose(1, 0, 2, 3).reshape(dim, -1)
        simple_l = flat_l[:, src_l].T * sign_l
        general_l = jnp.einsum("dgk,gkd->gd", flat_l[:, idx_l], w_l)
        labs = jnp.zeros((B, dim, L, L), x_loc.dtype)
        labs = labs.at[:, :, g:g + bs, g:g + bs].set(x_loc)
        if t.n_regions:
            # structured same-level strips — the same paint body as the
            # single-device FastHalo path (halo._paint_regions), over
            # the own-shard neighbor indices; uncovered blocks write
            # zeros there and their rows remain in the (filtered)
            # tables below
            labs = _paint_regions(x_loc, labs, fc_nb, fc_mask, g, bs,
                                  t.n_regions == 8)
        lf = labs.transpose(1, 0, 2, 3).reshape(dim, -1)
        lf = jnp.concatenate(
            [lf, jnp.zeros((dim, 1), x_loc.dtype)], axis=1)
        lf = lf.at[:, dest_sl].set(simple_l.T.astype(lf.dtype))
        lf = lf.at[:, dest_l].set(general_l.T.astype(lf.dtype))
        # 3. consume the exchange: surface-dependent rows only
        blocks = jnp.concatenate([x_loc, recv], axis=0)
        flat = blocks.transpose(1, 0, 2, 3).reshape(dim, -1)
        simple_r = flat[:, src_r].T * sign_r
        general_r = jnp.einsum("dgk,gkd->gd", flat[:, idx_r], w_r)
        lf = lf.at[:, dest_sr].set(simple_r.T.astype(lf.dtype))
        lf = lf.at[:, dest_r].set(general_r.T.astype(lf.dtype))
        return lf[:, :-1].reshape(dim, B, L, L).transpose(1, 0, 2, 3)

    return run(x, t.pack, t.src_l, t.sign_l, t.dest_sl, t.idx_l, t.w_l,
               t.dest_l, t.src_r, t.sign_r, t.dest_sr, t.idx_r, t.w_r,
               t.dest_r, t.fc_nb, t.fc_mask)


def exchange_padding_stats(t: HaloTables, n_pad: int, D: int,
                           mode: str = "ppermute") -> dict:
    """Host-only audit of the surface-exchange plan at an ARBITRARY
    simulated device count (no mesh, no devices): how many blocks the
    per-offset ppermute buffers actually carry over the wire (the
    sparse perm pairs x their per-offset buckets) vs the distinct real
    sends. The old shared-bucket plan grew padding with device count
    (VERDICT r5 weak #5: 2.64 -> 4.05 MB/device over 8 -> 64 devices
    on the 1e4-block probe); tests/test_comm_volume.py bounds the
    ratio so a pod-scale padding regression fails CI instead of
    passing silently."""
    assert n_pad % D == 0, (n_pad, D)
    B = n_pad // D
    remote_by_d = _halo_remote_by_d(t, n_pad, D)[0]
    offsets, S, pack, perms, _ = _build_exchange_plan(
        remote_by_d, D, B, n_pad, mode)
    # real payload: each (consumer, remote block) demand is exactly
    # one (owner, offset) send entry in the plan (the plan is BUILT
    # from remote_by_d, whose per-consumer sets are unique), so the
    # count needs no re-derivation that could drift from the plan
    real_blocks = sum(len(r) for r in remote_by_d)
    if mode == "allgather":
        padded_blocks = D * S
    else:
        padded_blocks = sum(
            len(perms[oi]) * pack[oi].shape[1]
            for oi in range(len(offsets)))
    return {
        "D": D, "B": B, "S": S, "offsets": offsets,
        "real_blocks": real_blocks,
        "padded_blocks": padded_blocks,
        "ratio": padded_blocks / max(real_blocks, 1),
    }


# ---------------------------------------------------------------------------
# comm/compute-overlapped Jacobi smoothing on x-split uniform fields
# ---------------------------------------------------------------------------

def overlap_jacobi_sweeps(e: jnp.ndarray, r: jnp.ndarray,
                          inv_d: jnp.ndarray, omega: float, n: int,
                          mesh: Mesh, tier: str = "xla") -> jnp.ndarray:
    """``n`` damped-Jacobi sweeps of the undivided zero-Neumann 5-point
    Laplacian, ``e += omega (r - lap e) inv_d``, on [Ny, Nx] fields
    x-split over ``mesh`` — the smoothing kernel of the FAS multigrid
    solver's sharded path (poisson.MultigridPreconditioner(mesh=...)).

    GSPMD lowers the stencil's shifted slices correctly but owns the
    schedule; this form makes the arXiv:1309.7128 overlap structural:
    each sweep ISSUES the two edge-column ``lax.ppermute``s first (the
    sparse interior pairs — boundary devices receive zeros, exactly the
    zero-ghost the wall stencil wants), then computes the y-direction
    terms and the interior x-columns from purely local data inside the
    exchange's latency-hiding window; only the two ghost-adjacent
    columns consume the received buffers. Same dependence idiom as
    ``_assemble_sharded``/``_poisson_apply_sharded``.

    Arithmetic matches ``ops.stencil.laplacian5_neumann`` termwise
    (xp + xm + yp + ym + p*(edges - 4), ghosts zero, rank-1 edge
    correction), so the sharded sweep agrees with the single-device
    sweep to reordering roundoff (tests/test_poisson.py pins the
    equivalence).

    ``tier`` (ISSUE 19): the grid's smoother tier. "strip" routes each
    sweep through the fused halo strip kernel
    (pallas_kernels.fused_jacobi_halo_sweep) with the SAME
    ppermute-before-dispatch structure — halo columns ride a
    lane-padded aux operand into the kernel, per the PR-16
    fused_advect_heun_sharded pattern; unsupported shapes fall back to
    the GSPMD body below (identical result, an optimization gate)."""
    D = mesh.devices.size
    if tier == "strip":
        from ..ops import pallas_kernels as pk
        nxl = int(e.shape[-1]) // int(D)
        if pk.jacobi_strip_supported(int(e.shape[-2]), nxl, e.dtype, 1):
            return _overlap_jacobi_sweeps_strip(e, r, omega, n, mesh)

    @partial(_shard_map, mesh=mesh,
             in_specs=(P(None, "x"),) * 3, out_specs=P(None, "x"))
    def run(e_loc, r_loc, inv_loc):
        ny, w = e_loc.shape
        idx = jax.lax.axis_index("x")
        dt_ = e_loc.dtype
        iy = jnp.arange(ny)
        ix = jnp.arange(w)
        one = jnp.ones((), dt_)
        zero = jnp.zeros((), dt_)
        ey = jnp.where((iy == 0) | (iy == ny - 1), one, zero)
        # x walls exist only on the boundary devices of the split axis
        ex = (jnp.where(ix == 0, one, zero) * (idx == 0).astype(dt_)
              + jnp.where(ix == w - 1, one, zero)
              * (idx == D - 1).astype(dt_))
        corr = (ey[:, None] + ex[None, :]) - 4.0
        zrow = jnp.zeros((1, w), dt_)

        def sweep(_, ee):
            # 1. exchange in flight: my left ghost is my left
            #    neighbor's last column, my right ghost the right
            #    neighbor's first; devices with no sender get zeros
            gl = jax.lax.ppermute(
                ee[:, -1:], "x", perm=[(d, d + 1) for d in range(D - 1)])
            gr = jax.lax.ppermute(
                ee[:, :1], "x", perm=[(d + 1, d) for d in range(D - 1)])
            # 2. local terms (the latency-hiding window): y shifts and
            #    the x contributions of interior columns read ee only
            yp = jnp.concatenate([ee[1:, :], zrow], axis=0)
            ym = jnp.concatenate([zrow, ee[:-1, :]], axis=0)
            # 3. ghost-adjacent columns consume the received buffers
            xp = jnp.concatenate([ee[:, 1:], gr], axis=1)
            xm = jnp.concatenate([gl, ee[:, :-1]], axis=1)
            lap = xp + xm + yp + ym + ee * corr
            return ee + omega * (r_loc - lap) * inv_loc

        return jax.lax.fori_loop(0, n, sweep, e_loc)

    return run(e, r, inv_d)


def _overlap_jacobi_sweeps_strip(e: jnp.ndarray, r: jnp.ndarray,
                                 omega: float, n: int,
                                 mesh: Mesh) -> jnp.ndarray:
    """Strip-tier body of ``overlap_jacobi_sweeps``: per sweep, issue
    the two edge-column ppermutes FIRST, then dispatch the fused halo
    strip kernel over the local slab (one read of (e, r), one write per
    sweep — the sharded chain cannot time-skew across sweeps because
    each needs fresh neighbor columns). The wall-diagonal corr/inv_d
    are rebuilt in-kernel from the (is_lo, is_hi) SMEM row, the same
    values as the GSPMD body's device-index-masked indicators, so both
    tiers agree to reordering roundoff."""
    from ..ops import pallas_kernels as pk
    D = mesh.devices.size
    interpret = not pk._on_accel()
    pad_w = 2 * pk._GX - 2

    # check_rep=False: shard_map has no replication rule for
    # pallas_call (the fused_advect_heun_sharded precedent)
    @partial(_shard_map, mesh=mesh,
             in_specs=(P(None, "x"),) * 2, out_specs=P(None, "x"),
             check_rep=False)
    def run(e_loc, r_loc):
        idx = jax.lax.axis_index("x")
        i32 = jnp.int32
        info = jnp.stack([(idx == 0).astype(i32),
                          (idx == D - 1).astype(i32)])[None, :]

        def sweep(_, ee):
            gl = jax.lax.ppermute(
                ee[..., -1:], "x",
                perm=[(d, d + 1) for d in range(D - 1)])
            gr = jax.lax.ppermute(
                ee[..., :1], "x",
                perm=[(d + 1, d) for d in range(D - 1)])
            aux = jnp.pad(jnp.concatenate([gl, gr], axis=-1),
                          ((0, 0), (0, pad_w)))
            return pk.fused_jacobi_halo_sweep(ee, r_loc, aux, info,
                                              omega,
                                              interpret=interpret)

        return jax.lax.fori_loop(0, n, sweep, e_loc)

    return run(e, r)


# ---------------------------------------------------------------------------
# comm/compute-overlapped megakernel substages on x-split velocity
# (tentpole, ISSUE 16)
# ---------------------------------------------------------------------------

def fused_advect_heun_sharded(vel, h, nu, dt, mesh: Mesh, *, bc=None,
                              bf16: bool = False, interpret=None):
    """Both Heun substages of the fused megakernel tier on an x-split
    velocity — the mesh-aware twin of
    ``ops.pallas_kernels.fused_advect_heun``.

    Each substage ISSUES the two 3-wide edge-column ``lax.ppermute``s
    for the WENO halo FIRST (the ``overlap_jacobi_sweeps`` idiom,
    arXiv:1309.7128 — boundary devices receive zeros), then dispatches
    the interior strip pipeline; the received columns are fused inside
    the kernel as the boundary strips' ghost source
    (``_fused_substage_sharded``), so the exchange latency hides behind
    the kernel body. The halo is exchanged in the STORAGE dtype (bf16
    on the bf16 tier) — exactly the columns the solo kernel reads from
    its own ring — so the sharded trajectory is termwise-identical to
    the GSPMD chain. The BCTable (default free-slip) is static; wall
    shards where-select the x-face ghost paint over the non-received
    halo columns, interior shards never branch.

    vel: [..., 2, Ny, Nx] with Nx divisible by the mesh size; dt:
    scalar or leading-shaped (per-member). Returns the substage-2
    velocity in vel's shape/dtype."""
    from ..bc import BCTable
    from ..ops import pallas_kernels as pk

    if bc is None or bc.is_free_slip:
        bc = BCTable()
    # capability gate (names face/kind/token): periodic ('pd') tables
    # refuse here — the halo exchange is a 3-wide NEIGHBOR ppermute
    # with zero-filled boundary shards, and a wrap ghost would need a
    # ring permute plus a wrap-aware strip pipeline neither kernel
    # has. The same x-split is why CUP2D_POIS=fftd refuses
    # attach_mesh: it would shard the FFT transform axis (periodic x)
    # or the tridiagonal scan axis (periodic y). Run sharded periodic
    # cases under the XLA tier with bicgstab/fas.
    pk.kernel_supports(bc)
    lead = vel.shape[:-3]
    L = pk._flatten_lead(lead)
    v = vel.reshape((L,) + vel.shape[-3:])
    dtv = pk._per_member(dt, lead, L)
    hh = float(h)
    facs = jnp.stack([-dtv * hh, nu * dtv, dtv], axis=-1)   # [L, 3] f32
    ih2 = 1.0 / (hh * hh)
    if interpret is None:
        interpret = not pk._on_accel()
    D = int(mesh.devices.size)
    nx = v.shape[-1]
    if nx % D:
        raise ValueError(
            f"fused_advect_heun_sharded: Nx={nx} not divisible by the "
            f"mesh size D={D}")
    nxl = nx // D
    g = pk._G
    pad_w = 2 * pk._GX - 2 * g   # halo operand lane-padded to 128

    # check_rep=False: shard_map has no replication rule for
    # pallas_call; every output is explicitly sharded on "x" anyway
    @partial(_shard_map, mesh=mesh,
             in_specs=(P(None, None, None, "x"), P(None, None)),
             out_specs=P(None, None, None, "x"), check_rep=False)
    def run(vb, facsb):
        idx = jax.lax.axis_index("x")
        i32 = jnp.int32
        info = jnp.stack([(idx == 0).astype(i32),
                          (idx == D - 1).astype(i32),
                          (idx * nxl).astype(i32)])[None, :]

        def halo(a):
            # exchange first (storage dtype): my left halo is my left
            # neighbor's last g columns, my right halo the right
            # neighbor's first g; wall devices receive zeros (replaced
            # in-kernel by the x-face BC paint)
            hl = jax.lax.ppermute(
                a[..., -g:], "x", perm=[(d, d + 1) for d in range(D - 1)])
            hr = jax.lax.ppermute(
                a[..., :g], "x", perm=[(d + 1, d) for d in range(D - 1)])
            aux = jnp.concatenate([hl, hr], axis=-1)        # [L,2,ny,2g]
            return jnp.pad(aux, ((0, 0), (0, 0), (0, 0), (0, pad_w)))

        def sub(stage_v, vold, cfac, out_dtype):
            return pk._fused_substage_sharded(
                stage_v, vold, halo(stage_v), info, facsb, cfac, ih2,
                out_dtype, bc, hh, nx, interpret)

        if bf16:
            v0 = vb.astype(jnp.bfloat16)
            v1 = sub(v0, None, 0.5, jnp.bfloat16)
            return sub(v1, v0, 1.0, vb.dtype)
        v1 = sub(vb, None, 0.5, vb.dtype)
        return sub(v1, vb, 1.0, vb.dtype)

    return run(v, facs).reshape(vel.shape)


# ---------------------------------------------------------------------------
# structured per-face Poisson operator across shards (round 5 on the mesh)
# ---------------------------------------------------------------------------

class ShardPoissonOp(NamedTuple):
    """Per-device rows of flux.PoissonOp behind the surface exchange.

    The structured operator's only data-dependent reads are its 2
    block-row gathers per face (``nba``/``nbb``); on the mesh those are
    remapped into the [B own blocks ++ received surface blocks] space
    of the SAME per-offset ppermute plan the halo gather uses
    (_build_exchange_plan), so the Krylov loop's per-iteration traffic
    stays shard-boundary-proportional — no whole-field GSPMD
    collectives (the reason forest_mesh kept the round-4 lab-table
    operator until now). The strip math is flux._structured_lap, the
    ONE body shared with the single-device apply: every tangential
    matmul reduces over BS only, so per-block-row results are
    bit-identical across device counts. Exposes ``nba`` so
    amr._pressure_project's structured-operator dispatch works
    unchanged on one device and on eight."""

    pack: tuple            # per-offset [D, S_o] int32 own blocks to export
    nba: jnp.ndarray       # [D, 4, B] int32 into [B own ++ received]
    nbb: jnp.ndarray       # [D, 4, B]
    m_same: jnp.ndarray    # [D, 4, B] case one-hots
    m_coarse: jnp.ndarray  # [D, 4, B]
    m_fine: jnp.ndarray    # [D, 4, B]
    m_wall: jnp.ndarray    # [D, 4, B]
    par: jnp.ndarray       # [D, 4, B]
    wc0: jnp.ndarray       # [BS, BS] static tangential maps, replicated
    wc1: jnp.ndarray
    mcl: jnp.ndarray       # [2, BS, BS]
    mfr: jnp.ndarray       # [2, BS, BS]
    d2own: jnp.ndarray     # [BS, BS]
    mesh: Mesh
    B: int
    S: int
    bs: int
    offsets: tuple
    mode: str
    perms: tuple

    def apply(self, x: jnp.ndarray) -> jnp.ndarray:
        return _poisson_apply_sharded(x, self)


jax.tree_util.register_pytree_node(
    ShardPoissonOp,
    lambda t: ((t.pack, t.nba, t.nbb, t.m_same, t.m_coarse, t.m_fine,
                t.m_wall, t.par, t.wc0, t.wc1, t.mcl, t.mfr, t.d2own),
               (t.mesh, t.B, t.S, t.bs, t.offsets, t.mode, t.perms)),
    lambda aux, ch: ShardPoissonOp(*ch, *aux),
)


def shard_poisson_op(op, n_pad: int, mesh: Mesh,
                     mode: str = "ppermute") -> ShardPoissonOp:
    """Split a (numpy-leaf) flux.PoissonOp into per-device rows + a
    surface exchange plan. Surface demand = the live (non-wall,
    non-pad) neighbor positions of each device's own rows that fall
    outside its shard — the face-neighbor subset of the halo sets'
    demand, so S is bounded by the same shard boundary."""
    D = mesh.devices.size
    assert n_pad % D == 0, (n_pad, D)
    B = n_pad // D
    nba = np.asarray(op.nba, np.int64)          # [4, n_pad]
    nbb = np.asarray(op.nbb, np.int64)
    m_same = np.asarray(op.m_same)
    m_coarse = np.asarray(op.m_coarse)
    m_fine = np.asarray(op.m_fine)
    m_wall = np.asarray(op.m_wall)
    par = np.asarray(op.par)
    # a gather index is live iff some case mask actually consumes it
    # (wall/pad faces keep the n_real sentinel — dead, remapped to 0)
    live_a = (m_same + m_coarse + m_fine) > 0   # [4, n_pad]
    live_b = m_fine > 0                         # nbb only feeds g_fine

    remote_by_d = []
    for d in range(D):
        sl = slice(d * B, (d + 1) * B)
        refs = np.concatenate([nba[:, sl][live_a[:, sl]],
                               nbb[:, sl][live_b[:, sl]]])
        remote_by_d.append(
            np.unique(refs[(refs < d * B) | (refs >= (d + 1) * B)]))

    offsets, S, pack, perms, g2surf = _build_exchange_plan(
        remote_by_d, D, B, n_pad, mode)

    def remap(pos, live, d):
        local = (pos >= d * B) & (pos < (d + 1) * B)
        sidx = g2surf[d, np.clip(pos, 0, n_pad - 1)]
        out = np.where(local, pos - d * B, B + sidx)
        out = np.where(live, out, 0)
        assert not (live & ~local & (sidx < 0)).any(), \
            "gather source missing from surface set"
        return out

    nba_l = np.zeros((D, 4, B), np.int32)
    nbb_l = np.zeros((D, 4, B), np.int32)
    for d in range(D):
        sl = slice(d * B, (d + 1) * B)
        nba_l[d] = remap(nba[:, sl], live_a[:, sl], d)
        nbb_l[d] = remap(nbb[:, sl], live_b[:, sl], d)

    def per_dev(a):
        return np.ascontiguousarray(
            np.asarray(a).reshape(4, D, B).transpose(1, 0, 2))

    shard = NamedSharding(mesh, P("x"))
    repl = NamedSharding(mesh, P())
    pack = jax.device_put(list(pack), [shard] * len(pack))
    rows = jax.device_put(
        [nba_l, nbb_l, per_dev(m_same), per_dev(m_coarse),
         per_dev(m_fine), per_dev(m_wall), per_dev(par)], [shard] * 7)
    mats = jax.device_put(
        [np.asarray(op.wc0), np.asarray(op.wc1), np.asarray(op.mcl),
         np.asarray(op.mfr), np.asarray(op.d2own)], [repl] * 5)
    return ShardPoissonOp(tuple(pack), *rows, *mats, mesh=mesh, B=B,
                          S=S, bs=int(np.asarray(op.wc0).shape[0]),
                          offsets=offsets, mode=mode, perms=perms)


def overlap_block_jacobi_sweeps(e: jnp.ndarray, r: jnp.ndarray,
                                p_inv: jnp.ndarray, t: ShardPoissonOp,
                                n: int, tier: str = "xla") -> jnp.ndarray:
    """``n`` composite block-Jacobi sweeps ``e += P_inv (r - A e)`` on
    the block-sharded forest — the finest-level smoother of the forest
    FAS solver (poisson.ForestFASCycle via
    forest_mesh.ShardedAMRSim._fas_block_smoother), with the
    block-surface exchange latency made hideable: ONE shard_map whose
    per-sweep body ISSUES the per-offset surface ppermutes first
    (_exchange_surface), computes the within-block 5-point part and
    every own-neighbor strip from purely local data inside the
    collective's latency window, and consumes the received buffer only
    in the remote-neighbor gathers; the P_inv GEMM is shard-local.
    Extends ``overlap_jacobi_sweeps``'s issue-comms-first structure
    (arXiv:1309.7128) from the uniform x-split to the forest's
    block-surface exchange.

    Arithmetic is TERMWISE identical to the unoverlapped composition
    ``e + apply_block_precond_blocks(r - A(e), p_inv)`` with
    A = ``_poisson_apply_sharded``: the sweep body runs the same
    flux._structured_lap strip math over the same [own ++ received]
    gather space and the same GEMM, so sweeps agree with the
    single-shard_map-per-sweep form to the last bit
    (tests/test_forest_mesh.py pins <= 1e-12).

    ``tier`` (ISSUE 19): "strip"/"fused" fuses the smoother's own
    traffic — residual subtract, P_inv GEMM, update add — into one
    Pallas pass per sweep (pallas_kernels.fused_block_jacobi_update),
    dispatched AFTER the same exchange-first _structured_lap window;
    f64 (and Pallas-less hosts) keep the XLA composition."""
    from ..flux import _structured_lap
    use_fused = False
    if tier != "xla":
        from ..ops import pallas_kernels as pk
        use_fused = pk.block_update_supported(e.dtype)
        interpret = not pk._on_accel()

    @partial(_shard_map, mesh=t.mesh,
             in_specs=(P("x"),) * 10 + (P(),) * 6, out_specs=P("x"),
             check_rep=not use_fused)
    def run(e0, r_loc, pack, nba, nbb, ms, mc, mf, mw, par,
            p_inv_r, wc0, wc1, mcl, mfr, d2own):
        pack = tuple(p[0] for p in pack)
        nba, nbb, ms, mc, mf, mw, par = (
            a[0] for a in (nba, nbb, ms, mc, mf, mw, par))
        B, bs_, _ = e0.shape

        def sweep(_, ee):
            # 1. exchange in flight
            recv = _exchange_surface(ee, pack, t)
            # 2. local window: own-block stencil + own-neighbor strips
            #    (blocks[:B] = ee) — _structured_lap's gathers of local
            #    sources depend only on ee; 3. remote-sourced strips
            #    consume recv
            blocks = jnp.concatenate([ee, recv], axis=0)
            lap = _structured_lap(ee, blocks, nba, nbb, ms, mc, mf,
                                  mw, par, (wc0, wc1, mcl, mfr, d2own))
            if use_fused:
                return pk.fused_block_jacobi_update(
                    ee, r_loc, lap, p_inv_r, interpret=interpret)
            z = ((r_loc - lap).reshape(B, bs_ * bs_)
                 @ p_inv_r.T).reshape(B, bs_, bs_)
            return ee + z

        return jax.lax.fori_loop(0, n, sweep, e0)

    return run(e, r, t.pack, t.nba, t.nbb, t.m_same, t.m_coarse,
               t.m_fine, t.m_wall, t.par, p_inv, t.wc0, t.wc1,
               t.mcl, t.mfr, t.d2own)


def _poisson_apply_sharded(x: jnp.ndarray, t: ShardPoissonOp):
    """A(x) for [n_pad, BS, BS] ordered x sharded on the block axis:
    issue the surface exchange, then run the shared structured strip
    math over [own ++ received] gather space. The own-edge strips and
    the within-block 5-point part read x_loc only, so they sit in the
    exchange's latency-hiding window exactly like the halo assembly's
    local rows."""
    from ..flux import _structured_lap

    @partial(_shard_map, mesh=t.mesh,
             in_specs=(P("x"),) * 9 + (P(),) * 5, out_specs=P("x"))
    def run(x_loc, pack, nba, nbb, ms, mc, mf, mw, par,
            wc0, wc1, mcl, mfr, d2own):
        pack = tuple(p[0] for p in pack)
        nba, nbb, ms, mc, mf, mw, par = (
            a[0] for a in (nba, nbb, ms, mc, mf, mw, par))
        recv = _exchange_surface(x_loc, pack, t)
        blocks = jnp.concatenate([x_loc, recv], axis=0)
        return _structured_lap(x_loc, blocks, nba, nbb, ms, mc, mf, mw,
                               par, (wc0, wc1, mcl, mfr, d2own))

    return run(x, t.pack, t.nba, t.nbb, t.m_same, t.m_coarse, t.m_fine,
               t.m_wall, t.par, t.wc0, t.wc1, t.mcl, t.mfr, t.d2own)


# ---------------------------------------------------------------------------
# flux correction (fine-face deposits -> coarse rows) across shards
# ---------------------------------------------------------------------------

class ShardFluxCorr(NamedTuple):
    """Per-device flux-correction rows. Deposit index space per device:
    [B own blocks ++ received surface blocks] x 4 faces x BS; value
    dests are local cells [B*BS*BS] ++ 1 scratch. Exchange modes as in
    ShardTables."""

    pack: tuple          # per-offset [D, S_o] blocks whose deposits export
    dest: jnp.ndarray    # [D, M]
    cidx: jnp.ndarray    # [D, M]
    fidx1: jnp.ndarray   # [D, M]
    fidx2: jnp.ndarray   # [D, M]
    valid: jnp.ndarray   # [D, M]
    mesh: Mesh
    B: int
    S: int
    bs: int
    offsets: tuple
    mode: str
    perms: tuple

    def apply(self, values, deposits):
        return _apply_corr_sharded(values, deposits, self)


jax.tree_util.register_pytree_node(
    ShardFluxCorr,
    lambda t: ((t.pack, t.dest, t.cidx, t.fidx1, t.fidx2, t.valid),
               (t.mesh, t.B, t.S, t.bs, t.offsets, t.mode, t.perms)),
    lambda aux, ch: ShardFluxCorr(*ch, *aux),
)


def shard_flux_corr(corr, n_pad: int, mesh: Mesh, bs: int,
                    dtype=np.float32,
                    mode: str = "ppermute") -> ShardFluxCorr:
    """Split (unpadded) FluxCorrTables by owning coarse block."""
    D = mesh.devices.size
    assert n_pad % D == 0
    B = n_pad // D
    bs2 = bs * bs
    fb = 4 * bs                                   # deposit cells/block
    dest = np.asarray(corr.dest, np.int64)
    cidx = np.asarray(corr.cidx, np.int64)
    f1 = np.asarray(corr.fidx1, np.int64)
    f2 = np.asarray(corr.fidx2, np.int64)
    dev = (dest // bs2) // B

    remote_by_d = []
    for d in range(D):
        ref = np.concatenate([a[dev == d] // fb for a in (cidx, f1, f2)])
        remote_by_d.append(
            np.unique(ref[(ref < d * B) | (ref >= (d + 1) * B)]))

    offsets, S, pack, perms, g2surf = _build_exchange_plan(
        remote_by_d, D, B, n_pad, mode)

    def remap_dep(cells, d):
        blk = cells // fb
        off = cells % fb
        local = (blk >= d * B) & (blk < (d + 1) * B)
        sidx = g2surf[d, np.clip(blk, 0, n_pad - 1)]
        assert not ((~local) & (sidx < 0)).any()
        return np.where(local, (blk - d * B) * fb + off,
                        (B + sidx) * fb + off)

    M = _bucket(max(int((dev == d).sum()) for d in range(D)), lo=4)
    scratch = B * bs2
    pk_dest = np.full((D, M), scratch, np.int32)
    pk_c = np.zeros((D, M), np.int32)
    pk_f1 = np.zeros((D, M), np.int32)
    pk_f2 = np.zeros((D, M), np.int32)
    pk_v = np.zeros((D, M), dtype)
    for d in range(D):
        r = np.nonzero(dev == d)[0]
        n = len(r)
        pk_dest[d, :n] = dest[r] - d * B * bs2
        pk_c[d, :n] = remap_dep(cidx[r], d)
        pk_f1[d, :n] = remap_dep(f1[r], d)
        pk_f2[d, :n] = remap_dep(f2[r], d)
        pk_v[d, :n] = 1.0
    return _put_shard_tables(mesh, ShardFluxCorr(
        pack=pack, dest=pk_dest, cidx=pk_c, fidx1=pk_f1, fidx2=pk_f2,
        valid=pk_v, mesh=mesh, B=B, S=S, bs=bs,
        offsets=offsets, mode=mode, perms=perms,
    ))


def _apply_corr_sharded(values, deposits, t: ShardFluxCorr):
    B, bs = t.B, t.bs
    vec = values.ndim == 4

    @partial(_shard_map, mesh=t.mesh,
             in_specs=(P("x"),) * 8, out_specs=P("x"))
    def run(v_loc, d_loc, pack, dest, cidx, f1, f2, valid):
        pack = tuple(p[0] for p in pack)
        dest, cidx, f1, f2, valid = (
            a[0] for a in (dest, cidx, f1, f2, valid))
        recv = _exchange_surface(d_loc, pack, t)
        dep = jnp.concatenate([d_loc, recv], axis=0)
        if vec:
            dim = v_loc.shape[1]
            df = dep.reshape(-1, dim)
            corr = valid[:, None].astype(v_loc.dtype) * (
                df[cidx] + df[f1] + df[f2])
            flat = v_loc.transpose(0, 2, 3, 1).reshape(-1, dim)
            flat = jnp.concatenate(
                [flat, jnp.zeros((1, dim), v_loc.dtype)], axis=0)
            out = flat.at[dest].add(corr)[:-1]
            return out.reshape(B, bs, bs, dim).transpose(0, 3, 1, 2)
        df = dep.reshape(-1)
        corr = valid.astype(v_loc.dtype) * (df[cidx] + df[f1] + df[f2])
        flat = jnp.concatenate(
            [v_loc.reshape(-1), jnp.zeros((1,), v_loc.dtype)])
        return flat.at[dest].add(corr)[:-1].reshape(B, bs, bs)

    return run(values, deposits, t.pack, t.dest, t.cidx, t.fidx1,
               t.fidx2, t.valid)
