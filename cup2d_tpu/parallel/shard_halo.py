"""Shard-local ghost assembly: the explicit halo exchange.

GSPMD lowers `halo.assemble_labs_ordered`'s data-dependent gather from
a block-sharded operand to an **all-gather of the entire field** —
traffic proportional to shard volume, several times per step and once
per Krylov iteration (measured: validation/comm_audit.py). The
reference's comm layer exists precisely to avoid that: it ships only
halo slabs between neighbor ranks (/root/reference/main.cpp:909-2142,
Setup/sync1), so per-rank traffic scales with the shard *boundary*.

This module restores that scaling law on the device mesh:

* gather tables are split per device — rows whose destination block
  lives in shard d become d's rows, with every gather source remapped
  into a local index space = [d's own B blocks] ++ [an all-gathered
  SURFACE buffer];
* the surface buffer packs only blocks some OTHER shard references —
  the shard-boundary halo (SFC-contiguous shards keep it thin, the
  same locality argument as the reference's SFC rank ranges);
* assembly runs under `shard_map`: pack own surface blocks, ONE
  `lax.all_gather` of the packed buffer over the mesh axis, then purely
  local gathers/scatters.

The flux-correction fix-up (fine-face deposits added into coarse rows,
main.cpp:1392-1849) gets the identical treatment with face-deposit rows
as the exchanged payload.

Per-device row counts and surface sizes are padded to power-of-two
buckets so regrids reuse compiled executables (same rationale as
halo.pad_tables).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..halo import HaloTables, _bucket


class ShardTables(NamedTuple):
    """Per-device halo tables (leaves stacked [D, ...], sharded on the
    mesh axis so each device reads only its own rows inside shard_map).

    Index spaces (per device d owning ordered blocks [dB, dB+B)):
      gather sources: flat cells of [B own blocks ++ received surface
                      blocks (per-offset ppermute buckets, or the D*S
                      all-gather buffer in mode="allgather")]
      scatter dests:  flat cells of [B labs] ++ 1 trailing scratch cell
                      (pad rows write zeros there; dropped on return)

    Neighbor-wise exchange (default): ``offsets`` lists the nonzero
    consumer-minus-owner shard distances that actually occur;
    ``pack[o]`` holds, per owner device, the own-block indices to send
    to owner+offsets[o] (one ``lax.ppermute`` per offset). Per-device
    traffic is sum_o S_o — the device's own shard boundary — instead of
    the all-gather's D*S_max (the GLOBAL boundary), restoring the
    reference's per-neighbor-send scaling law (main.cpp:1971-2142).
    SFC-contiguous shards keep the offset set small (almost always
    {-1, +1}).

    Rows are SPLIT by surface dependence (comm/compute overlap,
    VERDICT r3 missing #3): the *_l row sets read only the device's own
    x_loc and are scattered while the surface exchange is still in
    flight; the *_r sets (every row with at least one remote source)
    consume the received buffer afterwards. The reference overlaps the
    same way — inner blocks compute while halo messages fly
    (main.cpp:864-893 avail_next + computeA 3024-3061).
    """

    pack: jnp.ndarray     # [D, n_off, S] int32 own blocks to export
    src_l: jnp.ndarray    # [D, Gsl] int32 (local-only simple rows)
    sign_l: jnp.ndarray   # [D, Gsl, dim]
    dest_sl: jnp.ndarray  # [D, Gsl] int32
    idx_l: jnp.ndarray    # [D, Ggl, K] int32 (local-only general rows)
    w_l: jnp.ndarray      # [D, Ggl, K, dim]
    dest_l: jnp.ndarray   # [D, Ggl] int32
    src_r: jnp.ndarray    # [D, Gsr] int32 (surface-dependent rows)
    sign_r: jnp.ndarray   # [D, Gsr, dim]
    dest_sr: jnp.ndarray  # [D, Gsr] int32
    idx_r: jnp.ndarray    # [D, Ggr, K] int32
    w_r: jnp.ndarray      # [D, Ggr, K, dim]
    dest_r: jnp.ndarray   # [D, Ggr] int32
    mesh: Mesh
    B: int                # blocks per device
    S: int                # surface bucket (mode-dependent semantics)
    L: int
    g: int
    dim: int
    offsets: tuple        # static nonzero shard offsets (ppermute mode)
    mode: str             # "ppermute" | "allgather"

    def assemble(self, x: jnp.ndarray) -> jnp.ndarray:
        return _assemble_sharded(x, self)


jax.tree_util.register_pytree_node(
    ShardTables,
    lambda t: ((t.pack, t.src_l, t.sign_l, t.dest_sl, t.idx_l, t.w_l,
                t.dest_l, t.src_r, t.sign_r, t.dest_sr, t.idx_r, t.w_r,
                t.dest_r),
               (t.mesh, t.B, t.S, t.L, t.g, t.dim, t.offsets, t.mode)),
    lambda aux, ch: ShardTables(*ch, *aux),
)


def _build_exchange_plan(remote_by_d, D: int, B: int, n_pad: int,
                         mode: str):
    """Common surface-exchange plan from the per-consumer remote-block
    sets: returns (offsets, S, pack[D, n_off, S], g2surf[D, n_pad])
    where g2surf[d, gblk] is the position of remote block gblk in
    consumer d's received-surface space (-1 if not received). Shared by
    the halo gather and the flux-correction deposit exchange so the two
    plans can never drift (code-review r4)."""
    if mode == "allgather":
        # one shared surface set per owner, broadcast to every device
        surf_lists: list[list[int]] = [[] for _ in range(D)]
        surf_pos: dict[int, int] = {}
        for d in range(D):
            for gblk in remote_by_d[d].tolist():
                if gblk not in surf_pos:
                    surf_pos[gblk] = len(surf_lists[gblk // B])
                    surf_lists[gblk // B].append(gblk)
        S = _bucket(max((len(x) for x in surf_lists), default=1), lo=4)
        offsets: tuple = ()
        pack = np.zeros((D, 1, S), np.int32)
        for e, lst in enumerate(surf_lists):
            pack[e, 0, :len(lst)] = np.asarray(lst, np.int64) - e * B
        g2surf = np.full((D, n_pad), -1, np.int64)
        for gblk, p in surf_pos.items():
            g2surf[:, gblk] = (gblk // B) * S + p
        return offsets, S, pack, g2surf
    # per (owner, offset) send lists; offset = consumer - owner
    send: dict = {}
    for d in range(D):
        for gblk in remote_by_d[d].tolist():
            e = gblk // B
            send.setdefault((e, d - e), []).append(gblk)
    offsets = tuple(sorted({o for (_, o) in send}))
    n_off = max(len(offsets), 1)
    S = _bucket(max((len(v) for v in send.values()), default=1), lo=4)
    pack = np.zeros((D, n_off, S), np.int32)
    g2surf = np.full((D, n_pad), -1, np.int64)
    for (e, o), lst in send.items():
        oi = offsets.index(o)
        pack[e, oi, :len(lst)] = np.asarray(lst, np.int64) - e * B
        for p, gblk in enumerate(lst):
            g2surf[e + o, gblk] = oi * S + p
    return offsets, S, pack, g2surf


def shard_tables(t: HaloTables, n_pad: int, mesh: Mesh,
                 mode: str = "ppermute") -> ShardTables:
    """Split (unpadded, numpy-leaf) tables into per-device rows with a
    surface-buffer exchange plan. ``n_pad`` must divide by the mesh
    size (amr buckets are powers of two >= 128).

    mode="ppermute" (default): per-offset neighbor sends; traffic per
    device scales with its OWN shard boundary. mode="allgather": the
    round-3 mesh-wide surface all-gather, kept for the comm-scaling
    audit (validation/comm_audit.py measures both)."""
    D = mesh.devices.size
    assert n_pad % D == 0, (n_pad, D)
    B = n_pad // D
    L, g, dim = t.L, t.g, t.dim
    bs = L - 2 * g
    bs2 = bs * bs
    LL = L * L

    dest_s = np.asarray(t.dest_s, np.int64)
    src = np.asarray(t.src_ord, np.int64)
    sign = np.asarray(t.sign)
    dest = np.asarray(t.dest, np.int64)
    idx = np.asarray(t.idx_ord, np.int64)
    w = np.asarray(t.w)
    K = idx.shape[1]

    # zero-weight K-padding entries must not create surface demand
    zmask = (w == 0).all(axis=2)                       # [Gg, K]

    dev_s = (dest_s // LL) // B
    dev_g = (dest // LL) // B
    src_blk = src // bs2
    idx_blk = idx // bs2

    # remote blocks referenced by each consumer device
    remote_by_d = []
    for d in range(D):
        ref = np.concatenate([
            src_blk[dev_s == d],
            idx_blk[dev_g == d][~zmask[dev_g == d]],
        ])
        remote_by_d.append(
            np.unique(ref[(ref < d * B) | (ref >= (d + 1) * B)]))

    offsets, S, pack, g2surf = _build_exchange_plan(
        remote_by_d, D, B, n_pad, mode)

    def remap_cells(cells, d, dead_local=None):
        blk = cells // bs2
        off = cells % bs2
        local = (blk >= d * B) & (blk < (d + 1) * B)
        sidx = g2surf[d, np.clip(blk, 0, n_pad - 1)]
        out = np.where(local, (blk - d * B) * bs2 + off,
                       (B + sidx) * bs2 + off)
        if dead_local is not None:
            out = np.where(dead_local, 0, out)
        bad = (~local) & (sidx < 0)
        if dead_local is not None:
            bad &= ~dead_local
        assert not bad.any(), "gather source missing from surface set"
        return out

    # -- per-device rows, split local/remote, bucketed -------------------
    # a row is LOCAL iff every (live) gather source is an own block:
    # local rows scatter while the surface exchange is in flight
    def local_s(rows, d):
        blk = src_blk[rows]
        return (blk >= d * B) & (blk < (d + 1) * B)

    def local_g(rows, d):
        blk = idx_blk[rows]                       # [n, K]
        own = (blk >= d * B) & (blk < (d + 1) * B)
        return (own | zmask[rows]).all(axis=1)

    rs_by_d = [np.nonzero(dev_s == d)[0] for d in range(D)]
    rg_by_d = [np.nonzero(dev_g == d)[0] for d in range(D)]
    rs_l = [r[local_s(r, d)] for d, r in enumerate(rs_by_d)]
    rs_r = [r[~local_s(r, d)] for d, r in enumerate(rs_by_d)]
    rg_l = [r[local_g(r, d)] for d, r in enumerate(rg_by_d)]
    rg_r = [r[~local_g(r, d)] for d, r in enumerate(rg_by_d)]

    scratch = B * LL
    f32 = sign.dtype

    def pack_rows(rows_by_d, kind):
        G = _bucket(max(len(r) for r in rows_by_d), lo=4)
        pk_src = np.zeros((D, G) + ((K,) if kind == "g" else ()),
                          np.int32)
        pk_wgt = np.zeros(
            (D, G) + ((K, dim) if kind == "g" else (dim,)), f32)
        pk_dst = np.full((D, G), scratch, np.int32)
        for d, r in enumerate(rows_by_d):
            n = len(r)
            if kind == "s":
                pk_src[d, :n] = remap_cells(src[r], d)
                pk_wgt[d, :n] = sign[r]
                pk_dst[d, :n] = dest_s[r] - d * B * LL
            else:
                pk_src[d, :n] = remap_cells(
                    idx[r], d, dead_local=zmask[r]).reshape(n, K)
                pk_wgt[d, :n] = w[r]
                pk_dst[d, :n] = dest[r] - d * B * LL
        return pk_src, pk_wgt, pk_dst

    src_l_, sign_l_, dest_sl_ = pack_rows(rs_l, "s")
    src_r_, sign_r_, dest_sr_ = pack_rows(rs_r, "s")
    idx_l_, w_l_, dest_l_ = pack_rows(rg_l, "g")
    idx_r_, w_r_, dest_r_ = pack_rows(rg_r, "g")

    return _put_shard_tables(mesh, ShardTables(
        pack=pack,
        src_l=src_l_, sign_l=sign_l_, dest_sl=dest_sl_,
        idx_l=idx_l_, w_l=w_l_, dest_l=dest_l_,
        src_r=src_r_, sign_r=sign_r_, dest_sr=dest_sr_,
        idx_r=idx_r_, w_r=w_r_, dest_r=dest_r_,
        mesh=mesh, B=B, S=S, L=L, g=g, dim=dim,
        offsets=offsets, mode=mode,
    ))


def _put_shard_tables(mesh: Mesh, t):
    leaves, treedef = jax.tree_util.tree_flatten(t)
    put = jax.device_put(
        leaves, [NamedSharding(mesh, P("x"))] * len(leaves))
    return jax.tree_util.tree_unflatten(treedef, put)


def _exchange_surface(x_loc, pack, t: "ShardTables"):
    """Surface-block exchange inside shard_map: per-offset ppermute
    sends (default) or the mesh-wide all-gather (audit mode). Returns
    the received surface blocks [R, ...] to append after the B own
    blocks. The ppermute issue order matters for overlap: all sends
    start before any consumer indexes the results, so XLA can overlap
    them with the local lab initialization below."""
    D = t.mesh.devices.size
    if t.mode == "allgather":
        surf = x_loc[pack[0]]                       # [S, dim, bs, bs]
        asurf = jax.lax.all_gather(surf, "x")       # [D, S, ...]
        return asurf.reshape((D * t.S,) + x_loc.shape[1:])
    parts = []
    for oi, o in enumerate(t.offsets):
        buf = x_loc[pack[oi]]                       # [S, ...] to owner+o
        perm = [(e, e + o) for e in range(D) if 0 <= e + o < D]
        parts.append(jax.lax.ppermute(buf, "x", perm=perm))
    if not parts:
        return jnp.zeros((0,) + x_loc.shape[1:], x_loc.dtype)
    return jnp.concatenate(parts, axis=0)           # [n_off*S, ...]


def _assemble_sharded(x: jnp.ndarray, t: ShardTables) -> jnp.ndarray:
    """[n_pad, dim, BS, BS] ordered field -> [n_pad, dim, L, L] labs,
    sharded on the block axis; comm = per-offset neighbor ppermutes
    (or one surface all-gather in audit mode).

    Overlap structure (main.cpp:864-893): the exchange is ISSUED first;
    the lab initialization and every local-only ghost row (the vast
    majority) depend only on x_loc and sit between the collective's
    start and its first consumer in the dependence graph, so the
    scheduler can hide the exchange latency behind them; only the *_r
    rows wait for the received buffer. validation/overlap_check.py
    verifies the compiled schedule actually interleaves."""
    B, L, g, dim = t.B, t.L, t.g, t.dim
    bs = L - 2 * g

    @partial(jax.shard_map, mesh=t.mesh,
             in_specs=(P("x"),) * 14, out_specs=P("x"))
    def run(x_loc, pack, src_l, sign_l, dest_sl, idx_l, w_l, dest_l,
            src_r, sign_r, dest_sr, idx_r, w_r, dest_r):
        (pack, src_l, sign_l, dest_sl, idx_l, w_l, dest_l,
         src_r, sign_r, dest_sr, idx_r, w_r, dest_r) = (
            a[0] for a in (pack, src_l, sign_l, dest_sl, idx_l, w_l,
                           dest_l, src_r, sign_r, dest_sr, idx_r, w_r,
                           dest_r))
        # 1. exchange in flight
        recv = _exchange_surface(x_loc, pack, t)
        # 2. local work: lab init + all local-only rows (x_loc only)
        flat_l = x_loc.transpose(1, 0, 2, 3).reshape(dim, -1)
        simple_l = flat_l[:, src_l].T * sign_l
        general_l = jnp.einsum("dgk,gkd->gd", flat_l[:, idx_l], w_l)
        labs = jnp.zeros((B, dim, L, L), x_loc.dtype)
        labs = labs.at[:, :, g:g + bs, g:g + bs].set(x_loc)
        lf = labs.transpose(1, 0, 2, 3).reshape(dim, -1)
        lf = jnp.concatenate(
            [lf, jnp.zeros((dim, 1), x_loc.dtype)], axis=1)
        lf = lf.at[:, dest_sl].set(simple_l.T.astype(lf.dtype))
        lf = lf.at[:, dest_l].set(general_l.T.astype(lf.dtype))
        # 3. consume the exchange: surface-dependent rows only
        blocks = jnp.concatenate([x_loc, recv], axis=0)
        flat = blocks.transpose(1, 0, 2, 3).reshape(dim, -1)
        simple_r = flat[:, src_r].T * sign_r
        general_r = jnp.einsum("dgk,gkd->gd", flat[:, idx_r], w_r)
        lf = lf.at[:, dest_sr].set(simple_r.T.astype(lf.dtype))
        lf = lf.at[:, dest_r].set(general_r.T.astype(lf.dtype))
        return lf[:, :-1].reshape(dim, B, L, L).transpose(1, 0, 2, 3)

    return run(x, t.pack, t.src_l, t.sign_l, t.dest_sl, t.idx_l, t.w_l,
               t.dest_l, t.src_r, t.sign_r, t.dest_sr, t.idx_r, t.w_r,
               t.dest_r)


# ---------------------------------------------------------------------------
# flux correction (fine-face deposits -> coarse rows) across shards
# ---------------------------------------------------------------------------

class ShardFluxCorr(NamedTuple):
    """Per-device flux-correction rows. Deposit index space per device:
    [B own blocks ++ received surface blocks] x 4 faces x BS; value
    dests are local cells [B*BS*BS] ++ 1 scratch. Exchange modes as in
    ShardTables."""

    pack: jnp.ndarray    # [D, n_off, S] own blocks whose deposits export
    dest: jnp.ndarray    # [D, M]
    cidx: jnp.ndarray    # [D, M]
    fidx1: jnp.ndarray   # [D, M]
    fidx2: jnp.ndarray   # [D, M]
    valid: jnp.ndarray   # [D, M]
    mesh: Mesh
    B: int
    S: int
    bs: int
    offsets: tuple
    mode: str

    def apply(self, values, deposits):
        return _apply_corr_sharded(values, deposits, self)


jax.tree_util.register_pytree_node(
    ShardFluxCorr,
    lambda t: ((t.pack, t.dest, t.cidx, t.fidx1, t.fidx2, t.valid),
               (t.mesh, t.B, t.S, t.bs, t.offsets, t.mode)),
    lambda aux, ch: ShardFluxCorr(*ch, *aux),
)


def shard_flux_corr(corr, n_pad: int, mesh: Mesh, bs: int,
                    dtype=np.float32,
                    mode: str = "ppermute") -> ShardFluxCorr:
    """Split (unpadded) FluxCorrTables by owning coarse block."""
    D = mesh.devices.size
    assert n_pad % D == 0
    B = n_pad // D
    bs2 = bs * bs
    fb = 4 * bs                                   # deposit cells/block
    dest = np.asarray(corr.dest, np.int64)
    cidx = np.asarray(corr.cidx, np.int64)
    f1 = np.asarray(corr.fidx1, np.int64)
    f2 = np.asarray(corr.fidx2, np.int64)
    dev = (dest // bs2) // B

    remote_by_d = []
    for d in range(D):
        ref = np.concatenate([a[dev == d] // fb for a in (cidx, f1, f2)])
        remote_by_d.append(
            np.unique(ref[(ref < d * B) | (ref >= (d + 1) * B)]))

    offsets, S, pack, g2surf = _build_exchange_plan(
        remote_by_d, D, B, n_pad, mode)

    def remap_dep(cells, d):
        blk = cells // fb
        off = cells % fb
        local = (blk >= d * B) & (blk < (d + 1) * B)
        sidx = g2surf[d, np.clip(blk, 0, n_pad - 1)]
        assert not ((~local) & (sidx < 0)).any()
        return np.where(local, (blk - d * B) * fb + off,
                        (B + sidx) * fb + off)

    M = _bucket(max(int((dev == d).sum()) for d in range(D)), lo=4)
    scratch = B * bs2
    pk_dest = np.full((D, M), scratch, np.int32)
    pk_c = np.zeros((D, M), np.int32)
    pk_f1 = np.zeros((D, M), np.int32)
    pk_f2 = np.zeros((D, M), np.int32)
    pk_v = np.zeros((D, M), dtype)
    for d in range(D):
        r = np.nonzero(dev == d)[0]
        n = len(r)
        pk_dest[d, :n] = dest[r] - d * B * bs2
        pk_c[d, :n] = remap_dep(cidx[r], d)
        pk_f1[d, :n] = remap_dep(f1[r], d)
        pk_f2[d, :n] = remap_dep(f2[r], d)
        pk_v[d, :n] = 1.0
    return _put_shard_tables(mesh, ShardFluxCorr(
        pack=pack, dest=pk_dest, cidx=pk_c, fidx1=pk_f1, fidx2=pk_f2,
        valid=pk_v, mesh=mesh, B=B, S=S, bs=bs,
        offsets=offsets, mode=mode,
    ))


def _apply_corr_sharded(values, deposits, t: ShardFluxCorr):
    B, bs = t.B, t.bs
    vec = values.ndim == 4

    @partial(jax.shard_map, mesh=t.mesh,
             in_specs=(P("x"),) * 8, out_specs=P("x"))
    def run(v_loc, d_loc, pack, dest, cidx, f1, f2, valid):
        pack, dest, cidx, f1, f2, valid = (
            a[0] for a in (pack, dest, cidx, f1, f2, valid))
        recv = _exchange_surface(d_loc, pack, t)
        dep = jnp.concatenate([d_loc, recv], axis=0)
        if vec:
            dim = v_loc.shape[1]
            df = dep.reshape(-1, dim)
            corr = valid[:, None].astype(v_loc.dtype) * (
                df[cidx] + df[f1] + df[f2])
            flat = v_loc.transpose(0, 2, 3, 1).reshape(-1, dim)
            flat = jnp.concatenate(
                [flat, jnp.zeros((1, dim), v_loc.dtype)], axis=0)
            out = flat.at[dest].add(corr)[:-1]
            return out.reshape(B, bs, bs, dim).transpose(0, 3, 1, 2)
        df = dep.reshape(-1)
        corr = valid.astype(v_loc.dtype) * (df[cidx] + df[f1] + df[f2])
        flat = jnp.concatenate(
            [v_loc.reshape(-1), jnp.zeros((1,), v_loc.dtype)])
        return flat.at[dest].add(corr)[:-1].reshape(B, bs, bs)

    return run(values, deposits, t.pack, t.dest, t.cidx, t.fidx1,
               t.fidx2, t.valid)
