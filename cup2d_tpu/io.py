"""Field dumps (reference-format), checkpoint/restore, diagnostics.

Dump writes the exact on-disk triplet of the reference's `dump()`
(`/root/reference/main.cpp:3367-3467`): per-cell quad geometry as float32
``.xyz.raw`` (4 corners x 2 coords, (x0,y0),(x0,y1),(x1,y1),(x1,y0)),
cell attributes as float32 ``.attr.raw`` (u, v, 0 triplets), and an
``.xdmf2`` XML index — byte-compatible with the reference's `post.py`
renderer and any XDMF2 reader (ParaView).

Checkpoint/restore is a capability the reference lacks entirely
(SURVEY.md §5: `dump()` has no reader, no restart path): the full
simulation state — fields, time/step counters, shape objects including
scheduler state — round-trips through one ``.npz`` + pickle pair.
"""

from __future__ import annotations

import json
import os
import pickle

import numpy as np

_XDMF_TEMPLATE = """<Xdmf
    Version="2.0">
  <Domain>
    <Grid>
      <Time Value="{time:.16e}"/>
      <Topology
          Dimensions="{ncell}"
          TopologyType="Quadrilateral"/>
     <Geometry
         GeometryType="XY">
       <DataItem
           Dimensions="{npoint} 2"
           Format="Binary">
         {xyz_base}
       </DataItem>
     </Geometry>
       <Attribute
           AttributeType="Vector"
           Name="vort"
           Center="Cell">
         <DataItem
             Dimensions="3 {ncell}"
             Format="Binary">
           {attr_base}
         </DataItem>
       </Attribute>
    </Grid>
  </Domain>
</Xdmf>
"""


def dump_uniform(path: str, time: float, vel, h: float,
                 origin=(0.0, 0.0)) -> None:
    """Write a uniform-grid velocity field in the reference dump format.

    vel: [2, Ny, Nx] (numpy or jax array). Cells are emitted in row-major
    (y-outer) order, like the reference's per-block x-inner loop.
    """
    vel = np.asarray(vel, dtype=np.float64)
    _, ny, nx = vel.shape
    ncell = ny * nx

    x0 = origin[0] + np.arange(nx) * h
    y0 = origin[1] + np.arange(ny) * h
    xg, yg = np.meshgrid(x0, y0, indexing="xy")   # [ny, nx]
    x1 = xg + h
    y1 = yg + h
    xyz = np.empty((ncell, 4, 2), dtype=np.float32)
    xyz[:, 0, 0] = xg.ravel(); xyz[:, 0, 1] = yg.ravel()
    xyz[:, 1, 0] = xg.ravel(); xyz[:, 1, 1] = y1.ravel()
    xyz[:, 2, 0] = x1.ravel(); xyz[:, 2, 1] = y1.ravel()
    xyz[:, 3, 0] = x1.ravel(); xyz[:, 3, 1] = yg.ravel()

    attr = np.zeros((ncell, 3), dtype=np.float32)
    attr[:, 0] = vel[0].ravel()
    attr[:, 1] = vel[1].ravel()

    xyz.tofile(path + ".xyz.raw")
    attr.tofile(path + ".attr.raw")
    with open(path + ".xdmf2", "w") as f:
        f.write(_XDMF_TEMPLATE.format(
            time=time, ncell=ncell, npoint=4 * ncell,
            xyz_base=os.path.basename(path) + ".xyz.raw",
            attr_base=os.path.basename(path) + ".attr.raw",
        ))


def read_dump(path: str):
    """Read back a dump triplet -> (time, xyz [ncell,4,2], attr [ncell,3]).
    The reader the reference never had."""
    import xml.etree.ElementTree as ET

    time = float(ET.parse(path + ".xdmf2").find("Domain/Grid/Time")
                 .get("Value"))
    xyz = np.fromfile(path + ".xyz.raw", dtype=np.float32).reshape(-1, 4, 2)
    attr = np.fromfile(path + ".attr.raw", dtype=np.float32).reshape(-1, 3)
    return time, xyz, attr


# ---------------------------------------------------------------------------
# checkpoint / restore (beyond-parity, SURVEY.md §5)
# ---------------------------------------------------------------------------

def save_checkpoint(dirpath: str, sim) -> None:
    """Serialize a Simulation (or UniformSim) to ``dirpath``.

    Written to a sibling temp dir and renamed into place so a crash
    mid-save (the very event checkpointing exists for) can't destroy the
    previous restart point."""
    tmp = dirpath.rstrip("/") + ".tmp"
    if os.path.exists(tmp):
        import shutil
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    fields = {k: np.asarray(v) for k, v in sim.state._asdict().items()}
    np.savez(os.path.join(tmp, "fields.npz"), **fields)
    shapes = getattr(sim, "shapes", [])
    with open(os.path.join(tmp, "shapes.pkl"), "wb") as f:
        pickle.dump(shapes, f)
    meta = {
        "time": sim.time,
        "step_count": sim.step_count,
        "config": {k: v for k, v in vars(sim.cfg).items()
                   if not k.startswith("_")},
    }
    with open(os.path.join(tmp, "meta.json"), "w") as f:
        json.dump(meta, f, indent=1)
    # swap order matters for crash safety: park the old checkpoint aside,
    # move the new one into place, THEN delete the old — at every instant
    # either dirpath or dirpath+'.old' holds a complete checkpoint (a
    # rmtree-before-replace window would leave neither; ADVICE.md r1)
    old = dirpath.rstrip("/") + ".old"
    import shutil
    if os.path.exists(old):
        shutil.rmtree(old)
    if os.path.exists(dirpath):
        os.replace(dirpath, old)
    os.replace(tmp, dirpath)
    if os.path.exists(old):
        shutil.rmtree(old)


def load_checkpoint(dirpath: str, sim) -> None:
    """Restore state saved by save_checkpoint into ``sim`` (built with a
    matching config/grid). Falls back to ``dirpath.old`` when a save
    crashed between parking the previous checkpoint and installing the
    new one."""
    import jax.numpy as jnp

    if not os.path.exists(os.path.join(dirpath, "meta.json")):
        old = dirpath.rstrip("/") + ".old"
        if os.path.exists(os.path.join(old, "meta.json")):
            dirpath = old
    with np.load(os.path.join(dirpath, "fields.npz")) as data:
        sim.state = type(sim.state)(**{
            k: jnp.asarray(data[k], dtype=sim.grid.dtype)
            for k in sim.state._fields
        })
    with open(os.path.join(dirpath, "meta.json")) as f:
        meta = json.load(f)
    sim.time = float(meta["time"])
    sim.step_count = int(meta["step_count"])
    shapes_path = os.path.join(dirpath, "shapes.pkl")
    if hasattr(sim, "shapes") and os.path.exists(shapes_path):
        with open(shapes_path, "rb") as f:
            sim.shapes[:] = pickle.load(f)
        sim._initialized = True  # fields already hold the blended state
