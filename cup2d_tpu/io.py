"""Field dumps (reference-format), checkpoint/restore, diagnostics.

Dump writes the exact on-disk triplet of the reference's `dump()`
(`/root/reference/main.cpp:3367-3467`): per-cell quad geometry as float32
``.xyz.raw`` (4 corners x 2 coords, (x0,y0),(x0,y1),(x1,y1),(x1,y0)),
cell attributes as float32 ``.attr.raw`` (u, v, 0 triplets), and an
``.xdmf2`` XML index — byte-compatible with the reference's `post.py`
renderer and any XDMF2 reader (ParaView).

Checkpoint/restore is a capability the reference lacks entirely
(SURVEY.md §5: `dump()` has no reader, no restart path): the full
simulation state — fields, time/step counters, shape objects including
scheduler state — round-trips through one ``.npz`` + pickle pair.
"""

from __future__ import annotations

import json
import os
import pickle
from typing import NamedTuple

import numpy as np


# ---------------------------------------------------------------------------
# multi-host (pod) support: the reference dumps collectively from every
# rank via MPI-IO (main.cpp:3367-3467, MPI_File_write_at_all with
# MPI_Exscan offsets; the XDMF index written by the last rank). The pod
# equivalent here: every process joins ONE gather collective that
# replicates the sharded field on hosts, then process 0 alone writes
# the files (which must live on shared storage, the same assumption
# MPI-IO makes). Single-host runs take the plain np.asarray path.
# ---------------------------------------------------------------------------

def _multihost() -> bool:
    import jax
    return jax.process_count() > 1


def _to_host_global(x) -> np.ndarray:
    """Full host copy of a (possibly cross-host-sharded) array. A
    COLLECTIVE on pods: every process must call it, in the same order.

    Must be an OWNING copy, never a view: np.asarray of a CPU-backend
    jax array is zero-copy, and the StepGuard's snapshot ring
    (resilience.py) holds these arrays across steps whose jits DONATE
    the state buffers — a view into a donated buffer dangles once XLA
    reuses the memory (observed as heap corruption in the supervised
    CLI loop)."""
    if _multihost():
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.array(x)


def _is_writer() -> bool:
    import jax
    return jax.process_index() == 0


def _sync_processes(tag: str) -> None:
    """Barrier so non-writer processes cannot race past an incomplete
    checkpoint/dump (no-op single-host)."""
    if _multihost():
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(tag)

_XDMF_TEMPLATE = """<Xdmf
    Version="2.0">
  <Domain>
    <Grid>
      <Time Value="{time:.16e}"/>
      <Topology
          Dimensions="{ncell}"
          TopologyType="Quadrilateral"/>
     <Geometry
         GeometryType="XY">
       <DataItem
           Dimensions="{npoint} 2"
           Format="Binary">
         {xyz_base}
       </DataItem>
     </Geometry>
       <Attribute
           AttributeType="Vector"
           Name="vort"
           Center="Cell">
         <DataItem
             Dimensions="3 {ncell}"
             Format="Binary">
           {attr_base}
         </DataItem>
       </Attribute>
    </Grid>
  </Domain>
</Xdmf>
"""


def _write_quads(path: str, time: float, xg, yg, x1, y1, u, v) -> None:
    """Emit the reference dump triplet from per-cell corner/value arrays
    (any shape; raveled in C order). (x0,y0),(x0,y1),(x1,y1),(x1,y0)
    corner order, (u, v, 0) attr triplets — main.cpp:3367-3467."""
    ncell = int(np.prod(np.shape(u)))
    xyz = np.empty((ncell, 4, 2), dtype=np.float32)
    xyz[:, 0, 0] = np.ravel(xg); xyz[:, 0, 1] = np.ravel(yg)
    xyz[:, 1, 0] = np.ravel(xg); xyz[:, 1, 1] = np.ravel(y1)
    xyz[:, 2, 0] = np.ravel(x1); xyz[:, 2, 1] = np.ravel(y1)
    xyz[:, 3, 0] = np.ravel(x1); xyz[:, 3, 1] = np.ravel(yg)

    attr = np.zeros((ncell, 3), dtype=np.float32)
    attr[:, 0] = np.ravel(u)
    attr[:, 1] = np.ravel(v)

    xyz.tofile(path + ".xyz.raw")
    attr.tofile(path + ".attr.raw")
    with open(path + ".xdmf2", "w") as f:
        f.write(_XDMF_TEMPLATE.format(
            time=time, ncell=ncell, npoint=4 * ncell,
            xyz_base=os.path.basename(path) + ".xyz.raw",
            attr_base=os.path.basename(path) + ".attr.raw",
        ))


def dump_uniform(path: str, time: float, vel, h: float,
                 origin=(0.0, 0.0)) -> None:
    """Write a uniform-grid velocity field in the reference dump format.

    vel: [2, Ny, Nx] (numpy or jax array). Cells are emitted in row-major
    (y-outer) order, like the reference's per-block x-inner loop.
    """
    vel = np.asarray(vel, dtype=np.float64)
    _, ny, nx = vel.shape
    x0 = origin[0] + np.arange(nx) * h
    y0 = origin[1] + np.arange(ny) * h
    xg, yg = np.meshgrid(x0, y0, indexing="xy")   # [ny, nx]
    _write_quads(path, time, xg, yg, xg + h, yg + h, vel[0], vel[1])


def dump_forest(path: str, time: float, forest, order=None) -> None:
    """Write an adaptive forest's velocity in the reference dump format.

    The format is per-cell quads precisely so resolution can vary
    (main.cpp:3367-3467 writes one quad per cell of every block); blocks
    are emitted in SFC order, cells y-outer/x-inner within each block —
    the reference's own emission order."""
    order = forest.order() if order is None else order
    bs = forest.bs
    n = len(order)
    # collective on pods (every process calls; process 0 writes below).
    # The [order] gather runs on DEVICE before the host transfer —
    # identical order arrays on every process keep it SPMD-valid, and
    # the host only ever sees the active blocks, not the padded bucket.
    vel = _to_host_global(forest.fields["vel"][np.asarray(order)])
    if not _is_writer():
        _sync_processes("dump_forest")
        return
    vel = vel.astype(np.float64)

    h = forest.cfg.h0 / (1 << forest.level[order]).astype(np.float64)
    ar = np.arange(bs, dtype=np.float64)
    x0b = forest.bi[order].astype(np.float64) * bs * h
    y0b = forest.bj[order].astype(np.float64) * bs * h
    shape = (n, bs, bs)
    xg = np.broadcast_to(
        x0b[:, None, None] + ar[None, None, :] * h[:, None, None], shape)
    yg = np.broadcast_to(
        y0b[:, None, None] + ar[None, :, None] * h[:, None, None], shape)
    x1 = xg + h[:, None, None]
    y1 = yg + h[:, None, None]
    _write_quads(path, time, xg, yg, x1, y1, vel[:, 0], vel[:, 1])
    _sync_processes("dump_forest")


def read_dump(path: str):
    """Read back a dump triplet -> (time, xyz [ncell,4,2], attr [ncell,3]).
    The reader the reference never had."""
    import xml.etree.ElementTree as ET

    time = float(ET.parse(path + ".xdmf2").find("Domain/Grid/Time")
                 .get("Value"))
    xyz = np.fromfile(path + ".xyz.raw", dtype=np.float32).reshape(-1, 4, 2)
    attr = np.fromfile(path + ".attr.raw", dtype=np.float32).reshape(-1, 3)
    return time, xyz, attr


# ---------------------------------------------------------------------------
# checkpoint / restore (beyond-parity, SURVEY.md §5)
# ---------------------------------------------------------------------------

def _gather_state(sim):
    """Collect the full checkpoint payload (host numpy fields) + meta
    dict. The shared gather half of ``save_checkpoint`` and the host
    :class:`Snapshot` machinery — one machinery, so a restore installs
    EXACTLY what a disk restore would. COLLECTIVE on pods (field
    all-gathers); every process must call it in the same order.

    This is the FULL D2H state gather — since the device snapshot ring
    (:func:`snapshot_state_device`) took over the StepGuard's rewind
    path, it runs only for disk checkpoints and post-mortems, never per
    step; ``profiling.HostCounters.state_gathers`` counts invocations
    so the CI sync guard can assert exactly that."""
    from .profiling import _note_state_gather
    _note_state_gather()
    if hasattr(sim, "sync_fields"):
        # the adaptive driver's per-step truth is its ordered working
        # state; flush it into the slot-layout dict read below
        sim.sync_fields()
    # collectives FIRST, identical order on every process
    if hasattr(sim, "forest"):
        # adaptive: topology as (level, i, j) keys + fields in SFC order
        # (slot numbering is an allocator detail that need not survive)
        f = sim.forest
        order = f.order()
        keys = np.stack([f.level[order], f.bi[order], f.bj[order]],
                        axis=1).astype(np.int32)
        # device-side [order] gather before the host transfer (active
        # blocks only; identical order on all processes keeps the
        # collective valid)
        oj = np.asarray(order)
        fields = {k: _to_host_global(v[oj])
                  for k, v in sorted(f.fields.items())}
        payload = {"__forest_keys": keys, **fields}
    else:
        payload = {k: _to_host_global(v)
                   for k, v in sim.state._asdict().items()}
    meta = {
        "time": sim.time,
        "step_count": sim.step_count,
        "config": {k: v for k, v in vars(sim.cfg).items()
                   if not k.startswith("_")},
    }
    if hasattr(sim, "times"):
        # fleet driver (fleet.FleetSim): per-member clocks must survive
        # the checkpoint — sim.time is only their min
        meta["fleet"] = {"members": int(sim.members),
                         "times": [float(t) for t in sim.times]}
    if hasattr(sim, "forest") and hasattr(sim, "_next_dt"):
        # the cached next-dt state must SURVIVE the checkpoint, or a
        # restart right after a regrid takes compute_dt's post-regrid
        # umax while the uninterrupted run takes 1.05x the cached
        # pre-regrid umax — a dt fork the bit-exact-resume contract
        # forbids. 'current' records whether each cache matched the
        # topology at save time (version counters don't survive a
        # rebuild, the boolean does).
        fver = sim.forest.version
        meta["dt_cache"] = {
            "next_dt": sim._next_dt,
            "next_dt_current": bool(
                sim._next_dt is not None
                and sim._next_dt_version == fver),
            "next_umax": (float(sim._next_umax)
                          if sim._next_umax is not None else None),
            "next_umax_current": bool(
                sim._next_umax is not None
                and getattr(sim, "_next_umax_version", -1) == fver),
        }
    if hasattr(sim, "_coarse_on"):
        # the production two-level trigger state must survive too, for
        # the same same-branch contract: a restore that re-arms the
        # trigger would run its first production solve on plain
        # block-Jacobi (up to hundreds of iterations at 1e4 blocks)
        # with a DIFFERENT preconditioner than the uninterrupted run
        # (ADVICE r4 medium). The coarse maps themselves rebuild
        # lazily (_use_coarse). A pending device iters scalar is
        # drained first so the persisted count is the latest one.
        if getattr(sim, "_last_iters_dev", None) is not None:
            import jax
            sim._last_iters = int(jax.device_get(sim._last_iters_dev))
            sim._last_iters_dev = None
        meta["poisson_trigger"] = {
            "coarse_on": bool(sim._coarse_on),
            "last_iters": int(sim._last_iters),
        }
    return payload, meta


def save_checkpoint(dirpath: str, sim) -> None:
    """Serialize a Simulation (or UniformSim) to ``dirpath``.

    Written to a sibling temp dir and renamed into place so a crash
    mid-save (the very event checkpointing exists for) can't destroy the
    previous restart point. On a multi-host pod this is a COLLECTIVE:
    every process must call it (the field gathers are all-gathers);
    process 0 alone writes, to storage all processes can read back
    (the reference's MPI-IO dump makes the same shared-FS assumption),
    and a barrier keeps the others from racing past an incomplete
    save."""
    payload, meta = _gather_state(sim)
    if not _is_writer():
        _sync_processes("save_checkpoint")
        return
    tmp = dirpath.rstrip("/") + ".tmp"
    if os.path.exists(tmp):
        import shutil
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    np.savez(os.path.join(tmp, "fields.npz"), **payload)
    shapes = getattr(sim, "shapes", [])
    with open(os.path.join(tmp, "shapes.pkl"), "wb") as f:
        pickle.dump(shapes, f)
    with open(os.path.join(tmp, "meta.json"), "w") as f:
        json.dump(meta, f, indent=1)
    # swap order matters for crash safety: park the old checkpoint aside,
    # move the new one into place, THEN delete the old — at every instant
    # either dirpath or dirpath+'.old' holds a complete checkpoint (a
    # rmtree-before-replace window would leave neither; ADVICE.md r1)
    old = dirpath.rstrip("/") + ".old"
    import shutil
    if os.path.exists(old):
        shutil.rmtree(old)
    if os.path.exists(dirpath):
        os.replace(dirpath, old)
    # fault-injection window (faults.crash_point is a no-op unless a
    # FaultPlan armed crash_in_save): the instant where NEITHER rename
    # has completed — dirpath absent, dirpath.old complete — which
    # load_checkpoint's .old fallback must cover (tested in
    # tests/test_resilience.py::test_crash_mid_save_restores_old)
    from . import faults
    faults.crash_point("checkpoint_install")
    os.replace(tmp, dirpath)
    if os.path.exists(old):
        shutil.rmtree(old)
    _sync_processes("save_checkpoint")


def load_checkpoint(dirpath: str, sim) -> None:
    """Restore state saved by save_checkpoint into ``sim`` (built with a
    matching config/grid). Falls back to ``dirpath.old`` when a save
    crashed between parking the previous checkpoint and installing the
    new one — loudly: the fallback means the run lost its newest
    restart point, which the operator (and the resilience event log)
    must know about."""
    import sys

    if not os.path.exists(os.path.join(dirpath, "meta.json")):
        old = dirpath.rstrip("/") + ".old"
        if os.path.exists(os.path.join(old, "meta.json")):
            print(f"cup2d_tpu: checkpoint {dirpath!r} is missing or "
                  f"incomplete; falling back to parked copy {old!r} "
                  "(a save crashed between park and install)",
                  file=sys.stderr)
            from .resilience import record_event
            record_event(event="checkpoint_fallback_old",
                         requested=dirpath, used=old)
            dirpath = old
    with open(os.path.join(dirpath, "meta.json")) as f:
        meta = json.load(f)
    shapes = None
    shapes_path = os.path.join(dirpath, "shapes.pkl")
    if os.path.exists(shapes_path):
        with open(shapes_path, "rb") as f:
            shapes = pickle.load(f)
    with np.load(os.path.join(dirpath, "fields.npz")) as data:
        _install_state(sim, data, meta, shapes)


def _install_state(sim, data, meta: dict, shapes) -> None:
    """Install a gathered payload (``data``: name -> array mapping, an
    open npz or a snapshot dict) + meta + shapes into ``sim``. The
    shared install half of ``load_checkpoint`` and the StepGuard's
    in-RAM rewind (resilience.py)."""
    import jax.numpy as jnp

    # counters BEFORE the field restore: the _refresh() below branches
    # on step_count (a production-stage restore with the counter still
    # at 0 would eagerly build the ~50 MB two-level coarse maps the
    # lazy-trigger design defers — code-review r5)
    sim.time = float(meta["time"])
    sim.step_count = int(meta["step_count"])
    if "__forest_keys" in data:
        f = sim.forest
        for key in list(f.blocks):
            f.release(*key)
        keys = data["__forest_keys"]
        slots = np.asarray(
            [f.allocate(int(l), int(i), int(j)) for (l, i, j) in keys],
            np.int32)
        for name in f.fields:
            vals = jnp.asarray(data[name], dtype=f.dtype)
            f.fields[name] = jnp.zeros(
                (f.capacity,) + vals.shape[1:], f.dtype
            ).at[jnp.asarray(slots)].set(vals)
        if hasattr(sim, "_ord"):
            # the restored slot fields are now the truth — discard
            # the ordered-state cache outright. Leaving _ord_dirty
            # set would make the next _ordered_state() raise, and
            # its advice (sync_fields) would overwrite the freshly
            # restored fields with pre-restore data (ADVICE r3).
            # The key is re-anchored (not None-ed) at the restored
            # (version, wver) so a field write BETWEEN restore and
            # the first step still trips the wver-moved branch that
            # drops the restored dt cache — _ordered_state()'s
            # invalidation is guarded by _ord_key being non-None.
            sim._ord = None
            sim._ord_dirty = False
            if hasattr(sim, "_refresh"):
                # refresh BEFORE anchoring: an exactly-full forest
                # makes the first _refresh_impl call _grow(), whose
                # field reassignments move wver — anchoring at the
                # pre-refresh wver would then spuriously drop the
                # restored dt cache below
                sim._refresh()
            sim._ord_key = (f.version, f.fields.wver)
    else:
        # jnp.array (copy=True), NOT jnp.asarray: asarray zero-copies a
        # matching-dtype numpy buffer on the CPU backend, and these
        # arrays become the state the stepping jits DONATE — a donated
        # alias of numpy-owned (npz-extracted, not XLA-aligned) memory
        # intermittently corrupts the heap (pre-PR2 the restart CLI
        # path crashed ~50% of runs with SIGSEGV/"corrupted
        # double-linked list"). The forest branch is safe as-is: its
        # gathered values land in fresh .at[].set outputs.
        sim.state = type(sim.state)(**{
            k: jnp.array(data[k], dtype=sim.grid.dtype)
            for k in sim.state._fields
        })
    # restore the cached next-dt state (or clear it for checkpoints
    # predating dt_cache): the restart must take the SAME dt branch as
    # the uninterrupted run (see save_checkpoint)
    for attr, cleared in (("_next_dt", None), ("_next_umax", None),
                          ("_next_dt_version", -1),
                          ("_next_umax_version", -1)):
        if hasattr(sim, attr):
            setattr(sim, attr, cleared)
    dtc = meta.get("dt_cache")
    if dtc and hasattr(sim, "forest") and hasattr(sim, "_next_dt"):
        fver = sim.forest.version
        if dtc.get("next_dt") is not None:
            sim._next_dt = float(dtc["next_dt"])
            sim._next_dt_version = fver if dtc["next_dt_current"] else -1
        if dtc.get("next_umax") is not None:
            sim._next_umax = float(dtc["next_umax"])
            sim._next_umax_version = (
                fver if dtc["next_umax_current"] else -1)
    # restored AFTER the _refresh() above (which re-arms the trigger
    # from scratch): the restart's first production solve must take the
    # same preconditioner branch as the uninterrupted run (ADVICE r4)
    trig = meta.get("poisson_trigger")
    if trig and hasattr(sim, "_coarse_on"):
        sim._coarse_on = bool(trig["coarse_on"])
        sim._last_iters = int(trig["last_iters"])
        sim._last_iters_dev = None
    fl = meta.get("fleet")
    if hasattr(sim, "times"):
        if fl is not None:
            if int(fl["members"]) != int(sim.members):
                raise ValueError(
                    f"checkpoint holds {fl['members']} fleet members, "
                    f"sim has {sim.members}")
            sim.times = np.asarray(fl["times"], np.float64)
        else:
            # pre-fleet checkpoint restored into a fleet: every member
            # inherits the shared clock
            sim.times = np.full(sim.members, float(meta["time"]))
        sim.time = float(sim.times.min())
    if hasattr(sim, "shapes") and shapes is not None:
        sim.shapes[:] = shapes
        sim._initialized = True  # fields already hold the blended state


# ---------------------------------------------------------------------------
# per-member session checkpoints (fleet serving, PR 11)
# ---------------------------------------------------------------------------
# A serving client's session must survive its slot: the FleetServer
# saves one of these on retire and a FleetRequest(checkpoint=...) admits
# from it — into ANY live fleet, any slot, bit-exact (state, the
# member's own clock, and its chained dt all round-trip losslessly; the
# f64 JSON repr round-trip is exact, and a float of an f32 lane is
# exact in double both ways). Layout mirrors save_checkpoint
# (fields.npz + meta.json, tmp -> park -> replace install) but holds
# ONE member's solo-shaped slice, so the same session can also be
# resumed standalone.

def save_member_checkpoint(dirpath: str, sim, m: int) -> None:
    """Serialize fleet member ``m``'s session to ``dirpath``."""
    st = sim.member_state(m)
    payload = {k: _to_host_global(v)
               for k, v in st._asdict().items()}
    meta = {
        "kind": "member",
        "time": float(sim.times[m]),
        "step_count": int(sim.step_count),
        "config": {k: v for k, v in vars(sim.cfg).items()
                   if not k.startswith("_")},
        "next_dt": (float(np.asarray(sim._next_dt)[m])
                    if sim._next_dt is not None else None),
    }
    if not _is_writer():
        _sync_processes("save_member_checkpoint")
        return
    import shutil
    tmp = dirpath.rstrip("/") + ".tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    np.savez(os.path.join(tmp, "fields.npz"), **payload)
    with open(os.path.join(tmp, "meta.json"), "w") as f:
        json.dump(meta, f, indent=1)
    # same crash-safe swap order as save_checkpoint: at every instant
    # either dirpath or dirpath+'.old' holds a complete session
    old = dirpath.rstrip("/") + ".old"
    if os.path.exists(old):
        shutil.rmtree(old)
    if os.path.exists(dirpath):
        os.replace(dirpath, old)
    os.replace(tmp, dirpath)
    if os.path.exists(old):
        shutil.rmtree(old)
    _sync_processes("save_member_checkpoint")


def load_member_checkpoint(dirpath: str, grid):
    """Read a member session -> (solo FlowState, meta dict). ``grid``
    supplies the dtype (the state is cast exactly as a fleet admission
    would install it); falls back to ``dirpath.old`` like
    load_checkpoint."""
    import sys

    import jax.numpy as jnp

    if not os.path.exists(os.path.join(dirpath, "meta.json")):
        old = dirpath.rstrip("/") + ".old"
        if os.path.exists(os.path.join(old, "meta.json")):
            print(f"cup2d_tpu: member checkpoint {dirpath!r} missing or "
                  f"incomplete; falling back to parked copy {old!r}",
                  file=sys.stderr)
            from .resilience import record_event
            record_event(event="checkpoint_fallback_old",
                         requested=dirpath, used=old)
            dirpath = old
    with open(os.path.join(dirpath, "meta.json")) as f:
        meta = json.load(f)
    if meta.get("kind") != "member":
        raise ValueError(
            f"{dirpath!r} is not a member session checkpoint "
            f"(kind={meta.get('kind')!r})")
    from .uniform import FlowState
    with np.load(os.path.join(dirpath, "fields.npz")) as data:
        # jnp.array (copy) for the same donation-safety reason as
        # _install_state: the admitted slice feeds executables that
        # donate their operands
        st = FlowState(**{k: jnp.array(data[k], dtype=grid.dtype)
                          for k in FlowState._fields})
    return st, meta


# ---------------------------------------------------------------------------
# device-resident snapshots (the StepGuard's HBM ring, resilience.py)
# ---------------------------------------------------------------------------
# The PR-2 host ring gathered the full state to host RAM per good step
# — a real per-step D2H tax through a TPU tunnel (the former ROADMAP
# pod gap (b)). The device snapshots keep the ring IN HBM: entries are
# donation-safe jnp copies of the state pytree (no transfer — the copy
# is enqueued on the device stream before the next step's jit donates
# the source buffers, so stream order guarantees it reads pre-donation
# data), restore is a device-to-device copy back, and the host only
# ever sees the small meta scalars. Host gathers remain exactly where
# they belong: disk checkpoints and post-mortems (_gather_state).
#
# Multi-host: jnp copies of sharded arrays are per-shard local (no
# collective, unlike the host gather) and restores reinstall the same
# sharding — the ring is pod-safe by construction.


def device_copy(x):
    """Donation-safe device-to-device copy of a jax array (host leaves
    pass through as numpy copies). ``copy=True`` guarantees a fresh XLA
    buffer: the stepping jits DONATE the state, so a ring entry must
    never alias a buffer a later dispatch invalidates — and a restored
    entry must survive being donated itself."""
    import jax
    import jax.numpy as jnp

    if isinstance(x, jax.Array):
        return jnp.array(x, copy=True)
    return np.array(x)


class DeviceSnapshot(NamedTuple):
    """One state in HBM: device copies of the field pytree + host meta.

    ``dev`` carries the dt-cache entries that are device scalars at
    capture time (the async/lagged drivers keep ``_next_dt`` /
    ``_next_umax`` / ``_last_iters_dev`` on device — float()ing them
    here would be exactly the blocking sync the ring exists to kill).
    ``meta['time']`` is patched by the StepGuard at verdict time on the
    lagged paths (the host clock is settled one step behind capture)."""

    payload: dict        # field name -> device array copy
    meta: dict           # host scalars (+ forest keys for topo restore)
    dev: dict            # dt-cache entries still on device
    shapes_pkl: object   # bytes | None
    mirror: object = None  # MirroredSnapshot | None (host-redundant tier)


def _split_cache(meta: dict, dev: dict, name: str, val) -> None:
    """File a dt-cache value under meta (host) or dev (device copy)."""
    import jax

    if isinstance(val, jax.Array):
        dev[name] = device_copy(val)
    elif val is not None:
        meta[name] = float(val)


def snapshot_state_device(sim) -> "DeviceSnapshot":
    """Capture ``sim`` into an HBM-resident snapshot: zero host
    transfers, zero collectives. The forest topology metadata is host
    numpy already (level/bi/bj); the ordered device fields are copied
    in place."""
    meta = {"time": sim.time, "step_count": sim.step_count}
    dev: dict = {}
    if hasattr(sim, "forest"):
        f = sim.forest
        ordf = sim._ordered_state()
        payload = {k: device_copy(v) for k, v in ordf.items()}
        order = sim._order
        meta.update(
            kind="forest",
            forest_version=f.version,
            n_real=int(sim._n_real),
            keys=np.stack([f.level[order], f.bi[order], f.bj[order]],
                          axis=1).astype(np.int32),
            next_dt_current=bool(sim._next_dt is not None
                                 and sim._next_dt_version == f.version),
            next_umax_current=bool(
                sim._next_umax is not None
                and getattr(sim, "_next_umax_version", -1) == f.version),
            last_iters=int(sim._last_iters),
            coarse_on=bool(sim._coarse_on),
        )
        _split_cache(meta, dev, "next_dt", sim._next_dt)
        _split_cache(meta, dev, "next_umax", sim._next_umax)
        if sim._last_iters_dev is not None:
            dev["last_iters_dev"] = device_copy(sim._last_iters_dev)
    else:
        payload = {k: device_copy(v)
                   for k, v in sim.state._asdict().items()}
        meta["kind"] = "uniform"
        if hasattr(sim, "times"):
            # fleet: per-member clocks ride the snapshot (host numpy —
            # the FleetStepGuard settles them at verdict time exactly
            # like the scalar clock)
            meta["times"] = np.array(sim.times)
        _split_cache(meta, dev, "next_dt", getattr(sim, "_next_dt", None))
    shapes = getattr(sim, "shapes", None)
    return DeviceSnapshot(
        payload=payload, meta=meta, dev=dev,
        shapes_pkl=pickle.dumps(list(shapes)) if shapes else None)


def snapshot_nbytes(snap) -> int:
    """HBM footprint of one snapshot's field payload (host metadata on
    the arrays — no sync)."""
    return int(sum(getattr(v, "nbytes", 0)
                   for v in snap.payload.values()))


def _restore_cache(sim, snap: DeviceSnapshot, fver=None) -> None:
    meta, dev = snap.meta, snap.dev
    if hasattr(sim, "_next_dt"):
        nd = dev.get("next_dt")
        sim._next_dt = (device_copy(nd) if nd is not None
                        else meta.get("next_dt"))
        if hasattr(sim, "_next_dt_version"):
            sim._next_dt_version = (
                fver if meta.get("next_dt_current") else -1)
    if hasattr(sim, "_next_umax"):
        nu = dev.get("next_umax")
        sim._next_umax = (device_copy(nu) if nu is not None
                          else meta.get("next_umax"))
        sim._next_umax_version = (
            fver if meta.get("next_umax_current") else -1)
    if hasattr(sim, "_last_iters"):
        sim._last_iters = int(meta.get("last_iters", 0))
        li = dev.get("last_iters_dev")
        sim._last_iters_dev = device_copy(li) if li is not None else None
    if hasattr(sim, "_coarse_on"):
        sim._coarse_on = bool(meta.get("coarse_on", False))


def restore_snapshot_device(sim, snap: DeviceSnapshot) -> None:
    """Install a device snapshot back into ``sim``, device-to-device.

    Same-topology restores (the only ones the StepGuard's ladder issues
    — the guard re-anchors its ring after every regrid) install copies
    of the ordered working state directly. A topology-mismatched
    snapshot falls back to the full reinstall path (_install_state fed
    device arrays — still device-to-device for the fields; only the
    dt-cache scalars are resolved to host floats there)."""
    meta = snap.meta
    if meta["kind"] == "forest":
        f = sim.forest
        if meta["forest_version"] == f.version and sim._ord is not None \
                and next(iter(snap.payload.values())).shape[0] \
                == next(iter(sim._ord.values())).shape[0]:
            sim.time = float(meta["time"])
            sim.step_count = int(meta["step_count"])
            sim._ord = {k: device_copy(v)
                        for k, v in snap.payload.items()}
            # the restored ordered state is now the truth; the slot
            # fields are stale until the next sync_fields()
            sim._ord_key = (f.version, f.fields.wver)
            sim._ord_dirty = True
            _restore_cache(sim, snap, fver=f.version)
        else:
            # topology moved since capture: rebuild through the shared
            # install half (counters-before-refresh ordering and the
            # _ord re-anchor live there). Device scalars in the cache
            # must become floats for the meta dict — one cold pull.
            import jax
            dev = {k: float(np.asarray(v))
                   for k, v in jax.device_get(snap.dev).items()}
            m2 = {
                "time": meta["time"], "step_count": meta["step_count"],
                "dt_cache": {
                    "next_dt": dev.get("next_dt", meta.get("next_dt")),
                    "next_dt_current": meta["next_dt_current"],
                    "next_umax": dev.get("next_umax",
                                         meta.get("next_umax")),
                    "next_umax_current": meta["next_umax_current"],
                },
                "poisson_trigger": {
                    "coarse_on": meta["coarse_on"],
                    "last_iters": int(dev.get("last_iters_dev",
                                              meta["last_iters"])),
                },
            }
            n_real = meta["n_real"]
            data = {"__forest_keys": meta["keys"],
                    **{k: v[:n_real] for k, v in snap.payload.items()}}
            shapes = (pickle.loads(snap.shapes_pkl)
                      if snap.shapes_pkl is not None else None)
            _install_state(sim, data, m2, shapes)
            return
    else:
        sim.time = float(meta["time"])
        sim.step_count = int(meta["step_count"])
        if hasattr(sim, "times") and "times" in meta:
            sim.times = np.array(meta["times"])
            sim.time = float(sim.times.min())
        sim.state = type(sim.state)(
            **{k: device_copy(v) for k, v in snap.payload.items()})
        _restore_cache(sim, snap)
    if getattr(sim, "shapes", None) and snap.shapes_pkl is not None:
        sim.shapes[:] = pickle.loads(snap.shapes_pkl)
        sim._initialized = True


# ---------------------------------------------------------------------------
# elastic topology resume (PR 7): snapshot coverage + re-sharding restore
# ---------------------------------------------------------------------------

def snapshot_covers(snap, lost_processes=(), *, lost_hosts=(),
                    shards_destroyed=False, mirror=True) -> bool:
    """True iff a :class:`DeviceSnapshot` can seed an elastic resume
    after a topology loss: every payload shard must still be readable
    from its OWNER, or (mirror-aware coverage, the host-redundant tier)
    from the ring neighbor that holds its mirror.

    The snapshot payload is per-shard-local device copies (the module
    note above), so the owner rule is addressability: a shard held only
    by a LOST process died with it, and on a multi-host pod each
    process only ever addresses its own shards — a real host loss
    therefore fails the owner check for any cross-host-sharded state.
    SIMULATED topologies (a single process whose virtual devices are
    grouped into fake hosts, resilience.TopologyGuard(sim_hosts=...))
    keep every shard addressable; ``shards_destroyed=True`` is the
    simulated real-loss semantics (the ``shard_loss@N`` injector zeroed
    the lost hosts' slices), voiding owner coverage the way a real loss
    would.

    Mirror coverage (``mirror=True``, the default): a shard whose owner
    died is still covered when the snapshot carries a
    :class:`MirroredSnapshot` and the lost host's ring neighbor — the
    holder of its mirror block — is itself alive. ``lost_hosts`` names
    the dead hosts by ring index (simulated hosts or real processes;
    both ride the same contiguous-block ring). Pass ``mirror=False`` to
    ask about the owner-only (plain ring) rung."""
    import jax

    lost = set(lost_processes)
    dead = set(lost_hosts) | lost
    owner_ok = not (shards_destroyed and dead)
    if owner_ok:
        for v in snap.payload.values():
            if isinstance(v, jax.Array):
                if not v.is_fully_addressable:
                    owner_ok = False
                    break
                if lost and any(d.process_index in lost
                                for d in v.sharding.device_set):
                    owner_ok = False
                    break
    if owner_ok:
        return True
    m = getattr(snap, "mirror", None)
    if not mirror or m is None or not dead:
        return False
    # every dead host's mirror holder (ring neighbor) must be alive —
    # two adjacent losses take a block AND its only mirror
    for h in dead:
        if (h + 1) % m.n_hosts in dead:
            return False
    # and the holders' mirror slices must be readable from here. On the
    # simulated drill every shard stays addressable; after a REAL
    # process loss cross-host arrays stop being fully addressable until
    # the survivor runtime re-inits (launch.reinit_distributed — the
    # ROADMAP real-pod remainder), so real-mode mirror coverage is
    # honest about that prerequisite rather than promising a read that
    # would hang
    for v in m.payload.values():
        if isinstance(v, jax.Array) and not v.is_fully_addressable:
            return False
    return True


def restore_snapshot_resharded(sim, snap: "DeviceSnapshot") -> None:
    """Install a device snapshot into a sim whose MESH changed since
    capture (resilience.StepGuard.elastic_recover, after
    ``sim.remesh``): the standard device-to-device install first (its
    copies land with the capture-time placement), then every field is
    re-placed onto the sim's current mesh — the re-shard the elastic
    resume owes the rebuilt step executable. Works for both families:
    the uniform state goes back through ``set_state`` (the placement
    authority), the forest's ordered working state through
    ``_put_ordered``. Only valid where :func:`snapshot_covers` said so
    — re-sharding reads every source shard."""
    restore_snapshot_device(sim, snap)
    if hasattr(sim, "forest"):
        if sim._ord is not None:
            sim._ord = {k: sim._put_ordered(v)
                        for k, v in sim._ord.items()}
    elif hasattr(sim, "set_state"):
        sim.set_state(sim.state)
    nd = getattr(sim, "_next_dt", None)
    if nd is not None and hasattr(nd, "sharding"):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        mesh = getattr(sim, "mesh", None)
        if mesh is not None:
            sim._next_dt = jax.device_put(
                nd, NamedSharding(mesh, PartitionSpec()))


# ---------------------------------------------------------------------------
# host-redundant mirrored snapshot tier (PR 17): in-HBM recovery from
# REAL host loss. A per-shard-local ring entry dies with its host; the
# mirror tier additionally ships every host's contiguous shard block to
# its ring neighbor at capture time (the host-granular shard_map
# ppermute of parallel.mesh.host_ring_shift, fused with the checksums
# into ONE launch — _mirror_capture_fn — and enqueued off the critical
# path before the next dispatch donates the source buffers),
# checksummed per host block ON DEVICE so the capture stays
# transfer-free and a torn/corrupt mirror is detected at restore time
# rather than installed. Lose host
# h: its block still lives (physically) on host h+1, realigned at
# restore by the global-roll identity the exchange satisfies.
# ---------------------------------------------------------------------------


class MirroredSnapshot(NamedTuple):
    """The neighbor-held redundancy of one :class:`DeviceSnapshot`.

    ``payload[k]`` has the SAME global shape and sharding as the
    snapshot field it mirrors, but globally rolled by one host-block
    width (+Nx/H columns): the slice physically resident on host h's
    devices is host h-1's data. ``sums[k]`` is the capture-time [H]
    uint32 bitwise checksum vector, one entry per physical host block,
    kept on device until a restore actually needs the comparison."""

    payload: dict        # field name -> ring-shifted device array
    sums: dict           # field name -> [H] uint32 device checksums
    n_hosts: int         # ring size at capture time


def _block_sums(v, h: int):
    """Trace body of the per-host-block checksum: reshape the x axis
    into (H, W) blocks, bitcast to uint32, wrap-sum every axis but the
    block one (mod 2**32) — any byte flip in a block moves its entry."""
    import jax
    import jax.numpy as jnp

    w = v.shape[-1] // h
    vr = v.reshape(v.shape[:-1] + (h, w))
    bits = jax.lax.bitcast_convert_type(vr, jnp.uint32)
    axes = tuple(i for i in range(bits.ndim) if i != vr.ndim - 2)
    return jnp.sum(bits, axis=axes, dtype=jnp.uint32)


_mirror_sums_jit = None


def _mirror_block_sums(x, n_hosts: int):
    """[H] uint32 bitwise checksum of ONE x-split field (see
    :func:`_block_sums`) — a device-side reduction, zero host
    transfers. Single-field unit/test entry point; multi-field callers
    must use :func:`_mirror_block_sums_tree` so all fields share one
    launch (collective-ordering contract below)."""
    global _mirror_sums_jit
    import jax

    if _mirror_sums_jit is None:
        from . import tracing
        _mirror_sums_jit = tracing.named_jit(
            "io.mirror_sums",
            jax.jit(_block_sums, static_argnums=(1,)))
    return _mirror_sums_jit(x, n_hosts)


_mirror_sums_tree_jit = None


def _mirror_block_sums_tree(payload: dict, n_hosts: int) -> dict:
    """Block checksums of EVERY field in ONE jitted launch. The sum of
    a sharded block spans that host's device pair, so each field's
    checksum compiles to per-host-group collectives; fusing the fields
    into one executable keeps those collectives in one consistent
    per-device schedule (the CPU client runs independent launches out
    of order — see :func:`mirror_snapshot`)."""
    global _mirror_sums_tree_jit
    import jax

    if _mirror_sums_tree_jit is None:
        from . import tracing
        _mirror_sums_tree_jit = tracing.named_jit(
            "io.mirror_sums_tree", jax.jit(
                lambda pl, h: {k: _block_sums(v, h)
                               for k, v in pl.items()},
                static_argnums=(1,)))
    return _mirror_sums_tree_jit(payload, n_hosts)


# fused capture executables: one per (mesh, host count, field-rank
# signature) — the capture runs per snapshot, so the jit must be
# reused, never rebuilt (same rule as mesh._RING_SHIFT_CACHE)
_MIRROR_CAPTURE_CACHE: dict = {}


def _mirror_capture_fn(mesh, n_hosts: int, sig: tuple):
    """Build (or fetch) the ONE-launch capture executable: every
    field's ring shift (a single shard_map issuing the host-granular
    ppermute per field — the same (i, i+D/H) perm as
    parallel.mesh.host_ring_shift) and every field's block checksums,
    inside one jitted program. One launch is a CORRECTNESS requirement,
    not a dispatch micro-optimisation: the shift's CollectivePermute
    and the checksums' per-host-group reductions are collectives, and
    the PJRT CPU client may execute independent launches out of order
    per device — per-field launches can interleave into a cross-launch
    rendezvous deadlock (observed in the CLI drill). Inside one
    program every device runs the same collective schedule."""
    import jax
    from jax.sharding import PartitionSpec as P
    from .parallel.mesh import _shard_map

    key = (mesh, int(n_hosts), sig)
    fn = _MIRROR_CAPTURE_CACHE.get(key)
    if fn is None:
        n_dev = mesh.devices.size
        dph = n_dev // n_hosts
        perm = [(i, (i + dph) % n_dev) for i in range(n_dev)]
        specs = {k: P(*([None] * (nd - 1) + ["x"])) for k, nd in sig}

        def _shift_all(pl):
            return {k: jax.lax.ppermute(v, "x", perm=perm)
                    for k, v in pl.items()}

        shift = _shard_map(_shift_all, mesh=mesh,
                           in_specs=(specs,), out_specs=specs)

        def impl(pl):
            mirrored = shift(pl)
            sums = {k: _block_sums(v, n_hosts)
                    for k, v in mirrored.items()}
            return mirrored, sums

        from . import tracing
        fn = tracing.named_jit("io.mirror_capture", jax.jit(impl))
        _MIRROR_CAPTURE_CACHE[key] = fn
    return fn


def mirror_snapshot(snap: DeviceSnapshot, mesh, n_hosts: int):
    """Capture the host-redundant mirror of ``snap``: ring-shift every
    payload field one host block to the right + per-block checksums,
    all in ONE launch (:func:`_mirror_capture_fn`). Device-only (no
    pulls, no host staging); returns None for payloads the tier does
    not cover (the forest family's padded block-leading layout keeps
    its disk rung for real losses — documented in the README
    recoverability matrix)."""
    import jax

    if snap.meta.get("kind") != "uniform":
        return None
    for v in snap.payload.values():
        if not (isinstance(v, jax.Array) and v.ndim >= 2
                and v.shape[-1] % n_hosts == 0):
            return None
    sig = tuple(sorted((k, v.ndim) for k, v in snap.payload.items()))
    fn = _mirror_capture_fn(mesh, int(n_hosts), sig)
    payload, sums = fn(dict(snap.payload))
    if jax.default_backend() == "cpu":
        # capture fence, CPU ONLY: the next step's dispatch reads the
        # same state arrays this capture read, so its halo collectives
        # are launch-order independent of ours — and the CPU client
        # honors no cross-launch device order, so the two can deadlock
        # at rendezvous. Settling the (tiny, [H] uint32) checksum
        # outputs settles the whole capture program before anything
        # else is enqueued. TPU streams execute launches in enqueue
        # order per device, so the fence (and the hazard) don't exist
        # there and the capture stays off the critical path.
        for s in sums.values():
            s.block_until_ready()
    return MirroredSnapshot(payload=payload, sums=sums,
                            n_hosts=int(n_hosts))


def mirror_nbytes(snap) -> int:
    """HBM footprint of a snapshot's mirror payload (0 when absent) —
    host metadata on the arrays, no sync."""
    m = getattr(snap, "mirror", None)
    if m is None:
        return 0
    return int(sum(getattr(v, "nbytes", 0) for v in m.payload.values()))


def _lost_col_mask(nx: int, lost_hosts, n_hosts: int) -> np.ndarray:
    """Boolean [nx] mask of the x-columns owned by the lost hosts
    (contiguous block h*Nx/H .. (h+1)*Nx/H, the TopologyGuard's host
    grouping)."""
    w = nx // n_hosts
    mask = np.zeros(nx, bool)
    for h in lost_hosts:
        mask[h * w:(h + 1) * w] = True
    return mask


def verify_mirror(snap: DeviceSnapshot, lost_hosts) -> list:
    """Checksum the mirror blocks an elastic restore would install (the
    lost hosts' holder blocks) against their capture-time sums. Returns
    the rejects — [] means the mirror is installable. ONE batched
    device_get of the small checksum vectors; runs only on the cold
    restore path, never per step."""
    import jax

    m = snap.mirror
    holders = sorted({(h + 1) % m.n_hosts for h in lost_hosts})
    current = _mirror_block_sums_tree(dict(m.payload), m.n_hosts)
    expect, actual = jax.device_get((m.sums, current))
    bad = []
    for k in sorted(m.payload):
        for h in holders:
            if int(expect[k][h]) != int(actual[k][h]):
                bad.append({"field": k, "block": int(h),
                            "expected": int(expect[k][h]),
                            "actual": int(actual[k][h])})
    return bad


def corrupt_mirror(snap: DeviceSnapshot) -> bool:
    """Fault injector (faults.py ``mirror_corrupt@N``): flip one
    element's bit pattern in EVERY host block of every mirror field —
    sign-flip of the block's first element, which moves that block's
    uint32 word sum whatever the value (the ±0.0 patterns differ too) —
    WITHOUT touching the stored checksums, so the next verify_mirror
    rejects whichever holder block a restore asks about. Returns False
    when the snapshot carries no mirror."""
    m = snap.mirror
    if m is None:
        return False
    payload = {}
    for k, v in m.payload.items():
        w = v.shape[-1] // m.n_hosts
        idx = (0,) * (v.ndim - 1) + (slice(None, None, w),)
        payload[k] = v.at[idx].multiply(-1.0)
    snap.mirror.payload.clear()
    snap.mirror.payload.update(payload)
    return True


def destroy_shards(sim, snaps, lost_hosts, n_hosts: int) -> list:
    """The simulated real-loss semantics (faults.py ``shard_loss@N``):
    ZERO the lost hosts' x-column blocks in the live state and in every
    snapshot — payloads AND the mirror slices physically resident on
    the dead hosts — exactly what a real host loss takes to the grave.
    This is what makes the CPU drill honest: after it runs, a resumed
    trajectory can only have come from surviving mirror blocks (or
    disk), never from the "lost" originals. Returns the replaced
    snapshot list (DeviceSnapshot is immutable); mutates ``sim.state``
    in place."""
    import jax.numpy as jnp

    def wipe(v):
        mask = jnp.asarray(_lost_col_mask(v.shape[-1], lost_hosts,
                                          n_hosts))
        return jnp.where(mask, 0, v)

    if hasattr(sim, "state") and not hasattr(sim, "forest"):
        sim.state = type(sim.state)(
            **{k: wipe(v) for k, v in sim.state._asdict().items()})
    out = []
    for s in snaps:
        payload = {k: wipe(v) for k, v in s.payload.items()}
        m = s.mirror
        if m is not None:
            m = m._replace(payload={k: wipe(v)
                                    for k, v in m.payload.items()})
        out.append(s._replace(payload=payload, mirror=m))
    return out


def restore_snapshot_mirrored(sim, snap: DeviceSnapshot,
                              lost_hosts) -> None:
    """The mirrored-ring rung: reconstruct the lost hosts' shard blocks
    from their ring neighbors' mirror slices, then install through the
    standard re-sharding restore. The mirror is globally
    roll(x, +Nx/H), so roll(mirror, -Nx/H) realigns it; the lost
    columns are taken from the realigned mirror, everything else from
    the (still-owned) primary payload. Only valid where
    :func:`snapshot_covers` said so with ``mirror=True`` and
    :func:`verify_mirror` returned no rejects."""
    import jax
    import jax.numpy as jnp

    m = snap.mirror
    # the eager jnp.roll on a still-sharded mirror compiles to
    # collective permutes; on the CPU client independent per-field
    # launches can reorder into a rendezvous deadlock (the capture-side
    # story, mirror_snapshot), so serialize them there. Cold path —
    # runs once per recovery.
    fence = jax.default_backend() == "cpu"
    payload = {}
    for k, v in snap.payload.items():
        nx = v.shape[-1]
        mask = jnp.asarray(_lost_col_mask(nx, lost_hosts, m.n_hosts))
        realigned = jnp.roll(m.payload[k], -(nx // m.n_hosts), axis=-1)
        payload[k] = jnp.where(mask, realigned, v)
        if fence:
            payload[k].block_until_ready()
    restore_snapshot_resharded(
        sim, snap._replace(payload=payload, mirror=None))
