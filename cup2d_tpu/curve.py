"""Hilbert space-filling curve over the block forest.

TPU-native re-design of the reference SFC (`/root/reference/main.cpp:342-450`):
the reference walks one (i, j) pair at a time through bit-twiddling loops; here
the same public-domain Hilbert transpose algorithm is vectorized over numpy
arrays so a whole level's worth of block coordinates is encoded in one shot
(the forest planner re-encodes every block after each regrid, so this is
host-side hot code).

Semantics matched to the reference:
  * ``forward(l, i, j)``  — (level, block coords) -> Z index along the curve,
    with the multi-base-block compaction scheme of `main.cpp:385-400` (a
    non-square bpdx x bpdy domain tiles the curve of the enclosing square and
    compacts out-of-domain quadrants, `main.cpp:6357-6376`).
  * ``inverse(Z, l)``     — Z -> (i, j)  (`main.cpp:402-420`).
  * ``encode(l, i, j)``   — globally unique, level-aware ordering id ("id2",
    `main.cpp:422-445`): blocks of mixed levels sort along the curve with
    children adjacent to their parents' position.
"""

from __future__ import annotations

import numpy as np


def _xy2d(order: int, x: np.ndarray, y: np.ndarray) -> np.ndarray:
    """Vectorized Hilbert (x, y) -> d on a 2**order x 2**order grid."""
    x = np.asarray(x, dtype=np.int64).copy()
    y = np.asarray(y, dtype=np.int64).copy()
    d = np.zeros_like(x)
    s = np.int64(1) << max(order - 1, 0)
    if order == 0:
        return d
    while s > 0:
        rx = ((x & s) > 0).astype(np.int64)
        ry = ((y & s) > 0).astype(np.int64)
        d += s * s * ((3 * rx) ^ ry)
        # rotate quadrant
        swap = ry == 0
        flip = swap & (rx == 1)
        x_f = np.where(flip, s - 1 - x, x)
        y_f = np.where(flip, s - 1 - y, y)
        x, y = np.where(swap, y_f, x_f), np.where(swap, x_f, y_f)
        s >>= 1
    return d


def _d2xy(order: int, d: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized Hilbert d -> (x, y) on a 2**order x 2**order grid."""
    t = np.asarray(d, dtype=np.int64).copy()
    x = np.zeros_like(t)
    y = np.zeros_like(t)
    s = np.int64(1)
    n = np.int64(1) << order
    while s < n:
        rx = 1 & (t // 2)
        ry = 1 & (t ^ rx)
        # rotate
        swap = ry == 0
        flip = swap & (rx == 1)
        x_f = np.where(flip, s - 1 - x, x)
        y_f = np.where(flip, s - 1 - y, y)
        x, y = np.where(swap, y_f, x_f), np.where(swap, x_f, y_f)
        x = x + s * rx
        y = y + s * ry
        t //= 4
        s <<= 1
    return x, y


class SpaceCurve:
    """Level-aware Hilbert curve over a bpdx x bpdy forest of base blocks.

    Mirrors `/root/reference/main.cpp:342-446` + its construction at
    `main.cpp:6342-6376`, fully vectorized.
    """

    def __init__(self, bpdx: int, bpdy: int, level_max: int):
        self.bpdx = int(bpdx)
        self.bpdy = int(bpdy)
        self.level_max = int(level_max)
        n_max = max(self.bpdx, self.bpdy)
        self.base_level = int(np.ceil(np.log2(n_max))) if n_max > 1 else 0

        # Compact the base-square curve onto the bpdx x bpdy sub-domain
        # (reference main.cpp:6357-6376).
        side = 1 << self.base_level
        ii, jj = np.meshgrid(np.arange(side), np.arange(side), indexing="ij")
        d_all = _xy2d(self.base_level, ii.ravel(), jj.ravel())
        inside = (ii.ravel() < self.bpdx) & (jj.ravel() < self.bpdy)
        order = np.argsort(d_all)
        inside_sorted = inside[order]
        # compacted index for each inside block, in curve order
        comp = np.cumsum(inside_sorted) - 1
        self.is_regular = bool(np.all(inside_sorted))
        # Zsave[j * bpdx + i] = compacted index of base block (i, j)
        self._zsave = np.full(self.bpdx * self.bpdy, -1, dtype=np.int64)
        self._i_inverse = np.full(self.bpdx * self.bpdy, -1, dtype=np.int64)
        self._j_inverse = np.full(self.bpdx * self.bpdy, -1, dtype=np.int64)
        io = ii.ravel()[order][inside_sorted]
        jo = jj.ravel()[order][inside_sorted]
        co = comp[inside_sorted]
        self._zsave[jo * self.bpdx + io] = co
        self._i_inverse[co] = io
        self._j_inverse[co] = jo

        # Per-level curve lengths and level offsets ("sim.levels",
        # reference main.cpp:6490-6493 — note the reference's expression
        # `bpdx*bpdy*2` then `+ bpdx*bpdy*1 << (m+1)` evaluates to
        # offsets[0] = 2*nb, offsets[m] = offsets[m-1] + (nb << (m+1)),
        # which over-allocates level 0; we use exact per-level counts,
        # a deliberate cleanup — offsets only need to be unique ranges).
        self.level_offsets = np.zeros(self.level_max + 1, dtype=np.int64)
        nb = self.bpdx * self.bpdy
        for m in range(self.level_max):
            self.level_offsets[m + 1] = self.level_offsets[m] + nb * (1 << (2 * m))

    def blocks_at(self, level: int) -> tuple[int, int]:
        """(nx, ny) block counts at a level."""
        return self.bpdx << level, self.bpdy << level

    def forward(self, level: int, i, j) -> np.ndarray:
        """Z index of block(s) (i, j) at `level`. Vectorized."""
        i = np.asarray(i, dtype=np.int64)
        j = np.asarray(j, dtype=np.int64)
        aux = np.int64(1) << level
        if self.is_regular:
            return _xy2d(level + self.base_level, i, j)
        bi = i // aux
        bj = j // aux
        z_local = _xy2d(level, i - bi * aux, j - bj * aux)
        return z_local + self._zsave[bj * self.bpdx + bi] * aux * aux

    def inverse(self, z, level: int) -> tuple[np.ndarray, np.ndarray]:
        """(i, j) of Z index/indices at `level`. Vectorized."""
        z = np.asarray(z, dtype=np.int64)
        if self.is_regular:
            return _d2xy(level + self.base_level, z)
        aux = np.int64(1) << level
        zloc = z % (aux * aux)
        x, y = _d2xy(level, zloc)
        base = z // (aux * aux)
        return x + self._i_inverse[base] * aux, y + self._j_inverse[base] * aux

    def encode(self, level, i, j) -> np.ndarray:
        """Global level-aware ordering key ("id2", main.cpp:422-445).

        Sums the curve positions of all ancestors, plus (for finer levels)
        the start of the 4-child group descended along the curve, plus the
        level itself — so mixed-level forests sort depth-first along the
        curve. Vectorized over arrays of (level, i, j).
        """
        level = np.asarray(level, dtype=np.int64)
        i = np.asarray(i, dtype=np.int64)
        j = np.asarray(j, dtype=np.int64)
        scalar = level.ndim == 0 and i.ndim == 0 and j.ndim == 0
        level, i, j = np.broadcast_arrays(
            np.atleast_1d(level), np.atleast_1d(i), np.atleast_1d(j)
        )
        level = level.copy()
        out = np.zeros_like(i)
        # ancestors (own level down to 0)
        for lvl in range(self.level_max - 1, -1, -1):
            sel = level >= lvl
            if not sel.any():
                continue
            shift = (level - lvl).clip(min=0)
            out[sel] += self.forward(lvl, (i >> shift)[sel], (j >> shift)[sel])
        # descendants: follow the first-child-group chain down the levels,
        # vectorized across all blocks at once (chain state (cx, cy) holds
        # the current-level coords of each block's descendant group).
        cx = np.zeros_like(i)
        cy = np.zeros_like(j)
        for lvl in range(1, self.level_max):
            start = level == lvl - 1
            cx[start] = 2 * i[start]
            cy[start] = 2 * j[start]
            sel = level < lvl
            if not sel.any():
                continue
            zc = self.forward(lvl, cx[sel], cy[sel])
            zc -= zc % 4
            out[sel] += zc
            x1, y1 = self.inverse(zc, lvl)
            cx[sel] = 2 * x1
            cy[sel] = 2 * y1
        out += level
        return out[0] if scalar else out
