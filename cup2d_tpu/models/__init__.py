"""Obstacle / agent models: self-propelled fish, rigid disk."""

from .disk import DiskShape  # noqa: F401
from .fish import FishShape  # noqa: F401
