"""Self-propelled fish swimmer: midline kinematics on the host.

TPU-native re-design of the reference's Shape/ongrid machinery
(`/root/reference/main.cpp:3548-3710` schedulers, `111-161` if2d_solve,
`3991-4207` the kinematics part of ongrid, `6413-6443` discretization and
width profile): the midline is O(10^2) nodes of sequential, branchy f64
work recomputed once per step — exactly the wrong shape for a TPU core and
exactly right for the host CPU (the same altitude split as SURVEY.md §7's
"AMR on host, compute on device"). Everything per-cell (SDF rasterization,
chi, integrals, penalization) runs on device from the arrays this module
produces — see cup2d_tpu/ops/obstacle.py.

All host math is numpy float64, like the reference's Real=double.
"""

from __future__ import annotations

import numpy as np


# ---------------------------------------------------------------------------
# Interpolation primitives (reference IF2D_Interpolation1D, main.cpp:3476-3547)
# ---------------------------------------------------------------------------

def natural_cubic_spline(x, y, xx):
    """Natural cubic spline through (x, y) evaluated at xx
    (main.cpp:3477-3523). x strictly increasing."""
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    n = len(x)
    y2 = np.zeros(n)
    u = np.zeros(n - 1)
    for i in range(1, n - 1):
        sig = (x[i] - x[i - 1]) / (x[i + 1] - x[i - 1])
        p = sig * y2[i - 1] + 2.0
        y2[i] = (sig - 1.0) / p
        u[i] = (y[i + 1] - y[i]) / (x[i + 1] - x[i]) - (y[i] - y[i - 1]) / (
            x[i] - x[i - 1]
        )
        u[i] = (6.0 * u[i] / (x[i + 1] - x[i - 1]) - sig * u[i - 1]) / p
    y2[n - 1] = 0.0
    for k in range(n - 2, 0, -1):
        y2[k] = y2[k] * y2[k + 1] + u[k]
    y2[0] = 0.0

    xx = np.asarray(xx, dtype=np.float64)
    klo = np.clip(np.searchsorted(x, xx, side="right") - 1, 0, n - 2)
    khi = klo + 1
    h = x[khi] - x[klo]
    a = (x[khi] - xx) / h
    b = (xx - x[klo]) / h
    return (
        a * y[klo] + b * y[khi]
        + ((a**3 - a) * y2[klo] + (b**3 - b) * y2[khi]) * (h * h) / 6.0
    )


def cubic_interp(x0, x1, x, y0, y1, dy0=0.0, dy1=0.0):
    """Hermite cubic between (x0,y0,dy0) and (x1,y1,dy1); returns (y, dy)
    (main.cpp:3524-3539). Vectorized over y0/y1/dy0."""
    xrel = x - x0
    dx = x1 - x0
    a = (dy0 + dy1) / (dx * dx) - 2.0 * (y1 - y0) / (dx * dx * dx)
    b = (-2.0 * dy0 - dy1) / dx + 3.0 * (y1 - y0) / (dx * dx)
    c = dy0
    d = y0
    y = a * xrel**3 + b * xrel**2 + c * xrel + d
    dy = 3.0 * a * xrel**2 + 2.0 * b * xrel + c
    return y, dy


# ---------------------------------------------------------------------------
# Schedulers (main.cpp:3548-3710)
# ---------------------------------------------------------------------------

class SchedulerScalar:
    """Cubic-in-time transition of one scalar (SchedulerScalar,
    main.cpp:3608-3622)."""

    def __init__(self):
        self.t0, self.t1 = -1.0, 0.0
        self.p0, self.p1 = 0.0, 0.0
        self.dp0 = 0.0

    def transition(self, t, tstart, tend, pstart, pend):
        if t < tstart or t > tend:
            return
        if tstart < self.t0:
            return
        self.t0, self.t1 = tstart, tend
        self.p0, self.p1 = pstart, pend

    def gimme(self, t):
        if t < self.t0 or self.t0 < 0:
            return self.p0, 0.0
        if t > self.t1:
            return self.p1, 0.0
        return cubic_interp(self.t0, self.t1, t, self.p0, self.p1, self.dp0, 0.0)


class SchedulerVector:
    """N control values cubic in time, natural-spline in arclength
    (SchedulerVector, main.cpp:3623-3662)."""

    def __init__(self, npoints):
        self.n = npoints
        self.t0, self.t1 = -1.0, 0.0
        self.p0 = np.zeros(npoints)
        self.p1 = np.zeros(npoints)
        self.dp0 = np.zeros(npoints)

    def transition(self, t, tstart, tend, pstart, pend):
        if t < tstart or t > tend:
            return
        if tstart < self.t0:
            return
        self.t0, self.t1 = tstart, tend
        self.p0 = np.asarray(pstart, dtype=np.float64).copy()
        self.p1 = np.asarray(pend, dtype=np.float64).copy()

    def gimme_fine(self, t, positions, positions_fine):
        """Returns (parameters_fine, dparameters_fine) at positions_fine."""
        p0f = natural_cubic_spline(positions, self.p0, positions_fine)
        p1f = natural_cubic_spline(positions, self.p1, positions_fine)
        dp0f = natural_cubic_spline(positions, self.dp0, positions_fine)
        if t < self.t0 or self.t0 < 0:
            return p0f, np.zeros_like(p0f)
        if t > self.t1:
            return p1f, np.zeros_like(p1f)
        return cubic_interp(self.t0, self.t1, t, p0f, p1f, dp0f, 0.0)


class SchedulerLearnWave:
    """Traveling-wave interpolation of RL bending actions
    (SchedulerLearnWave, main.cpp:3663-3710): control point j holds the
    j-th most recent action; the wave coordinate c = s/L - (t-t0)/Twave
    rides tailward so each action propagates head->tail."""

    def __init__(self, npoints):
        self.n = npoints
        self.t0 = -1.0
        self.p0 = np.zeros(npoints)

    def turn(self, b, t_turn):
        """Inject action b (main.cpp:3703-3710)."""
        self.t0 = t_turn
        self.p0[2:] = self.p0[:-2].copy()[: self.n - 2]
        self.p0[1] = b
        self.p0[0] = 0.0

    def gimme_fine(self, t, twave, length, positions, positions_fine):
        c = positions_fine / length - (t - self.t0) / twave
        params = np.zeros_like(positions_fine)
        dparams = np.zeros_like(positions_fine)
        below = c < positions[0]
        above = c > positions[-1]
        params[below] = self.p0[0]
        params[above] = self.p0[-1]
        mid = ~(below | above)
        if np.any(mid):
            cm = c[mid]
            j = np.clip(np.searchsorted(positions, cm, side="left"), 1,
                        self.n - 1)
            y, dy = cubic_interp(
                positions[j - 1], positions[j], cm,
                self.p0[j - 1], self.p0[j],
            )
            params[mid] = y
            dparams[mid] = -dy / twave
        return params, dparams


# ---------------------------------------------------------------------------
# Frenet midline integration (if2d_solve, main.cpp:111-161)
# ---------------------------------------------------------------------------

def if2d_solve(rS, curv, curv_dt):
    """Integrate curvature -> midline positions/velocities/normals.
    Sequential O(Nm) recurrence, numpy scalars (the reference's exact
    update order incl. per-step renormalization of ksi and nor)."""
    nm = len(rS)
    rX = np.zeros(nm); rY = np.zeros(nm)
    vX = np.zeros(nm); vY = np.zeros(nm)
    norX = np.zeros(nm); norY = np.zeros(nm)
    vNorX = np.zeros(nm); vNorY = np.zeros(nm)
    norY[0] = 1.0
    ksiX, ksiY = 1.0, 0.0
    vKsiX, vKsiY = 0.0, 0.0
    eps = np.finfo(np.float64).eps
    for i in range(1, nm):
        dksiX = curv[i - 1] * norX[i - 1]
        dksiY = curv[i - 1] * norY[i - 1]
        dnuX = -curv[i - 1] * ksiX
        dnuY = -curv[i - 1] * ksiY
        dvKsiX = curv_dt[i - 1] * norX[i - 1] + curv[i - 1] * vNorX[i - 1]
        dvKsiY = curv_dt[i - 1] * norY[i - 1] + curv[i - 1] * vNorY[i - 1]
        dvNuX = -curv_dt[i - 1] * ksiX - curv[i - 1] * vKsiX
        dvNuY = -curv_dt[i - 1] * ksiY - curv[i - 1] * vKsiY
        ds = rS[i] - rS[i - 1]
        rX[i] = rX[i - 1] + ds * ksiX
        rY[i] = rY[i - 1] + ds * ksiY
        norX[i] = norX[i - 1] + ds * dnuX
        norY[i] = norY[i - 1] + ds * dnuY
        ksiX += ds * dksiX
        ksiY += ds * dksiY
        vX[i] = vX[i - 1] + ds * vKsiX
        vY[i] = vY[i - 1] + ds * vKsiY
        vNorX[i] = vNorX[i - 1] + ds * dvNuX
        vNorY[i] = vNorY[i - 1] + ds * dvNuY
        vKsiX += ds * dvKsiX
        vKsiY += ds * dvKsiY
        d1 = ksiX * ksiX + ksiY * ksiY
        d2 = norX[i] * norX[i] + norY[i] * norY[i]
        if d1 > eps:
            f = 1.0 / np.sqrt(d1)
            ksiX *= f
            ksiY *= f
        if d2 > eps:
            f = 1.0 / np.sqrt(d2)
            norX[i] *= f
            norY[i] *= f
    return rX, rY, vX, vY, norX, norY, vNorX, vNorY


def _dds(a, b):
    """Centered d(a)/d(b) with one-sided ends (reference dds,
    main.cpp:36-45), vectorized. Zero-length intervals (coarse grids can
    produce dSref == 0 in the end-refinement ramp, where width == 0 and
    the contribution vanishes anyway) contribute 0 instead of inf."""
    out = np.empty_like(a)
    db = np.diff(b)
    fwd = np.divide(np.diff(a), db, out=np.zeros_like(db), where=db > 0)
    out[0] = fwd[0]
    out[-1] = fwd[-1]
    out[1:-1] = 0.5 * (fwd[1:] + fwd[:-1])
    return out


def _rot(ang, x, y):
    c, s = np.cos(ang), np.sin(ang)
    return c * x - s * y, s * x + c * y


class FishShape:
    """One self-propelled swimmer: geometry, schedulers, rigid + internal
    state, and the per-step midline pipeline (reference Shape +
    ongrid kinematics, main.cpp:3711-3773, 3991-4207, 6386-6446)."""

    def __init__(self, length, xpos, ypos, angle_deg, min_h,
                 phase_shift=0.0, period=1.0):
        self.length = float(length)
        self.center = np.array([xpos, ypos], dtype=np.float64)
        self.com = np.array([xpos, ypos], dtype=np.float64)
        self.orientation = float(angle_deg) * np.pi / 180.0
        self.u = 0.0
        self.v = 0.0
        self.omega = 0.0
        self.d_gm = np.zeros(2)
        self.phase_shift = float(phase_shift)
        self.theta_internal = 0.0
        self.angvel_internal = 0.0
        self.time0 = 0.0
        self.timeshift = 0.0
        self.current_period = float(period)
        self.next_period = float(period)
        self.transition_start = 0.0
        self.transition_duration = 0.1
        self.period_val = float(period)
        self.period_dif = 0.0
        self.M = 0.0
        self.J = 0.0
        self.area = 0.0
        self.free = True   # fish always move under the momentum solve

        # --- midline discretization (main.cpp:3733-3741, 6413-6425) ---
        L = self.length
        frac_refined = 0.1
        frac_mid = 1.0 - 2.0 * frac_refined
        nmid = int(np.ceil(L * frac_mid / (min_h / np.sqrt(2.0)) / 8.0)) * 8
        ds_mid = L * frac_mid / nmid
        nend = int(np.ceil(frac_refined * L * 2.0
                           / (ds_mid + 0.125 * min_h) / 4.0)) * 4
        ds_ref = frac_refined * L * 2.0 / nend - ds_mid
        if ds_ref < 0.0:
            # the reference formula (main.cpp:3736-3740) goes negative when
            # min_h is coarse relative to L (ceil overshoot) and rS would
            # run backwards; shrink Nend so the end-ramp still sums to
            # fracRefined*L with non-negative spacing — at the reference's
            # resolutions this branch never fires
            nend = max(4, int(frac_refined * L * 2.0 / ds_mid / 4.0) * 4)
            ds_ref = max(frac_refined * L * 2.0 / nend - ds_mid, 0.0)
        self.nm = nmid + 2 * nend + 1
        rs = np.zeros(self.nm)
        k = 0
        for i in range(nend):
            rs[k + 1] = rs[k] + ds_ref + (ds_mid - ds_ref) * i / (nend - 1.0)
            k += 1
        for _ in range(nmid):
            rs[k + 1] = rs[k] + ds_mid
            k += 1
        for i in range(nend):
            rs[k + 1] = rs[k] + ds_ref + (ds_mid - ds_ref) * (
                nend - i - 1) / (nend - 1.0)
            k += 1
        rs[k] = min(rs[k], L)
        self.rS = rs

        # --- width profile (main.cpp:6429-6443) ---
        sb, st = 0.04 * L, 0.95 * L
        wt, wh = 0.01 * L, 0.04 * L
        s = self.rS
        w = np.where(
            s < sb, np.sqrt(np.maximum(2.0 * wh * s - s * s, 0.0)),
            np.where(
                s < st, wh - (wh - wt) * (s - sb) / (st - sb),
                wt * (L - s) / (L - st),
            ),
        )
        self.width = np.where((s < 0) | (s > L), 0.0, w)

        self.curvature_scheduler = SchedulerVector(6)
        self.rl_bending_scheduler = SchedulerLearnWave(7)
        self.period_scheduler = SchedulerScalar()
        # seed so a first midline() call at t > transition end still sees
        # the configured period (the reference always starts at t=0 inside
        # the window, main.cpp:4030-4034; entering later would divide by 0)
        self.period_scheduler.p0 = float(period)
        self.period_scheduler.p1 = float(period)

        # outputs of the last midline() call (fish frame, internal
        # momentum removed), used by rasterization and diagnostics
        self.rX = np.zeros(self.nm)
        self.rY = np.zeros(self.nm)
        self.vX = np.zeros(self.nm)
        self.vY = np.zeros(self.nm)
        self.norX = np.zeros(self.nm)
        self.norY = np.zeros(self.nm)
        self.vNorX = np.zeros(self.nm)
        self.vNorY = np.zeros(self.nm)
        self.skin_upper = np.zeros((self.nm, 2))
        self.skin_lower = np.zeros((self.nm, 2))

    # -- rigid advection (ongrid head, main.cpp:3992-4018) --
    def advect(self, dt, extents):
        self.com[0] += dt * self.u
        self.com[1] += dt * self.v
        self.orientation += dt * self.omega
        if self.orientation > np.pi:
            self.orientation -= 2.0 * np.pi
        if self.orientation < -np.pi:
            self.orientation += 2.0 * np.pi
        c, s = np.cos(self.orientation), np.sin(self.orientation)
        self.center[0] = self.com[0] + c * self.d_gm[0] - s * self.d_gm[1]
        self.center[1] = self.com[1] + s * self.d_gm[0] + c * self.d_gm[1]
        self.theta_internal -= dt * self.angvel_internal
        if not (0 < self.center[0] < extents[0]
                and 0 < self.center[1] < extents[1]):
            raise RuntimeError("a body out of the domain")

    # -- per-step midline pipeline (main.cpp:4029-4207) --
    def midline(self, time):
        L = self.length
        nm = self.nm
        self.period_scheduler.transition(
            time, self.transition_start,
            self.transition_start + self.transition_duration,
            self.current_period, self.next_period,
        )
        self.period_val, self.period_dif = self.period_scheduler.gimme(time)
        if (self.transition_start < time
                < self.transition_start + self.transition_duration):
            self.timeshift = (time - self.time0) / self.period_val \
                + self.timeshift
            self.time0 = time

        curv_points = np.array([0.0, 0.15, 0.4, 0.65, 0.9, 1.0]) * L
        curv_values = np.array(
            [0.82014, 1.46515, 2.57136, 3.75425, 5.09147, 5.70449]) / L
        bend_points = np.array([-0.5, -0.25, 0.0, 0.25, 0.5, 0.75, 1.0])
        self.curvature_scheduler.transition(
            0.0, 0.0, 1.0, 0.01 * curv_values, curv_values)
        rC, vC = self.curvature_scheduler.gimme_fine(
            time, curv_points, self.rS)
        rB, vB = self.rl_bending_scheduler.gimme_fine(
            time, self.period_val, L, bend_points, self.rS)

        diffT = 1.0 - (time - self.time0) * self.period_dif / self.period_val
        darg = 2.0 * np.pi / self.period_val * diffT
        arg0 = (2.0 * np.pi * ((time - self.time0) / self.period_val
                               + self.timeshift)
                + np.pi * self.phase_shift)
        arg = arg0 - 2.0 * np.pi * self.rS / L
        rK = rC * (np.sin(arg) + rB)
        vK = vC * (np.sin(arg) + rB) + rC * (np.cos(arg) * darg + vB)

        rX, rY, vX, vY, norX, norY, vNorX, vNorY = if2d_solve(
            self.rS, rK, vK)

        # skins from the integrated (normalized) normals (main.cpp:4086-4097)
        nmag = np.sqrt(norX**2 + norY**2)
        skin_u = np.stack([rX + self.width * norX / nmag,
                           rY + self.width * norY / nmag], axis=1)
        skin_l = np.stack([rX - self.width * norX / nmag,
                           rY - self.width * norY / nmag], axis=1)

        # area / CoM / linear momentum integrals with the width^3
        # curvature correction (main.cpp:4098-4130)
        ds = np.empty(nm)
        ds[0] = self.rS[1] - self.rS[0]
        ds[-1] = self.rS[-1] - self.rS[-2]
        ds[1:-1] = self.rS[2:] - self.rS[:-2]
        fac1 = 2.0 * self.width
        fac2 = (2.0 * self.width**3
                * (_dds(norX, self.rS) * norY - _dds(norY, self.rS) * norX)
                / 3.0)
        area = np.sum(fac1 * ds / 2.0)
        cmx = np.sum((rX * fac1 + norX * fac2) * ds / 2.0) / area
        cmy = np.sum((rY * fac1 + norY * fac2) * ds / 2.0) / area
        lmx = np.sum((vX * fac1 + vNorX * fac2) * ds / 2.0) / area
        lmy = np.sum((vY * fac1 + vNorY * fac2) * ds / 2.0) / area
        self.area = area

        rX = rX - cmx; rY = rY - cmy
        vX = vX - lmx; vY = vY - lmy

        # angular momentum / inertia (main.cpp:4131-4170)
        fac3 = 2.0 * self.width**3 / 3.0
        tmp_m = ((rX * vY - rY * vX) * fac1
                 + (rX * vNorY - rY * vNorX + vY * norX - vX * norY) * fac2
                 + (norX * vNorY - norY * vNorX) * fac3)
        tmp_j = ((rX * rX + rY * rY) * fac1
                 + 2.0 * (rX * norX + rY * norY) * fac2 + fac3)
        ang_mom = np.sum(tmp_m * ds / 2.0)
        j_int = np.sum(tmp_j * ds / 2.0)
        self.angvel_internal = ang_mom / j_int

        # rotate into the internal-angle-free frame and remove the spin
        # (main.cpp:4171-4184)
        vX = vX + self.angvel_internal * rY
        vY = vY - self.angvel_internal * rX
        rX, rY = _rot(self.theta_internal, rX, rY)
        vX, vY = _rot(self.theta_internal, vX, vY)

        # recompute normals from midline tangents (main.cpp:4185-4203);
        # zero-length end intervals inherit the previous node's normal
        dsn = np.diff(self.rS)
        ok = dsn > 0
        inv = np.divide(1.0, dsn, out=np.zeros_like(dsn), where=ok)
        norX = np.empty(nm); norY = np.empty(nm)
        vNorX = np.empty(nm); vNorY = np.empty(nm)
        norX[:-1] = -np.diff(rY) * inv
        norY[:-1] = np.diff(rX) * inv
        vNorX[:-1] = -np.diff(vY) * inv
        vNorY[:-1] = np.diff(vX) * inv
        for arr in (norX, norY, vNorX, vNorY):
            for i in np.nonzero(~ok)[0]:
                arr[i] = arr[i - 1] if i > 0 else arr[i + 1]
        norX[-1] = norX[-2]; norY[-1] = norY[-2]
        vNorX[-1] = vNorX[-2]; vNorY[-1] = vNorY[-2]

        # skins follow the same de-meaning + rotation (main.cpp:4204-4217)
        for skin in (skin_u, skin_l):
            skin[:, 0] -= cmx
            skin[:, 1] -= cmy
            skin[:, 0], skin[:, 1] = _rot(
                self.theta_internal, skin[:, 0], skin[:, 1])

        self.rX, self.rY, self.vX, self.vY = rX, rY, vX, vY
        self.norX, self.norY, self.vNorX, self.vNorY = (
            norX, norY, vNorX, vNorY)
        self.skin_upper, self.skin_lower = skin_u, skin_l

    # -- computational-frame surface polygon for the SDF kernel --
    def surface_polygon(self):
        """Closed surface polyline in the computational frame: upper skin
        head->tail then lower skin tail->head (the same two offset curves
        the reference rasterizes per segment, main.cpp:4300-4310)."""
        pts = np.concatenate([self.skin_upper, self.skin_lower[::-1]], axis=0)
        x, y = _rot(self.orientation, pts[:, 0], pts[:, 1])
        return np.stack([x + self.center[0], y + self.center[1]], axis=1)

    def midline_comp_frame(self):
        """Midline nodes r, velocities v, normals n, normal-velocities vn
        rotated to the computational frame (velocities rotate without
        translation — changeVelocityToComputationalFrame,
        main.cpp:3975-3979)."""
        rx, ry = _rot(self.orientation, self.rX, self.rY)
        vx, vy = _rot(self.orientation, self.vX, self.vY)
        nx, ny = _rot(self.orientation, self.norX, self.norY)
        vnx, vny = _rot(self.orientation, self.vNorX, self.vNorY)
        return (np.stack([rx + self.center[0], ry + self.center[1]], axis=1),
                np.stack([vx, vy], axis=1),
                np.stack([nx, ny], axis=1),
                np.stack([vnx, vny], axis=1))
