"""Rigid disk obstacle: validation shape for the penalization machinery.

The reference only ships the fish (`-shapes` parser, main.cpp:6378-6446),
but its immersed-boundary method is shape-agnostic — the disk exercises
penalization, the momentum solve, and forces with an analytic geometry
(BASELINE.json configs 2 and 5: fixed cylinder / moving disk). It reuses
the exact same device pipeline as the fish: a surface polygon for the SDF
kernel and a midline-node table for the (identically zero) deformation
velocity.
"""

from __future__ import annotations

import numpy as np


class DiskShape:
    """Rigid disk; ``prescribed=(u, v)`` pins its motion (towed / fixed
    cylinder — the Galilean twin of an inflow past a fixed body, which the
    reference's closed free-slip box cannot express), otherwise it moves
    freely under the penalization momentum solve like any shape."""

    def __init__(self, radius, xpos, ypos, n_surface=256, prescribed=None):
        self.radius = float(radius)
        self.length = 2.0 * self.radius   # window sizing
        self.center = np.array([xpos, ypos], dtype=np.float64)
        self.com = np.array([xpos, ypos], dtype=np.float64)
        self.orientation = 0.0
        self.u, self.v, self.omega = 0.0, 0.0, 0.0
        self.d_gm = np.zeros(2)
        self.prescribed = prescribed
        if prescribed is not None:
            self.u, self.v = float(prescribed[0]), float(prescribed[1])
        self.M = 0.0
        self.J = 0.0
        self.n_surface = int(n_surface)
        self.nm = 1

    @property
    def free(self) -> bool:
        return self.prescribed is None

    def advect(self, dt, extents):
        self.com[0] += dt * self.u
        self.com[1] += dt * self.v
        self.orientation += dt * self.omega
        self.center[:] = self.com
        if not (0 < self.center[0] < extents[0]
                and 0 < self.center[1] < extents[1]):
            raise RuntimeError("a body out of the domain")

    def midline(self, time):
        pass  # rigid: no deformation kinematics

    def surface_polygon(self):
        th = np.linspace(0.0, 2.0 * np.pi, self.n_surface, endpoint=False)
        return np.stack([
            self.center[0] + self.radius * np.cos(th),
            self.center[1] + self.radius * np.sin(th),
        ], axis=1)

    def midline_comp_frame(self):
        """One node at the center with zero deformation velocity: the
        udef gather returns exactly 0 everywhere."""
        r = self.com[None, :].copy()
        z = np.zeros((1, 2))
        nor = np.array([[1.0, 0.0]])
        return r, z, nor, z

    @property
    def width(self):
        return np.array([self.radius])
