"""Per-face boundary-condition tables (ISSUE 12).

The reference (and rounds 1-11 of this reproduction) hard-code ONE box
treatment: free-slip velocity walls (mirror ghosts, zero normal flow)
paired with homogeneous-Neumann pressure. That single choice is baked
into four separate layers — the ghost paint (``uniform.pad_vector``),
the fused-BC stencil edge corrections (``ops/stencil._edge_ones``
coefficients), the Poisson operator/smoother diagonals, and the Pallas
megakernel's in-VMEM ghost synthesis. This module makes the table the
single source of truth instead:

* ``FaceBC`` — one face's treatment: ``free_slip`` | ``no_slip``
  (optionally moving wall, ``u_wall``) | ``inflow`` (Dirichlet
  velocity, uniform or parabolic profile) | ``outflow`` (convective
  outflow, extrapolated with the local advection speed).
* ``BCTable`` — four faces ``(x_lo, x_hi, y_lo, y_hi)``. Hashable and
  comparable, so drivers can key executables and the FleetServer can
  refuse mismatched admits.

Discretization contracts (zeroth-order ghost convention, matching the
legacy mirror paint — every ghost layer broadcasts from the edge line):

velocity ghosts (``pad_vector_bc``)
    free_slip   g = mirror: tangential copied, normal negated
    no_slip     g = 2*u_wall - edge        (both components)
    inflow      g = 2*u_in   - edge        (u_in possibly a profile)
    outflow     g = edge + c*(edge - inner), c = clip(u_n*dt/h, 0, 1)
                (local-advection-speed extrapolation; c=0 when no dt
                is available, i.e. plain zeroth-order extrapolation)
    periodic    g = wrap: the ghost layers are copies of the opposite
                side's interior lines (``np.roll`` semantics, ISSUE
                20). Periodic faces come in PAIRS (validate()); the
                y-then-x paint order makes the corner ghosts compose
                exactly like rolling the y-treated field along x.

pressure (per-face sign ``s``, see ``pressure_signs``)
    free_slip / no_slip / inflow -> homogeneous Neumann, s = +1
        (ghost p = edge p: the wall-normal velocity is prescribed, so
        the projection must not correct it)
    outflow -> homogeneous Dirichlet at the mid-face, s = -1
        (ghost p = -edge p => p = 0 on the face; the outflow face owns
        the pressure level, removing the all-Neumann nullspace)
    periodic -> s = 0: no edge correction at all — the missing
        neighbor is the WRAPPED interior cell, supplied by the
        periodic (roll) shifts in ops/stencil (``periodic_axes``
        flags), keeping the SPD row structure intact.

divergence (undivided central form, ``ops/stencil.divergence_bc``)
    free_slip / no_slip / inflow keep the legacy edge coefficients
    (lo=+1, hi=-1); prescribed nonzero wall-NORMAL velocity adds an
    affine constant on the edge line (-2*uw_n at lo, +2*uw_n at hi:
    the ghost 2*uw - edge splits into the legacy mirror part plus the
    constant — ``divergence_affine_bc``). outflow extrapolates
    (ghost = edge) so the coefficient flips sign (lo=-1, hi=+1).
    periodic contributes coefficient 0: the wrap shift supplies the
    opposite-side neighbor directly.

Tables with any outflow face yield a NON-singular Poisson operator:
``project_correct`` must then skip its mean-pressure removal
(``BCTable.all_neumann`` gates it). All-Neumann non-free-slip tables
(the lid-driven cavity) keep the legacy nullspace handling, and so do
periodic tables — a fully-periodic box (and the periodic channel)
retains the constant nullspace, so mean removal stays on.

The default table ``FREE_SLIP`` routes every consumer through the
UNMODIFIED legacy code paths — bit-identity with rounds 1-11 is a
tested contract, not an aspiration (tests/test_bc.py).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax.numpy as jnp

_KINDS = ("free_slip", "no_slip", "inflow", "outflow", "periodic")
# (x_lo, x_hi, y_lo, y_hi): index of the wall-NORMAL velocity component
# (vel layout [2, Ny, Nx]: component 0 = u, component 1 = v)
_FACES = ("x_lo", "x_hi", "y_lo", "y_hi")


class FaceBC(NamedTuple):
    """One domain face's boundary treatment.

    ``u_wall`` is the prescribed wall velocity (u, v) — the moving lid
    of a cavity (no_slip) or the inflow velocity (inflow); ignored for
    free_slip/outflow. ``profile`` shapes inflow along the face:
    ``uniform`` (default) or ``parabolic`` (4*s*(1-s) Poiseuille
    modulation, s in [0, 1] along the face tangent)."""

    kind: str = "free_slip"
    u_wall: Tuple[float, float] = (0.0, 0.0)
    profile: str = "uniform"


def free_slip() -> FaceBC:
    return FaceBC("free_slip")


def no_slip(u: float = 0.0, v: float = 0.0) -> FaceBC:
    return FaceBC("no_slip", (float(u), float(v)))


def dirichlet_inflow(u: float, v: float = 0.0,
                     profile: str = "uniform") -> FaceBC:
    if profile not in ("uniform", "parabolic"):
        raise ValueError(
            f"inflow profile {profile!r}: expected uniform|parabolic")
    return FaceBC("inflow", (float(u), float(v)), profile)


def convective_outflow() -> FaceBC:
    return FaceBC("outflow")


def periodic() -> FaceBC:
    return FaceBC("periodic")


class BCTable(NamedTuple):
    """Per-face boundary-condition table — the single source of truth
    for domain-edge treatment across ghost paint, divergence, Poisson
    operator/preconditioner and the projection correction."""

    x_lo: FaceBC = FaceBC()
    x_hi: FaceBC = FaceBC()
    y_lo: FaceBC = FaceBC()
    y_hi: FaceBC = FaceBC()

    @staticmethod
    def default() -> "BCTable":
        return FREE_SLIP

    def validate(self) -> "BCTable":
        for name, f in zip(_FACES, self):
            if f.kind not in _KINDS:
                raise ValueError(
                    f"BCTable.{name}: unknown kind {f.kind!r} "
                    f"(expected one of {_KINDS})")
        # periodic is a PAIRED treatment: a wrap ghost on one face
        # reads the opposite face's interior, so both must agree or
        # the paint would silently invent a half-periodic domain
        for lo, hi in (("x_lo", "x_hi"), ("y_lo", "y_hi")):
            klo = getattr(self, lo).kind
            khi = getattr(self, hi).kind
            if (klo == "periodic") != (khi == "periodic"):
                raise ValueError(
                    f"BCTable: periodic faces must be paired — "
                    f"{lo} is {klo!r} but {hi} is {khi!r}")
        return self

    @property
    def is_free_slip(self) -> bool:
        """True when every face is plain free-slip — the legacy path
        dispatch: consumers must then run the UNMODIFIED pre-BC-engine
        code, bit-identically."""
        return all(f.kind == "free_slip" for f in self)

    @property
    def all_neumann(self) -> bool:
        """True when no face is outflow: the pressure operator keeps
        its constant nullspace and project_correct keeps the legacy
        mean removal. Any outflow face pins the pressure level
        (Dirichlet row) — mean removal must be skipped."""
        return all(f.kind != "outflow" for f in self)

    @property
    def token(self) -> str:
        """Compact per-face token string for telemetry (schema v8
        ``bc_table``), order x_lo,x_hi,y_lo,y_hi — e.g. the legacy box
        is ``fs,fs,fs,fs``, the lid-driven cavity
        ``ns,ns,ns,ns(1,0)``."""
        short = {"free_slip": "fs", "no_slip": "ns",
                 "inflow": "in", "outflow": "out", "periodic": "pd"}
        toks = []
        for f in self:
            # unknown kinds pass through verbatim so diagnostics that
            # embed the token (ops.pallas_kernels.kernel_supports) can
            # name an unvalidated table without raising themselves
            t = short.get(f.kind, f.kind)
            if f.kind in ("no_slip", "inflow") and any(f.u_wall):
                u, v = f.u_wall
                t += f"({u:g},{v:g})"
            if f.kind == "inflow" and f.profile != "uniform":
                t += f"[{f.profile}]"
            toks.append(t)
        return ",".join(toks)


FREE_SLIP = BCTable()


# ---------------------------------------------------------------------------
# derived per-face coefficients for the operator tier (ops/stencil.*_bc)
# ---------------------------------------------------------------------------

def pressure_signs(bc: BCTable) -> Tuple[float, float, float, float]:
    """Per-face pressure-ghost sign (x_lo, x_hi, y_lo, y_hi):
    +1 homogeneous Neumann (ghost = edge) for prescribed-velocity
    faces, -1 homogeneous Dirichlet (ghost = -edge, p=0 mid-face) for
    convective outflow, 0 for periodic (no edge correction — the wrap
    shift supplies the opposite-side neighbor, see ``periodic_axes``).
    Feeds laplacian5_bc edge indicators, the smoother diagonal and the
    gradient edge coefficients."""
    sign = {"outflow": -1.0, "periodic": 0.0}
    return tuple(sign.get(f.kind, 1.0) for f in bc)


def divergence_coeffs(bc: BCTable) -> Tuple[float, float, float, float]:
    """Per-face edge coefficient of the wall-NORMAL velocity in the
    undivided central divergence (x_lo, x_hi, y_lo, y_hi). Mirror and
    2*uw-edge ghosts keep the legacy (+1, -1) pattern; extrapolated
    outflow ghosts flip it; periodic faces contribute 0 (wrap shift
    supplies the neighbor directly)."""
    lo = {True: -1.0, False: 1.0}

    def c(face, flip):
        if face.kind == "periodic":
            return 0.0
        return flip * lo[face.kind == "outflow"]

    return (c(bc.x_lo, 1.0), c(bc.x_hi, -1.0),
            c(bc.y_lo, 1.0), c(bc.y_hi, -1.0))


def periodic_axes(bc: BCTable) -> Tuple[bool, bool]:
    """(px, py): whether the x / y direction is periodic. Paired-face
    validation guarantees lo and hi agree, so the lo face speaks for
    the axis. Threaded to the ops/stencil ``*_bc`` forms, which switch
    the corresponding zero-ghost shifts to wrap (roll) shifts."""
    return (bc.x_lo.kind == "periodic", bc.y_lo.kind == "periodic")


def _profile_1d(face: FaceBC, n: int, dtype):
    """Inflow profile modulation along the face tangent: 1.0 (uniform)
    or 4*s*(1-s) at cell centers, s = (i+0.5)/n."""
    if face.kind != "inflow" or face.profile == "uniform":
        return None
    s = (jnp.arange(n, dtype=dtype) + 0.5) / n
    return 4.0 * s * (1.0 - s)


def divergence_affine_bc(bc: BCTable, ny: int, nx: int, dtype):
    """Constant (state-independent) edge-line contribution of prescribed
    nonzero wall-NORMAL velocities to the undivided divergence:
    -2*uw_n on each lo edge line, +2*uw_n on each hi edge line
    (no_slip / inflow faces only; the ghost 2*uw_n - edge minus the
    mirror ghost -edge differs by exactly 2*uw_n). Returns None when
    every term vanishes — notably the lid-driven cavity, whose walls
    move only TANGENTIALLY, so its divergence is identical to
    free-slip."""
    out = None
    # (face, normal component index, is_hi, axis): x faces -> u (0),
    # y faces -> v (1)
    specs = ((bc.x_lo, 0, False, "x"), (bc.x_hi, 0, True, "x"),
             (bc.y_lo, 1, False, "y"), (bc.y_hi, 1, True, "y"))
    for face, comp, is_hi, axis in specs:
        if face.kind not in ("no_slip", "inflow"):
            continue
        uw_n = face.u_wall[comp]
        if uw_n == 0.0:
            continue
        n_tan = ny if axis == "x" else nx
        prof = _profile_1d(face, n_tan, dtype)
        amp = (2.0 if is_hi else -2.0) * uw_n
        line = jnp.full((n_tan,), amp, dtype=dtype) if prof is None \
            else amp * prof
        field = jnp.zeros((ny, nx), dtype=dtype)
        if axis == "x":
            col = nx - 1 if is_hi else 0
            field = field.at[:, col].set(line)
        else:
            row = ny - 1 if is_hi else 0
            field = field.at[row, :].set(line)
        out = field if out is None else out + field
    return out


# ---------------------------------------------------------------------------
# velocity ghost paint
# ---------------------------------------------------------------------------

def _face_wall(face: FaceBC, n_tan: int, dtype, along_rows: bool):
    """Prescribed wall velocity (u, v) for a no_slip/inflow face as a
    pair of broadcastable arrays/scalars over the face line.
    ``along_rows``: the face tangent runs along rows (x faces, length
    ny(+ghosts)); else along columns (y faces, length nx)."""
    prof = _profile_1d(face, n_tan, dtype)
    uw = []
    for comp in range(2):
        val = face.u_wall[comp]
        if prof is None or val == 0.0:
            uw.append(val)
        else:
            line = val * prof
            uw.append(line[:, None] if along_rows else line[None, :])
    return uw


def _x_face_wall_padded(face: FaceBC, ny: int, g: int, dtype):
    """x-face wall velocity evaluated over the PADDED row range
    (ny + 2g): the x strips paint full rows so corners compose with
    the already-painted y ghosts. Profile coordinates are clamped to
    the face (s in [0,1]), so a parabolic profile closes to 0 at the
    wall corners."""
    if face.kind not in ("no_slip", "inflow"):
        return (0.0, 0.0)
    if face.profile == "uniform" or face.kind == "no_slip":
        return face.u_wall
    s = (jnp.arange(ny + 2 * g, dtype=dtype) - g + 0.5) / ny
    s = jnp.clip(s, 0.0, 1.0)
    prof = (4.0 * s * (1.0 - s))[:, None]
    return tuple(v * prof if v != 0.0 else 0.0 for v in face.u_wall)


def pad_vector_bc(v: jnp.ndarray, g: int, bc: BCTable, h: float,
                  dt=None) -> jnp.ndarray:
    """Per-face-table generalization of ``uniform.pad_vector``:
    zero-pad by ``g`` ghost layers, then paint each face per its
    ``FaceBC`` (kinds documented in the module docstring). Every ghost
    layer broadcasts from one painted line — the legacy zeroth-order
    mirror convention. y faces paint interior columns first; the x
    strips then read the y-padded edge columns so corner ghosts
    compose both faces' treatments, exactly like the legacy paint.

    ``dt`` feeds the convective-outflow extrapolation speed
    c = clip(u_n*dt/h, 0, 1); ``dt=None`` (diagnostics like the
    vorticity paint) degrades to plain zeroth-order extrapolation
    (c = 0). Free-slip tables dispatch to the legacy ``pad_vector``
    verbatim (bit-identity)."""
    if bc.is_free_slip:
        from .uniform import pad_vector
        return pad_vector(v, g)
    ny, nx = v.shape[-2], v.shape[-1]
    pad = [(0, 0)] * (v.ndim - 2) + [(g, g), (g, g)]
    out = jnp.pad(v, pad)

    def ghost(face, edge_u, edge_v, inner_u, inner_v, normal_comp,
              outward_sign, uw):
        # one painted line per component, broadcast over the g layers
        if face.kind == "free_slip":
            return ((-edge_u, edge_v) if normal_comp == 0
                    else (edge_u, -edge_v))
        if face.kind in ("no_slip", "inflow"):
            return (2.0 * uw[0] - edge_u, 2.0 * uw[1] - edge_v)
        # convective outflow: extrapolate with the local advection speed
        edge_n = edge_u if normal_comp == 0 else edge_v
        if dt is None:
            c = 0.0
        else:
            c = jnp.clip(outward_sign * edge_n * dt / h, 0.0, 1.0)
        return (edge_u + c * (edge_u - inner_u),
                edge_v + c * (edge_v - inner_v))

    # y faces first, interior columns (normal component = v = index 1).
    # Periodic y paints BOTH strips at once: the ghost layers are the
    # opposite side's interior lines (wrap, not a broadcast line), and
    # paired-face validation means y_lo periodic <=> y_hi periodic.
    if bc.y_lo.kind == "periodic":
        out = out.at[..., :g, g:-g].set(v[..., ny - g:, :])
        out = out.at[..., -g:, g:-g].set(v[..., :g, :])
    else:
        f = bc.y_lo
        uw = _face_wall(f, nx, v.dtype, along_rows=False) \
            if f.kind in ("no_slip", "inflow") else (0.0, 0.0)
        gu, gv = ghost(f, v[..., 0:1, :1, :], v[..., 1:2, :1, :],
                       v[..., 0:1, 1:2, :], v[..., 1:2, 1:2, :], 1, -1.0,
                       uw)
        out = out.at[..., 0:1, :g, g:-g].set(
            jnp.broadcast_to(gu, gu.shape[:-2] + (g, nx)))
        out = out.at[..., 1:2, :g, g:-g].set(
            jnp.broadcast_to(gv, gv.shape[:-2] + (g, nx)))
        f = bc.y_hi
        uw = _face_wall(f, nx, v.dtype, along_rows=False) \
            if f.kind in ("no_slip", "inflow") else (0.0, 0.0)
        gu, gv = ghost(f, v[..., 0:1, -1:, :], v[..., 1:2, -1:, :],
                       v[..., 0:1, -2:-1, :], v[..., 1:2, -2:-1, :], 1,
                       1.0, uw)
        out = out.at[..., 0:1, -g:, g:-g].set(
            jnp.broadcast_to(gu, gu.shape[:-2] + (g, nx)))
        out = out.at[..., 1:2, -g:, g:-g].set(
            jnp.broadcast_to(gv, gv.shape[:-2] + (g, nx)))

    # x faces over FULL rows, reading the y-painted columns so corners
    # compose (normal component = u = index 0). Periodic x wraps the
    # y-painted columns — identical to rolling the y-treated field
    # along x, so corner ghosts match the np.roll reference exactly.
    nyp = ny + 2 * g
    if bc.x_lo.kind == "periodic":
        out = out.at[..., :, :g].set(out[..., :, nx:nx + g])
        out = out.at[..., :, -g:].set(out[..., :, g:2 * g])
        return out
    f = bc.x_lo
    uw = _x_face_wall_padded(f, ny, g, v.dtype)
    gu, gv = ghost(f, out[..., 0:1, :, g:g + 1], out[..., 1:2, :, g:g + 1],
                   out[..., 0:1, :, g + 1:g + 2],
                   out[..., 1:2, :, g + 1:g + 2], 0, -1.0, uw)
    out = out.at[..., 0:1, :, :g].set(
        jnp.broadcast_to(gu, gu.shape[:-2] + (nyp, g)))
    out = out.at[..., 1:2, :, :g].set(
        jnp.broadcast_to(gv, gv.shape[:-2] + (nyp, g)))
    f = bc.x_hi
    uw = _x_face_wall_padded(f, ny, g, v.dtype)
    gu, gv = ghost(f, out[..., 0:1, :, -g - 1:-g],
                   out[..., 1:2, :, -g - 1:-g],
                   out[..., 0:1, :, -g - 2:-g - 1],
                   out[..., 1:2, :, -g - 2:-g - 1], 0, 1.0, uw)
    out = out.at[..., 0:1, :, -g:].set(
        jnp.broadcast_to(gu, gu.shape[:-2] + (nyp, g)))
    out = out.at[..., 1:2, :, -g:].set(
        jnp.broadcast_to(gv, gv.shape[:-2] + (nyp, g)))
    return out
