"""cup2d_tpu — a TPU-native 2D incompressible Navier–Stokes framework.

Brand-new JAX/XLA/Pallas implementation with the capabilities of
slitvinov/CUP2D (block-structured AMR, self-propelled swimmers via Brinkman
penalization, pressure projection with block-preconditioned BiCGSTAB, WENO5
advection, flux-corrected coarse–fine coupling, collisions, force/power
diagnostics), re-designed TPU-first:

* fields live in dense structure-of-arrays block forests (or a single dense
  grid on uniform runs) sharded over the device mesh with `jax.sharding`;
* ghost-cell assembly is batched gathers planned on host per regrid, not
  per-message MPI scheduling;
* stencil operators are fused XLA/Pallas kernels over all blocks at once;
* the Poisson solve is matrix-free BiCGSTAB inside `lax.while_loop` with the
  block-Cholesky preconditioner applied as one batched BS^2 x BS^2 GEMM
  (MXU work), instead of host-assembled COO + cuSPARSE;
* collectives (dt reduction, rigid-body integrals, residual norms) are XLA
  `psum`/`pmax` over ICI instead of MPI_Allreduce.

See SURVEY.md for the reference layer map this framework covers.
"""

__version__ = "0.2.0"

from .config import SimConfig, CommandlineParser, LineParser  # noqa: F401
from .curve import SpaceCurve  # noqa: F401

# heavier modules (jax-importing) are exposed lazily so `import cup2d_tpu`
# stays cheap for config-only consumers
_LAZY = {
    "AMRSim": ("cup2d_tpu.amr", "AMRSim"),
    "Simulation": ("cup2d_tpu.sim", "Simulation"),
    "UniformSim": ("cup2d_tpu.uniform", "UniformSim"),
    "UniformGrid": ("cup2d_tpu.uniform", "UniformGrid"),
    "Forest": ("cup2d_tpu.forest", "Forest"),
    "ShardedUniformSim": ("cup2d_tpu.parallel.mesh", "ShardedUniformSim"),
    "ShardedAMRSim": ("cup2d_tpu.parallel.forest_mesh", "ShardedAMRSim"),
    "PhaseTimers": ("cup2d_tpu.profiling", "PhaseTimers"),
    "enable_compilation_cache": ("cup2d_tpu.cache",
                                 "enable_compilation_cache"),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod, attr = _LAZY[name]
        return getattr(importlib.import_module(mod), attr)
    raise AttributeError(f"module 'cup2d_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_LAZY))
