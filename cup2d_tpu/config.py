"""Configuration & CLI — reference-flag-compatible.

Mirrors the reference's `CommandlineParser` (`/root/reference/main.cpp:459-501`)
semantics: ``-key value...`` pairs (multi-token values joined by spaces), bare
``-flag`` -> "true", ``+key`` force-override, and *abort on missing key* (no
silent defaults) — plus its `LineParser` (`main.cpp:6288-6305`) for the
``key=value`` obstacle descriptor lines of ``-shapes``.

On top of that sits :class:`SimConfig`, the typed config object the framework
uses internally (the reference keeps the same fields in the anonymous ``sim``
singleton, `main.cpp:309-341`).
"""

from __future__ import annotations

import dataclasses
from typing import Optional


class MissingKeyError(KeyError):
    pass


def _strtod_prefix(tok: str) -> Optional[float]:
    """Longest-numeric-prefix parse, like C strtod ("5x" -> 5.0)."""
    for n in range(len(tok), 0, -1):
        try:
            return float(tok[:n])
        except ValueError:
            continue
    return None


def _is_numeric(tok: str) -> bool:
    return _strtod_prefix(tok) is not None


class Value:
    """String-backed value with asDouble/asInt/asString accessors
    (reference `Value`, main.cpp:451-458)."""

    def __init__(self, content: str = ""):
        self.content = content

    def asDouble(self) -> float:
        # C atof semantics: longest numeric prefix, 0.0 if none.
        toks = self.content.split()
        if not toks:
            return 0.0
        v = _strtod_prefix(toks[0])
        return 0.0 if v is None else v

    def asInt(self) -> int:
        return int(self.asDouble())

    def asString(self) -> str:
        return self.content

    def __repr__(self):
        return f"Value({self.content!r})"


class CommandlineParser:
    """Reference-compatible ``-key value`` argv parser (main.cpp:459-501)."""

    def __init__(self, argv: list[str]):
        self.args: dict[str, Value] = {}
        i = 0
        while i < len(argv):
            tok = argv[i]
            if tok.startswith("-") and not _is_numeric(tok):
                values = []
                j = i + 1
                while j < len(argv):
                    nxt = argv[j]
                    if nxt.startswith("-") and not _is_numeric(nxt):
                        break
                    values.append(nxt)
                    j += 1
                content = " ".join(values) if values else "true"
                key = tok[1:]
                if key.startswith("+"):
                    self.args[key[1:]] = Value(content)
                else:
                    self.args.setdefault(key, Value(content))
                i = j
            else:
                i += 1

    def __call__(self, key: str) -> Value:
        if key not in self.args:
            raise MissingKeyError(f"runtime {key} is not set")
        return self.args[key]

    def has(self, key: str) -> bool:
        return key in self.args


class LineParser:
    """``key=value`` descriptor parser for shape lines (main.cpp:6288-6305)."""

    def __init__(self, line: str):
        self.args: dict[str, Value] = {}
        for tok in line.split():
            if "=" in tok:
                k, v = tok.split("=", 1)
                self.args[k.strip()] = Value(v.strip())

    def __call__(self, key: str) -> Value:
        if key not in self.args:
            raise MissingKeyError(f"shape descriptor {key} is not set")
        return self.args[key]

    def has(self, key: str) -> bool:
        return key in self.args


@dataclasses.dataclass
class SimConfig:
    """Typed simulation configuration (reference `sim` fields,
    main.cpp:309-341, populated at main.cpp:6321-6341)."""

    bpdx: int = 2
    bpdy: int = 1
    level_max: int = 1
    level_start: int = 0
    adapt_steps: int = 20
    rtol: float = 2.0
    ctol: float = 1.0
    extent: float = 4.0
    cfl: float = 0.5
    end_time: float = 10.0
    lam: float = 1e7
    nu: float = 4e-5
    poisson_tol: float = 1e-3
    poisson_tol_rel: float = 1e-2
    max_poisson_restarts: int = 0
    max_poisson_iterations: int = 1000
    dump_time: float = 0.0
    shapes: str = ""
    bs: int = 8               # block size (reference _BS_=8, Makefile:12)
    dtype: str = "float32"    # TPU-first default; float64 for CPU validation
    precond: bool = True
    # --- derived (computed in __post_init__) ---
    h0: float = dataclasses.field(init=False, default=0.0)
    extents: tuple = dataclasses.field(init=False, default=(0.0, 0.0))
    min_h: float = dataclasses.field(init=False, default=0.0)

    def __post_init__(self):
        # reference main.cpp:6338-6341
        self.h0 = self.extent / max(self.bpdx, self.bpdy) / self.bs
        self.extents = (self.bpdx * self.h0 * self.bs, self.bpdy * self.h0 * self.bs)
        self.min_h = self.h0 / (1 << max(self.level_max - 1, 0))

    def h_at(self, level: int) -> float:
        return self.h0 / (1 << level)

    @property
    def cells(self) -> tuple[int, int]:
        """Finest-level cell resolution cap."""
        s = 1 << max(self.level_max - 1, 0)
        return (self.bpdx * self.bs * s, self.bpdy * self.bs * s)

    @classmethod
    def from_argv(cls, argv: list[str]) -> "SimConfig":
        """Build from reference-style flags (same names as run.sh:1-22)."""
        p = CommandlineParser(argv)
        return cls(
            bpdx=p("bpdx").asInt(),
            bpdy=p("bpdy").asInt(),
            level_max=p("levelMax").asInt(),
            level_start=p("levelStart").asInt(),
            adapt_steps=p("AdaptSteps").asInt(),
            rtol=p("Rtol").asDouble(),
            ctol=p("Ctol").asDouble(),
            extent=p("extent").asDouble(),
            cfl=p("CFL").asDouble(),
            end_time=p("tend").asDouble(),
            lam=p("lambda").asDouble(),
            nu=p("nu").asDouble(),
            poisson_tol=p("poissonTol").asDouble(),
            poisson_tol_rel=p("poissonTolRel").asDouble(),
            max_poisson_restarts=p("maxPoissonRestarts").asInt(),
            max_poisson_iterations=p("maxPoissonIterations").asInt(),
            dump_time=p("tdump").asDouble(),
            shapes=p("shapes").asString() if p.has("shapes") else "",
            bs=p("bs").asInt() if p.has("bs") else 8,
            dtype=p("dtype").asString() if p.has("dtype") else "float32",
        )

    def parse_shapes(self) -> list[dict]:
        """Parse the -shapes multi-line descriptor string into dicts
        (reference main.cpp:6378-6446)."""
        out = []
        for raw_line in self.shapes.splitlines():
            for line in raw_line.split(","):
                line = line.strip()
                if not line:
                    continue
                p = LineParser(line)
                # angle/L/xpos/ypos are required, like the reference's aborting
                # accessor (main.cpp:6388-6390); the rest have defaults.
                out.append({
                    "angle": p("angle").asDouble(),
                    "length": p("L").asDouble(),
                    "xpos": p("xpos").asDouble(),
                    "ypos": p("ypos").asDouble(),
                    "T": p("T").asDouble() if p.has("T") else 1.0,
                    "kind": p("kind").asString() if p.has("kind") else "fish",
                    "radius": p("radius").asDouble() if p.has("radius") else 0.0,
                })
        return out
