"""Offline field renderer: per-cell quads colored by |attr|^2 -> PNG.

The reference ships an offline plotter with the same contract
(`/root/reference/post.py`: memmap the .xyz.raw/.attr.raw pair, draw
each cell's rectangle colored by the squared magnitude of its
attribute, save PNG at high dpi). This renderer reads the identical
byte-compatible dump format through `io.read_dump` and draws the quads
as one matplotlib PolyCollection — mixed-level AMR dumps render
naturally because the format is per-cell quads (each cell carries its
own geometry, so resolution can vary freely).

``--metrics`` switches to the telemetry reporter: summarize a run's
``metrics.jsonl`` stream (profiling.MetricsRecorder schema) as one JSON
line per file — solver iteration stats, dt/wall distributions, energy
endpoints, divergence peak, recompile/transfer counters, final AMR
shape, serving latency percentiles and the compile blame ledger.
Truncated/torn rows (a SIGKILL'd run's last line) are counted as
``truncated_records``, never raised. BENCH_*.json embeds the same
summary shape (bench.py), so a bench result and a production run read
as one trajectory.

``--trace`` exports a run's flushed span timeline (``spans.jsonl``
plus its per-process ``.pN`` siblings and rotated segments, the
flight-recorder stream — tracing.py) to Chrome/Perfetto
``trace.json``: one track per process, one per client session. Load at
https://ui.perfetto.dev or chrome://tracing.

Usage:  python -m cup2d_tpu.post out/vel.0000001234.xdmf2 [...]
        python -m cup2d_tpu.post --metrics out/metrics.jsonl [...]
        python -m cup2d_tpu.post --trace out/spans.jsonl [...]
"""

from __future__ import annotations

import json
import sys

import numpy as np

from .io import read_dump


def render(path: str, png_path: str | None = None,
           cmap: str = "viridis", dpi: int = 400) -> str:
    """Render one dump (any of the .xdmf2/.xyz.raw/.attr.raw paths or
    the bare prefix) to PNG; returns the written path."""
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    from matplotlib.collections import PolyCollection

    for suf in (".xdmf2", ".attr.raw", ".xyz.raw"):
        if path.endswith(suf):
            path = path[: -len(suf)]
    time, xyz, attr = read_dump(path)
    # xyz: [ncell, 4, 2] quad corners; attr: [ncell, 3] (u, v, 0)
    val = np.sum(attr.astype(np.float64) ** 2, axis=1)
    fig, ax = plt.subplots()
    pc = PolyCollection(xyz, array=val, cmap=cmap, edgecolors="none")
    ax.add_collection(pc)
    ax.set_xlim(float(xyz[..., 0].min()), float(xyz[..., 0].max()))
    ax.set_ylim(float(xyz[..., 1].min()), float(xyz[..., 1].max()))
    ax.set_aspect("equal")
    ax.set_title(f"t = {time:g}")
    fig.colorbar(pc, ax=ax, shrink=0.7)
    out = png_path or (path + ".png")
    fig.savefig(out, dpi=dpi, bbox_inches="tight")
    plt.close(fig)
    return out


def metrics_summary(path: str) -> dict:
    """Aggregate one metrics.jsonl stream (profiling.summarize_metrics
    + the source path). A serving run's per-client streams (schema v7:
    a ``clients/`` directory next to the metrics file, one
    ``<client>.jsonl`` each — profiling.ClientStreams) are summarized
    per client under ``clients``."""
    import os

    from .profiling import (load_metrics, load_metrics_report,
                            summarize_client, summarize_metrics)

    records, torn = load_metrics_report(path)
    out = summarize_metrics(records)
    out["truncated_records"] = torn
    out["source"] = path
    cdir = os.path.join(os.path.dirname(os.path.abspath(path)),
                        "clients")
    if os.path.isdir(cdir):
        out["clients"] = {
            fn[:-len(".jsonl")]: summarize_client(
                load_metrics(os.path.join(cdir, fn)))
            for fn in sorted(os.listdir(cdir))
            if fn.endswith(".jsonl")}
    return out


def trace_export(path: str, out_path: str | None = None) -> str:
    """Export a flight-recorder span stream to Perfetto trace JSON.

    ``path`` is the process-0 ``spans.jsonl``; per-process siblings
    (``spans.jsonl.pN`` — EventLog all_writers mode) and rotated
    segments of each are folded in automatically, so one command
    renders a whole multi-process run. Returns the written path."""
    import glob
    import os
    import re

    from .profiling import load_metrics
    from .tracing import spans_to_perfetto

    # exactly the live per-process siblings: rotated segments (.pN.M,
    # or .M on the base path) are folded in by load_metrics itself
    sibs = [p for p in sorted(glob.glob(path + ".p[0-9]*"))
            if re.fullmatch(r"\.p\d+", p[len(path):])]
    rows = []
    for p in [path] + sibs:
        try:
            rows.extend(load_metrics(p))
        except FileNotFoundError:
            continue
    trace = spans_to_perfetto(rows)
    out = out_path or os.path.join(
        os.path.dirname(os.path.abspath(path)) or ".", "trace.json")
    with open(out, "w") as f:
        json.dump(trace, f)
    return out


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if not args:
        print("usage: python -m cup2d_tpu.post <dump>[.xdmf2] ... | "
              "--metrics <metrics.jsonl> ... | "
              "--trace <spans.jsonl> ...", file=sys.stderr)
        return 2
    if args[0] == "--metrics":
        if not args[1:]:
            print("usage: python -m cup2d_tpu.post --metrics "
                  "<metrics.jsonl> ...", file=sys.stderr)
            return 2
        for a in args[1:]:
            print(json.dumps(metrics_summary(a)))
        return 0
    if args[0] == "--trace":
        if not args[1:]:
            print("usage: python -m cup2d_tpu.post --trace "
                  "<spans.jsonl> ...", file=sys.stderr)
            return 2
        for a in args[1:]:
            print(trace_export(a))
        return 0
    for a in args:
        print(render(a))
    return 0


if __name__ == "__main__":
    sys.exit(main())
