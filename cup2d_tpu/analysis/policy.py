"""graftlint policy — the sanctioned-site tables, as DATA.

Every table row is a deliberate, reasoned exception to a rule; adding
a row is a review-visible act (this file is the single source of
truth — the tests are thin wrappers over it, per ISSUE 15 there is no
second copy anywhere). Rows that stop matching reality are themselves
findings (`stale policy row`, emitted by each rule's finalize pass),
so the tables cannot rot silently.
"""

from __future__ import annotations


# ---------------------------------------------------------------------------
# env-latch — CUP2D_* env gates must be read ONCE at a sanctioned
# construction/enable point and stored, never consulted mid-run: a read
# inside a jitted body or a per-refresh helper means a mid-run env
# mutation silently flips an operator/preconditioner form at the next
# retrace or regrid (the hazard class CUP2D_SHARD_EXCHANGE and
# CUP2D_POIS/CUP2D_TWOLEVEL were each fixed for, ADVICE r5 / PR 1).
# Migrated verbatim from tests/test_env_latch.py (PR 2-13); the test is
# now a thin wrapper over this table.
# ---------------------------------------------------------------------------

# files where ANY CUP2D_* read is a sanctioned latch:
#   config.py — the typed-config construction point
ENV_LATCH_FILES = frozenset({"config.py"})

# (file, enclosing scope) -> allowed vars. Each is a construct-once /
# enable-once latch, grandfathered with its reason:
ENV_LATCH_SITES = {
    # A/B gates latched per-sim in the constructor (ADVICE r5).
    # CUP2D_POIS mode values: structured|tables|fft|fas|fas-f on the
    # forest (AMRSim validates; fas/fas-f select the forest-native FAS
    # full solver since PR 13, and fftd refuses by name — uniform-only),
    # and fas|fas-f|fftd on the uniform family (fftd, ISSUE 20: the
    # FFT-diagonalized direct solve is a VALUE of the existing latch,
    # NOT a new read site — tests/test_analysis.py pins that) —
    # the UniformGrid constructor is the ONE uniform-side latch;
    # fleet.py and the parallel/ modules read the GRID's stored latch
    # and stay env-read-free (the package walk enforces it).
    # CUP2D_PALLAS (PR 9): the forest's own fused-tier latch — the
    # lab-mode megakernel dispatch in _advect_rk2 reads the stored
    # self._kernel_tier, never the env.
    # CUP2D_PREC (ISSUE 19): the forest's SOLVER-side read — the
    # bf16-leg FAS tier (stored self._fas_leg_dtype; the cycle and
    # smoother consume the stored dtype, never the env)
    ("amr.py", "AMRSim.__init__"): {"CUP2D_POIS", "CUP2D_TWOLEVEL",
                                    "CUP2D_PALLAS", "CUP2D_PREC"},
    # per-grid constructor latches (stored as self._kernel_tier /
    # self.solver_mode+self.fas_fmg). CUP2D_PREC (PR 9) is the
    # storage-precision contract of the fused tier: ONE read site in
    # the whole package — fleet/mesh/bench consume the grid's stored
    # tier string, so a mid-run env mutation can never flip the
    # precision of a compiled step
    ("uniform.py", "UniformGrid.__init__"): {"CUP2D_PALLAS",
                                             "CUP2D_POIS",
                                             "CUP2D_PREC"},
    # the fault-injection latch (PR 7 tightened faults.py from a
    # whole-file sanction to this one scope): every injector —
    # including the elastic host_exit/host_hang tokens — parses from
    # the ONE plan FaultPlan.from_env constructs; consumers (StepGuard,
    # TopologyGuard, io's crash window) read the plan object, never the
    # env
    ("faults.py", "FaultPlan.from_env"): {"CUP2D_FAULTS"},
    # read once from ShardedAMRSim.__init__, stored as self._exchange
    ("parallel/forest_mesh.py", "_exchange_mode"):
        {"CUP2D_SHARD_EXCHANGE"},
    # windowed device tracing: latched once by the CLI before the run
    # loop (a mid-run mutation must not re-arm a finished window)
    ("profiling.py", "TraceWindow.from_env"): {"CUP2D_TRACE"},
    # enable-once process knobs (cache paths, not numerics gates)
    ("cache.py", "enable_compilation_cache"): {"CUP2D_CACHE"},
    ("native/__init__.py", "_load"): {"CUP2D_NATIVE_CACHE"},
    # flight-recorder span-ring latch (ISSUE 18): read once at
    # construction; the installed recorder stores spans_on/max_spans,
    # so a mid-run env mutation can never flip the span instrument of
    # a live run
    ("tracing.py", "FlightRecorder.from_env"): {"CUP2D_SPANS"},
}


# ---------------------------------------------------------------------------
# host-sync — the zero-extra-syncs contract (PR 3/4): the hot loop pays
# exactly ONE batched ``jax.device_get`` per step (the diag pull); every
# other device->host transfer lives on a cold path (checkpoint gather,
# post-mortem, restore) or inside the counting wrapper itself. A stray
# per-scalar pull (``float(jnp_scalar)``, ``np.asarray(tracer)``,
# ``.item()``) in a driver serializes the dispatch pipeline and used to
# be caught only AFTER the fact by the equal-device_get-count runtime
# tests. (file, scope) rows below are the sanctioned pull sites.
# ---------------------------------------------------------------------------

HOST_SYNC_SITES = {
    # THE batched scalar pull: library paths that keep diag scalars on
    # device pay one device_get for the whole set (PR 2)
    "resilience.py": {"_host_scalars"},
    # per-driver step pulls — each is the step's ONE existing batched
    # diag transfer (PR 3 folded the whole diag dict into what used to
    # fetch dt_next alone); the cold-start dt bootstrap in the same
    # scope is a once-per-run pull by design
    "sim.py": {"Simulation.step_once"},
    "uniform.py": {"UniformSim.step_once"},
    # fleet: the fused dispatch's one pull; member_step_once is the
    # guard's solo replay/retry executable — recovery is the cold path
    "fleet.py": {"FleetSim.step_once", "FleetSim.member_step_once"},
    # the forest driver's one pull per step, and _float_pull — the
    # trigger-drain helper that folds the pending poisson-iters scalar
    # into the SAME transfer precisely so no second round trip exists.
    # _pull_blockwise is the regrid path's tag-vector gather (per
    # regrid, not per step): on pods it MUST all-gather so every
    # process reaches the same host-side regrid decision
    "amr.py": {"AMRSim.step_once", "AMRSim._float_pull",
               "AMRSim._pull_blockwise"},
    # io's gather path: checkpoint/post-mortem state gathers and the
    # topology-mismatch restore fallback are cold paths that NEED the
    # transfer (HostCounters.state_gathers meters them at runtime).
    # _to_host_global is the owning-copy pull under them all — a
    # collective on pods, and deliberately np.array (not a view): the
    # snapshot ring holds its results across donated-buffer steps.
    # verify_mirror is the mirror tier's checksum pull (PR 17): ONE
    # batched device_get of the uint32 block sums, on the cold
    # elastic-recovery path only — capture-side mirroring is pure
    # device collectives and never syncs
    "io.py": {"_gather_state", "restore_snapshot_device",
              "_to_host_global", "verify_mirror"},
    # the counting wrapper itself (wraps jax.device_get to meter pulls)
    # and the recorder's library-path fallback (one pull, documented)
    "profiling.py": {"_install_hooks", "MetricsRecorder.record_step"},
    # shaped drivers: one batched device_get for all S x 19 shape
    # scalars / force rows (separate np.asarray pulls each paid a
    # blocking transfer — PR 3)
    "shapes_host.py": {"ShapeHostMixin._sync_shape_scalars",
                       "ShapeHostMixin._record_forces"},
    # flight recorder (ISSUE 18): both scopes are cold paths by
    # construction — _memory_analysis re-lowers at compile time only
    # (a run that compiles nothing never enters it) and flush drains
    # the span ring at shutdown/ring-full; neither runs per step, and
    # the zero-overhead runtime pin (equal device_gets, equal
    # jit_compiles) holds with both armed
    "tracing.py": {"_memory_analysis", "FlightRecorder.flush"},
}


# ---------------------------------------------------------------------------
# leading-dim-agnostic — the contract FleetSim (PR 5), the Pallas
# megakernel (PR 9) and the fleet server (PR 11) silently depend on:
# field operators address the trailing [-2]=y / [-1]=x axes via ``...``
# slicing and negative axis numbers ONLY, so one kernel serves uniform
# [Ny,Nx], member-batched [B,Ny,Nx] and forest-lab [N,2,H,W] operands.
# file -> checked scopes ("*" = whole file). Files/scopes NOT listed
# (e.g. the DCT base solves, forest FAS window images, Pallas kernel
# bodies with fixed block shapes) are 2-D by documented design.
# ---------------------------------------------------------------------------

LEADING_DIM_SCOPES = {
    # the stencil library is the contract's origin: every op was made
    # leading-dim agnostic in PR 5 and the megakernel shares the code
    "ops/stencil.py": ("*",),
    # the MG cycle runs member-batched (one V-cycle over [B, Ny, Nx]);
    # mg_solve is the fused fleet cycle loop; project_correct is the
    # shared epilogue over any leading shape; bicgstab carries the
    # member axis through its Krylov state
    # fft_diag_solve / FFTDiagPlan (ISSUE 20): the FFT-diagonalized
    # direct solve batches B fleet systems through the one transform
    # ([B, Ny, Nx] — trailing-axes rffts, the Thomas scans broadcast
    # the precomputed [n_s, nk] elimination constants over any lead)
    "poisson.py": ("MultigridPreconditioner", "mg_solve",
                   "project_correct", "bicgstab",
                   "fft_diag_solve", "FFTDiagPlan"),
    # host-side wrappers of the fused tier: normalize ANY leading shape
    # to the kernel's flat [L, ...] layout — the flattening itself must
    # not assume a rank (kernel bodies below them see fixed block
    # shapes and are exempt by design). _fused_substage_sharded rides
    # the same flat layout from the shard_map body (ISSUE 16)
    # fused_jacobi_sweeps / fused_block_jacobi_update (ISSUE 19): the
    # strip-smoother wrappers flatten any leading shape to the same
    # [L, ...] layout before dispatch (uniform [ny,nx], fleet
    # [B,ny,nx], forest-lab [B,bs,bs] callers share one executable)
    "ops/pallas_kernels.py": ("fused_advect_heun", "fused_lab_rhs",
                              "fused_correction", "_per_member",
                              "advect_diffuse_rhs_pallas",
                              "_fused_substage_sharded",
                              "fused_jacobi_sweeps",
                              "fused_block_jacobi_update"),
    # the sharded megakernel wrapper (ISSUE 16): flattens any leading
    # shape before entering shard_map, so fleet spatial pools (L=B) and
    # the solo sharded sim (L=1) share one executable per BC token.
    # _overlap_jacobi_sweeps_strip (ISSUE 19): the halo strip-smoother
    # form behind overlap_jacobi_sweeps' tier switch
    "parallel/shard_halo.py": ("fused_advect_heun_sharded",
                               "_overlap_jacobi_sweeps_strip"),
}


# ---------------------------------------------------------------------------
# donation-safety / retrace-hazard carry no sanctioned-site tables:
# there is never a good reason to feed a numpy buffer into a donated
# jit (the PR-2 heap-corruption class) or an f-string into a static
# operand (the zero-steady-state-recompile discipline FleetServer pins
# at runtime via jit_compiles==0). Exceptional cases use an in-line
# allow comment, so the written reason is auditable next to the code.
# ---------------------------------------------------------------------------
