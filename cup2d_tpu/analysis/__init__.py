"""graftlint — AST-based invariant checks for cup2d_tpu.

Jax-import-free by contract (nothing in this subpackage imports jax,
numpy, or the simulation modules it inspects); runs anywhere Python
runs in well under 5 s. See core.py for the framework, rules.py for
the five checkers, policy.py for the sanctioned-site tables.

Usage::

    python -m cup2d_tpu.analysis [--json] [--only env-latch] [paths]

or in-process::

    from cup2d_tpu.analysis import lint_package
    report = lint_package(only=["env-latch"])
    assert report.clean, "\\n".join(map(str, report.findings))
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from .core import (Finding, LintConfigError, Module, Report, Rule,
                   collect_package_modules, package_root, run_rules)
from .rules import ALL_RULES, RULE_NAMES, make_rules

__all__ = [
    "ALL_RULES", "RULE_NAMES", "Finding", "LintConfigError", "Module",
    "Report", "Rule", "lint_package", "lint_sources", "make_rules",
]


def lint_package(only: Optional[Sequence[str]] = None,
                 skip: Optional[Sequence[str]] = None,
                 root: Optional[str] = None) -> Report:
    """Lint the installed cup2d_tpu package (or ``root``) and return
    the Report — the in-process entry the tests wrap."""
    rules = make_rules(only=only, skip=skip)
    modules = collect_package_modules(root or package_root(),
                                      set(RULE_NAMES))
    return run_rules(modules, rules)


def lint_sources(sources: dict,
                 only: Optional[Sequence[str]] = None) -> Report:
    """Lint ``{relpath: source}`` strings — the fixture-test entry
    (snippets compiled from strings, never repo files)."""
    rules = make_rules(only=only)
    modules: List[Module] = [
        Module.parse(src, rel, set(RULE_NAMES))
        for rel, src in sorted(sources.items())]
    return run_rules(modules, rules)
