"""graftlint core — the shared AST-walk framework under the invariant
checkers (``cup2d_tpu.analysis.rules``).

The package's correctness/performance contracts are DISCIPLINES, not
types: env gates latched once, one batched device pull per step, no
numpy buffers into donated jits, no per-call-varying static operands,
leading-dim-agnostic stencils. Each rule here encodes one of those as a
static check that fails at review time instead of a runtime counter
that fires after the violation ships.

Deliberately jax-import-free and package-import-free: linting must run
anywhere Python runs (pre-commit, CI collect phase, a box with no
accelerator stack) in well under 5 s. Nothing in this subpackage may
import jax, numpy, or the simulation modules it inspects — the AST is
the only interface.

Framework pieces:

* :class:`Module` — one parsed source file: AST, per-line suppression
  table, and the import-alias map used for qualified-name resolution
  (``jnp.asarray`` -> ``jax.numpy.asarray`` under
  ``import jax.numpy as jnp``).
* :func:`iter_scoped` — AST iteration with ``Class.method.inner``
  scope strings (the same convention the env-latch sanctioned-site
  table has used since PR 2).
* :class:`Rule` — checker base: ``check(module)`` yields
  :class:`Finding`; an optional ``finalize(modules)`` pass runs once
  after every module for cross-file policy-reality checks.
* suppressions — ``# lint: allow[rule] -- reason`` on the flagged line
  (or the line directly above). The reason is REQUIRED: a bare allow is
  a :class:`LintConfigError` (rc 2 from the CLI), because an
  unexplained suppression is indistinguishable from a stale one.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple


class LintConfigError(Exception):
    """Malformed lint configuration: a suppression without a reason, an
    unknown rule name (in a suppression or ``--only/--skip``), or an
    unparseable target. Maps to CLI rc 2 — distinct from findings
    (rc 1) so CI can tell 'the tree is dirty' from 'the lint setup is
    broken'."""


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    file: str      # path relative to the lint root, posix separators
    line: int
    scope: str     # enclosing Class.method chain, "<module>" at top level
    message: str

    def __str__(self) -> str:
        return (f"{self.file}:{self.line}: [{self.rule}] "
                f"{self.scope}: {self.message}")

    def as_json(self) -> dict:
        return {"rule": self.rule, "file": self.file, "line": self.line,
                "scope": self.scope, "message": self.message}


# -- suppression comments ---------------------------------------------------

# allow-comment shape:  lint: allow[rule1,rule2] -- reason
_ALLOW_RE = re.compile(
    r"lint:\s*allow\s*\[([^\]]*)\]\s*(?:--\s*(.*))?$")
_LINT_MARK_RE = re.compile(r"#\s*lint\s*:")


def _parse_suppressions(source: str, relpath: str,
                        known_rules: Optional[Set[str]] = None,
                        ) -> Dict[int, Set[str]]:
    """Per-line suppression table from ``# lint: allow[...] -- reason``
    comments, via the tokenizer (a '# lint:' inside a string literal is
    NOT a suppression). Raises LintConfigError on a missing/empty
    reason or an unknown rule name."""
    table: Dict[int, Set[str]] = {}
    toks = tokenize.generate_tokens(io.StringIO(source).readline)
    for tok in toks:
        if tok.type != tokenize.COMMENT:
            continue
        if not _LINT_MARK_RE.search(tok.string):
            continue
        m = _ALLOW_RE.search(tok.string)
        line = tok.start[0]
        if not m:
            raise LintConfigError(
                f"{relpath}:{line}: malformed lint comment {tok.string!r}"
                " — expected '# lint: allow[rule] -- reason'")
        rules = [r.strip() for r in m.group(1).split(",") if r.strip()]
        reason = (m.group(2) or "").strip()
        if not rules:
            raise LintConfigError(
                f"{relpath}:{line}: suppression names no rule")
        if not reason:
            raise LintConfigError(
                f"{relpath}:{line}: suppression without a reason — every "
                "allow must say WHY: '# lint: allow[rule] -- reason'")
        if known_rules is not None:
            for r in rules:
                if r not in known_rules:
                    raise LintConfigError(
                        f"{relpath}:{line}: suppression names unknown "
                        f"rule {r!r} (known: {sorted(known_rules)})")
        table.setdefault(line, set()).update(rules)
    return table


# -- import-alias map / qualified names -------------------------------------

def _collect_imports(tree: ast.AST) -> Dict[str, str]:
    """alias -> dotted qualified name, over the WHOLE file (function-
    local `import jax` latches count too — several modules import jax
    lazily inside cold paths)."""
    table: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    table[a.asname] = a.name
                else:
                    # `import jax.numpy` binds `jax`; the usable dotted
                    # prefix is the top-level name
                    table[a.name.split(".")[0]] = a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            prefix = "." * node.level + mod
            for a in node.names:
                table[a.asname or a.name] = (
                    f"{prefix}.{a.name}" if prefix else a.name)
    return table


def qualified_name(node: ast.AST,
                   imports: Dict[str, str]) -> Optional[str]:
    """Resolve an Attribute/Name chain to a dotted name through the
    module's import aliases: ``jnp.asarray`` -> ``jax.numpy.asarray``,
    ``jax.device_get`` -> ``jax.device_get``. None for anything rooted
    in a call/subscript (dynamic receivers are out of scope for a
    static check)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(imports.get(node.id, node.id))
    return ".".join(reversed(parts))


# -- scope-aware iteration --------------------------------------------------

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)


def iter_scoped(tree: ast.AST) -> Iterator[Tuple[ast.AST, str]]:
    """Yield ``(node, scope)`` for every node, where scope is the
    dotted enclosing def/class chain (a def/class node reports under
    its OWN name — the env-latch table convention since PR 2) and
    ``"<module>"`` at top level."""

    def rec(node: ast.AST, scope: List[str]):
        for child in ast.iter_child_nodes(node):
            cs = scope + [child.name] if isinstance(child, _SCOPE_NODES) \
                else scope
            yield child, ".".join(cs) or "<module>"
            yield from rec(child, cs)

    yield from rec(tree, [])


def iter_functions(tree: ast.AST) -> Iterator[Tuple[ast.AST, str]]:
    """Every function/method def with its scope string (including its
    own name) — the unit of the function-local dataflow rules."""
    for node, scope in iter_scoped(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node, scope


def scope_matches(scope: str, sanctioned: Iterable[str]) -> bool:
    """True when ``scope`` is one of the sanctioned scopes or nested
    inside one (closures/comprehension helpers defined inside a
    sanctioned method inherit its sanction)."""
    for s in sanctioned:
        if scope == s or scope.startswith(s + "."):
            return True
    return False


# -- parsed module ----------------------------------------------------------

@dataclass
class Module:
    """One parsed source file plus everything rules need from it."""

    relpath: str                       # posix, relative to lint root
    source: str
    tree: ast.Module
    imports: Dict[str, str]
    suppressions: Dict[int, Set[str]]

    @classmethod
    def parse(cls, source: str, relpath: str,
              known_rules: Optional[Set[str]] = None) -> "Module":
        try:
            tree = ast.parse(source, filename=relpath)
        except SyntaxError as e:
            raise LintConfigError(
                f"{relpath}: cannot parse: {e}") from e
        return cls(
            relpath=relpath,
            source=source,
            tree=tree,
            imports=_collect_imports(tree),
            suppressions=_parse_suppressions(source, relpath, known_rules),
        )

    def suppressed(self, rule: str, line: int) -> bool:
        """A finding is suppressed by an allow comment on its own line
        or on the line directly above (multi-line calls anchor at the
        expression's first line, where the comment naturally sits)."""
        for ln in (line, line - 1):
            if rule in self.suppressions.get(ln, set()):
                return True
        return False


# -- rule base + engine -----------------------------------------------------

class Rule:
    """One invariant checker. Subclasses set ``name``/``description``
    and implement ``check``; ``finalize`` (optional) runs once after
    all modules for cross-file policy-reality checks (e.g. a
    sanctioned-site table row whose latch no longer exists)."""

    name: str = ""
    description: str = ""

    def check(self, module: Module) -> Iterable[Finding]:
        raise NotImplementedError

    def finalize(self, modules: List[Module]) -> Iterable[Finding]:
        return ()


@dataclass
class Report:
    """One lint run: what was scanned, what fired, what was allowed."""

    findings: List[Finding] = field(default_factory=list)
    suppressed: Dict[str, int] = field(default_factory=dict)
    files_scanned: int = 0
    rules_run: List[str] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.findings

    def counts(self) -> Dict[str, int]:
        out = {r: 0 for r in self.rules_run}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def as_json(self) -> dict:
        return {
            "graftlint": 1,
            "files_scanned": self.files_scanned,
            "rules": self.rules_run,
            "counts": self.counts(),
            "suppressed": dict(self.suppressed),
            "clean": self.clean,
            "findings": [f.as_json() for f in self.findings],
        }


def run_rules(modules: List[Module], rules: List[Rule]) -> Report:
    """Run every rule over every module, apply suppressions, run the
    finalize passes. Pure function of the sources — no filesystem."""
    rep = Report(files_scanned=len(modules),
                 rules_run=[r.name for r in rules])
    by_relpath = {m.relpath: m for m in modules}
    for mod in modules:
        for rule in rules:
            for f in rule.check(mod):
                if mod.suppressed(rule.name, f.line):
                    rep.suppressed[rule.name] = (
                        rep.suppressed.get(rule.name, 0) + 1)
                else:
                    rep.findings.append(f)
    for rule in rules:
        for f in rule.finalize(modules):
            mod = by_relpath.get(f.file)
            if mod is not None and mod.suppressed(rule.name, f.line):
                rep.suppressed[rule.name] = (
                    rep.suppressed.get(rule.name, 0) + 1)
            else:
                rep.findings.append(f)
    rep.findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return rep


def collect_package_modules(root: str,
                            known_rules: Optional[Set[str]] = None,
                            ) -> List[Module]:
    """Parse every ``.py`` under ``root`` (skipping ``__pycache__``),
    relpaths posix-normalized — the same walk the env-latch test has
    always done."""
    modules: List[Module] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, root).replace(os.sep, "/")
            with open(full, encoding="utf-8") as f:
                src = f.read()
            modules.append(Module.parse(src, rel, known_rules))
    return modules


def package_root() -> str:
    """The cup2d_tpu package directory (the default lint target)."""
    return os.path.normpath(
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
