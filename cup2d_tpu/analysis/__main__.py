"""``python -m cup2d_tpu.analysis`` entry point."""

from .cli import main

main()
