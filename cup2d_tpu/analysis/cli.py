"""graftlint CLI — ``python -m cup2d_tpu.analysis``.

rc semantics (pinned by tests/test_analysis.py the way bench's smoke
test pins the bench CLI):

* 0 — clean: no unsuppressed findings
* 1 — findings: the tree violates an invariant
* 2 — config error: malformed suppression, unknown rule, unparseable
  target (distinct so CI can tell 'dirty tree' from 'broken setup')

``--json`` emits ONE line (machine-readable, greppable from CI logs);
the default human format is one finding per line plus a summary.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from .core import (LintConfigError, Module, Report,
                   collect_package_modules, package_root, run_rules)
from .rules import ALL_RULES, RULE_NAMES, make_rules


def _split_rules(value: str) -> List[str]:
    return [v.strip() for v in value.split(",") if v.strip()]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m cup2d_tpu.analysis",
        description=("graftlint — AST invariant checks for cup2d_tpu "
                     "(jax-import-free; rc 0 clean / 1 findings / "
                     "2 config error)"))
    p.add_argument("paths", nargs="*",
                   help="files or directories to lint (default: the "
                        "cup2d_tpu package)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit one JSON line instead of human output")
    p.add_argument("--only", type=_split_rules, default=None,
                   metavar="RULES",
                   help="comma-separated rule names to run exclusively")
    p.add_argument("--skip", type=_split_rules, default=None,
                   metavar="RULES",
                   help="comma-separated rule names to skip")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit 0")
    return p


def _collect(paths: List[str], known) -> List[Module]:
    if not paths:
        return collect_package_modules(package_root(), known)
    modules: List[Module] = []
    for path in paths:
        if os.path.isdir(path):
            modules.extend(collect_package_modules(path, known))
        elif os.path.isfile(path):
            with open(path, encoding="utf-8") as f:
                src = f.read()
            rel = os.path.basename(path)
            modules.append(Module.parse(src, rel, known))
        else:
            raise LintConfigError(f"no such lint target: {path}")
    return modules


def run(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for cls in ALL_RULES:
            print(f"{cls.name:18s} {cls.description}")
        return 0
    try:
        rules = make_rules(only=args.only, skip=args.skip)
        known = set(RULE_NAMES)
        modules = _collect(args.paths, known)
        report = run_rules(modules, rules)
    except LintConfigError as e:
        if args.as_json:
            print(json.dumps({"graftlint": 1, "error": str(e)}))
        else:
            print(f"graftlint: config error: {e}", file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps(report.as_json(), sort_keys=True))
    else:
        for f in report.findings:
            print(f)
        counts = report.counts()
        summary = ", ".join(f"{r}={counts[r]}" for r in report.rules_run)
        nsup = sum(report.suppressed.values())
        print(f"graftlint: {report.files_scanned} files, "
              f"{len(report.findings)} findings ({summary}), "
              f"{nsup} suppressed")
    return 0 if report.clean else 1


def main() -> None:
    sys.exit(run())
