"""graftlint rules — the five load-bearing invariants as AST checkers.

Each rule is a heuristic over the AST, tuned to the package's idiom:
it catches the real hazard patterns (each demonstrated live by a
seeded-violation fixture in tests/test_analysis.py) while staying
quiet on the sanctioned sites policy.py tables. False-negative by
design where static analysis cannot see types (e.g. ``float(x)`` on a
device value hidden behind an untyped helper) — the runtime counters
(HostCounters, jit_compiles pins) remain the backstop; this layer
moves the KNOWN hazard classes to review time.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from . import policy
from .core import (Finding, Module, Rule, iter_functions, iter_scoped,
                   qualified_name, scope_matches)


# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------

def _is_jax_qual(q: Optional[str]) -> bool:
    return q is not None and (q == "jax" or q.startswith("jax."))


def _is_np_qual(q: Optional[str]) -> bool:
    return q is not None and (q == "numpy" or q.startswith("numpy."))


# jax-rooted calls that return HOST values or are pure metadata — not
# device-array producers for the host-sync / donation dataflow
_JAX_HOST_PREFIXES = (
    "jax.device_get", "jax.config", "jax.monitoring", "jax.profiler",
    "jax.debug", "jax.tree_util", "jax.dtypes", "jax.numpy.dtype",
    "jax.numpy.ndim", "jax.numpy.shape", "jax.numpy.finfo",
    "jax.numpy.iinfo",
)


def _is_device_producing(q: Optional[str]) -> bool:
    """A call into jax that returns device-resident values."""
    if not _is_jax_qual(q):
        return False
    return not any(q == p or q.startswith(p + ".")
                   for p in _JAX_HOST_PREFIXES)


def _call_qual(node: ast.AST, imports: Dict[str, str]) -> Optional[str]:
    if isinstance(node, ast.Call):
        return qualified_name(node.func, imports)
    return None


def _ordered_walk(node: ast.AST) -> Iterable[ast.AST]:
    """ast.walk in source order (iter_child_nodes preserves it)."""
    for child in ast.iter_child_nodes(node):
        yield child
        yield from _ordered_walk(child)


def _assign_targets(stmt: ast.AST) -> List[ast.expr]:
    if isinstance(stmt, ast.Assign):
        return stmt.targets
    if isinstance(stmt, (ast.AnnAssign, ast.AugAssign)) \
            and stmt.value is not None:
        return [stmt.target]
    return []


def _target_names(targets: Sequence[ast.expr]) -> List[str]:
    """Bare names bound by an assignment (tuple targets flattened;
    attribute/subscript targets skipped — per-function dataflow only
    tracks locals)."""
    out: List[str] = []
    for t in targets:
        if isinstance(t, ast.Name):
            out.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            out.extend(_target_names(list(t.elts)))
    return out


class _JitInfo:
    """One name bound to a jax.jit-wrapped callable in this module."""

    __slots__ = ("name", "line", "donate_nums", "static_nums",
                 "static_names")

    def __init__(self, name: str, line: int):
        self.name = name
        self.line = line
        self.donate_nums: Tuple[int, ...] = ()
        self.static_nums: Tuple[int, ...] = ()
        self.static_names: Tuple[str, ...] = ()


def _const_ints(node: ast.AST) -> Tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
        return tuple(out)
    return ()


def _const_strs(node: ast.AST) -> Tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    return ()


def _jit_call_of(value: ast.AST,
                 imports: Dict[str, str]) -> Optional[ast.Call]:
    """The jax.jit(...) call inside ``value``, unwrapping one level of
    functools.partial(jax.jit, ...) or tracing.named_jit(label,
    jax.jit(...)) — the three spellings the package uses (plain
    assignment, decorator, and the compile-ledger label wrapper)."""
    if not isinstance(value, ast.Call):
        return None
    q = qualified_name(value.func, imports)
    if q == "jax.jit":
        return value
    if q == "functools.partial" and value.args:
        inner = qualified_name(value.args[0], imports)
        if inner == "jax.jit":
            return value
    # tracing.named_jit("label", jax.jit(...), ...): the donation/
    # retrace declarations live on the wrapped jit — keep seeing them
    func = value.func
    name = func.attr if isinstance(func, ast.Attribute) else \
        func.id if isinstance(func, ast.Name) else None
    if name == "named_jit" and len(value.args) >= 2:
        return _jit_call_of(value.args[1], imports)
    return None


def collect_jit_bindings(mod: Module) -> Dict[str, _JitInfo]:
    """name -> _JitInfo for every ``x = jax.jit(f, ...)`` /
    ``self.x = jax.jit(...)`` assignment and every function decorated
    with ``jax.jit`` / ``functools.partial(jax.jit, ...)``. Attribute
    targets are keyed by their terminal attr name — call sites resolve
    ``anything._step(...)`` against it (module-local heuristic)."""
    out: Dict[str, _JitInfo] = {}

    def record(name: str, call: ast.Call, line: int) -> None:
        info = out.setdefault(name, _JitInfo(name, line))
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                info.donate_nums += _const_ints(kw.value)
            elif kw.arg == "static_argnums":
                info.static_nums += _const_ints(kw.value)
            elif kw.arg == "static_argnames":
                info.static_names += _const_strs(kw.value)

    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            value = getattr(node, "value", None)
            call = _jit_call_of(value, mod.imports) if value else None
            if call is None:
                continue
            targets = _assign_targets(node)
            for t in targets:
                if isinstance(t, ast.Name):
                    record(t.id, call, node.lineno)
                elif isinstance(t, ast.Attribute):
                    record(t.attr, call, node.lineno)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                call = _jit_call_of(dec, mod.imports)
                if call is not None:
                    record(node.name, call, node.lineno)
    return out


def _called_binding(call: ast.Call,
                    bindings: Dict[str, _JitInfo]) -> Optional[_JitInfo]:
    f = call.func
    if isinstance(f, ast.Name):
        return bindings.get(f.id)
    if isinstance(f, ast.Attribute):
        return bindings.get(f.attr)
    return None


# ---------------------------------------------------------------------------
# 1. env-latch
# ---------------------------------------------------------------------------

class EnvLatchRule(Rule):
    """CUP2D_* env vars are read ONCE at a sanctioned latch point
    (policy.ENV_LATCH_SITES), never mid-run. The PR-1/PR-2 hazard
    class: a read inside a jitted body or per-refresh helper lets a
    mid-run env mutation silently flip an operator form at the next
    retrace or regrid."""

    name = "env-latch"
    description = ("CUP2D_* env gates latched once at sanctioned "
                   "construction points, never read mid-run")

    @staticmethod
    def _env_var_of(node: ast.AST) -> Optional[str]:
        """The env var name a node reads, or None. Catches
        os.environ[...] / os.environ.get|pop|setdefault(...) /
        os.getenv(...) (and the bare `environ`/`getenv` import-form
        spellings) — ported verbatim from the PR-2 test walk."""
        def is_environ(n):
            return (isinstance(n, ast.Attribute) and n.attr == "environ") \
                or (isinstance(n, ast.Name) and n.id == "environ")

        def const(n):
            return n.value if (isinstance(n, ast.Constant)
                               and isinstance(n.value, str)) \
                else "<dynamic>"

        if isinstance(node, ast.Subscript) and is_environ(node.value):
            return const(node.slice)
        if isinstance(node, ast.Call):
            f = node.func
            envget = (isinstance(f, ast.Attribute)
                      and f.attr in ("get", "pop", "setdefault")
                      and is_environ(f.value))
            getenv = ((isinstance(f, ast.Attribute)
                       and f.attr == "getenv")
                      or (isinstance(f, ast.Name) and f.id == "getenv"))
            if envget or getenv:
                return const(node.args[0]) if node.args else "<dynamic>"
        return None

    @classmethod
    def env_reads(cls, mod: Module) -> List[Tuple[str, str, int]]:
        """(scope, var, lineno) for every constant CUP2D_* env read."""
        out = []
        for node, scope in iter_scoped(mod.tree):
            var = cls._env_var_of(node)
            if var is not None and var.startswith("CUP2D_"):
                out.append((scope, var, node.lineno))
        return out

    def check(self, mod: Module) -> Iterable[Finding]:
        if mod.relpath in policy.ENV_LATCH_FILES:
            return
        allowed = {scope: vars_
                   for (f, scope), vars_ in policy.ENV_LATCH_SITES.items()
                   if f == mod.relpath}
        for scope, var, line in self.env_reads(mod):
            if var in allowed.get(scope, ()):
                continue
            yield Finding(
                self.name, mod.relpath, line, scope,
                f"reads {var} outside the sanctioned latch sites — "
                "latch once at construction (policy.ENV_LATCH_SITES) "
                "and store the value")

    def finalize(self, modules: List[Module]) -> Iterable[Finding]:
        # policy-reality: every sanctioned (file, scope, var) row must
        # still name a real latch — a refactor that moves a latch must
        # move its policy row too (the old
        # test_latch_allowlist_matches_reality, now a lint finding)
        by_rel = {m.relpath: m for m in modules}
        for (rel, scope), vars_ in policy.ENV_LATCH_SITES.items():
            mod = by_rel.get(rel)
            if mod is None:
                continue    # partial lint target: row not in scope
            found = {v for s, v, _ in self.env_reads(mod) if s == scope}
            missing = set(vars_) - found
            if missing:
                yield Finding(
                    self.name, rel, 1, scope,
                    f"stale policy row: expected latched reads of "
                    f"{sorted(missing)} in scope {scope} — "
                    "policy.ENV_LATCH_SITES no longer matches reality")


# ---------------------------------------------------------------------------
# 2. host-sync
# ---------------------------------------------------------------------------

class HostSyncRule(Rule):
    """Device->host pulls only at the sanctioned pull sites
    (policy.HOST_SYNC_SITES): the hot loop's contract is ONE batched
    device_get per step (PR 3/4). Flags jax.device_get calls, .item()
    pulls, and int()/float()/np.asarray()/np.array() applied to
    device-producing jax expressions (directly or via a function-local
    name assigned from one)."""

    name = "host-sync"
    description = ("device->host transfers only at sanctioned batched "
                   "pull sites — one device_get per step")

    _COERCIONS = {"int", "float"}
    _NP_COERCIONS = {"numpy.asarray", "numpy.array"}

    @staticmethod
    def _device_tainted_names(func: ast.AST,
                              imports: Dict[str, str]) -> Set[str]:
        """Function-local names assigned from device-producing jax
        calls, minus names ALSO assigned from host expressions
        (order-insensitive approximation: an ambiguous name is not
        flagged — false negatives over false positives)."""
        device: Set[str] = set()
        host: Set[str] = set()
        for stmt in ast.walk(func):
            targets = _assign_targets(stmt)
            if not targets:
                continue
            value = stmt.value
            names = _target_names(targets)
            q = _call_qual(value, imports)
            if _is_device_producing(q):
                device.update(names)
            else:
                host.update(names)
        return device - host

    def _flags_pull(self, node: ast.Call, imports: Dict[str, str],
                    tainted: Set[str]) -> Optional[str]:
        """Reason string when ``node`` is a device pull, else None."""
        q = qualified_name(node.func, imports)
        if q is not None and (q == "jax.device_get"
                              or q.endswith(".device_get")
                              or q == "device_get"):
            return "jax.device_get"
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "item" and not node.args:
            return ".item() scalar pull"
        coerce = None
        if isinstance(node.func, ast.Name) \
                and node.func.id in self._COERCIONS:
            coerce = node.func.id
        elif q in self._NP_COERCIONS:
            coerce = q.replace("numpy.", "np.")
        if coerce is None or not node.args:
            return None
        arg = node.args[0]
        for sub in ast.walk(arg):
            sq = _call_qual(sub, imports)
            if sq == "jax.device_get" or (sq or "").endswith(
                    ".device_get"):
                return None     # value already pulled (and that pull
                #                 is flagged/sanctioned on its own)
            if _is_device_producing(sq):
                return (f"{coerce}() on a device-producing jax "
                        "expression")
            if isinstance(sub, ast.Name) and sub.id in tainted:
                return (f"{coerce}() on a device value "
                        f"({sub.id!r} assigned from a jax call)")
        return None

    def check(self, mod: Module) -> Iterable[Finding]:
        sanctioned = policy.HOST_SYNC_SITES.get(mod.relpath, ())
        # function-local device taint, precomputed per enclosing def
        taint_by_scope: Dict[str, Set[str]] = {}
        for func, scope in iter_functions(mod.tree):
            taint_by_scope[scope] = self._device_tainted_names(
                func, mod.imports)
        for node, scope in iter_scoped(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if scope_matches(scope, sanctioned):
                continue
            tainted = set()
            for s, t in taint_by_scope.items():
                if scope == s or scope.startswith(s + "."):
                    tainted |= t
            reason = self._flags_pull(node, mod.imports, tainted)
            if reason:
                yield Finding(
                    self.name, mod.relpath, node.lineno, scope,
                    f"{reason} outside the sanctioned pull sites — "
                    "ride the step's one batched diag pull "
                    "(policy.HOST_SYNC_SITES) or move to a cold path")

    def finalize(self, modules: List[Module]) -> Iterable[Finding]:
        by_rel = {m.relpath: m for m in modules}
        for rel, scopes in policy.HOST_SYNC_SITES.items():
            mod = by_rel.get(rel)
            if mod is None:
                continue
            present = {s for _, s in iter_scoped(mod.tree)}
            for s in scopes:
                if s not in present:
                    yield Finding(
                        self.name, rel, 1, s,
                        f"stale policy row: sanctioned pull scope "
                        f"{s!r} no longer exists in {rel} — "
                        "policy.HOST_SYNC_SITES must move with it")


# ---------------------------------------------------------------------------
# 3. donation-safety
# ---------------------------------------------------------------------------

class DonationSafetyRule(Rule):
    """No numpy buffer may flow into a donated argument of a jitted
    function without an intervening jnp copy — the PR-2 heap-corruption
    class: XLA donates (frees/reuses) the buffer backing the argument,
    and when that buffer is numpy-owned host memory the next write
    corrupts the heap. Data flow is function-local: names assigned from
    numpy-producing expressions (np.load/npz subscripts/np.asarray/...)
    are tainted; jnp.array()/jnp.asarray()/device_put clear the taint;
    unknown calls propagate taint from their arguments (the
    ``FlowState(*np_buffers)`` constructor shape of the original
    bug)."""

    name = "donation-safety"
    description = ("numpy buffers never flow into donated jit "
                   "arguments without a jnp copy")

    @staticmethod
    def _np_tainted_names(func: ast.AST,
                          imports: Dict[str, str]) -> Set[str]:
        tainted: Set[str] = set()

        def expr_tainted(e: ast.AST) -> bool:
            if isinstance(e, ast.Name):
                return e.id in tainted
            if isinstance(e, ast.Subscript):
                # npz["vel"], data[k] — subscript of a tainted object
                return expr_tainted(e.value)
            if isinstance(e, ast.Attribute):
                return expr_tainted(e.value)
            if isinstance(e, (ast.Tuple, ast.List)):
                return any(expr_tainted(x) for x in e.elts)
            if isinstance(e, ast.Starred):
                return expr_tainted(e.value)
            if isinstance(e, ast.GeneratorExp):
                return expr_tainted(e.elt)
            if isinstance(e, ast.Call):
                q = qualified_name(e.func, imports)
                if _is_np_qual(q):
                    return True             # numpy-producing call
                if _is_jax_qual(q):
                    return False            # device copy clears taint
                # unknown call (constructor, helper): tainted iff any
                # argument is — the FlowState(*npz_buffers) shape
                return any(expr_tainted(a) for a in e.args) \
                    or any(expr_tainted(k.value) for k in e.keywords)
            return False

        # two passes so taint propagates through forward references in
        # simple cases; source order within a pass
        for _ in range(2):
            for stmt in _ordered_walk(func):
                targets = _assign_targets(stmt)
                if not targets:
                    continue
                names = _target_names(targets)
                if expr_tainted(stmt.value):
                    tainted.update(names)
                else:
                    tainted.difference_update(names)
        return tainted

    def _arg_taint(self, arg: ast.AST, tainted: Set[str],
                   imports: Dict[str, str]) -> Optional[str]:
        if isinstance(arg, ast.Name) and arg.id in tainted:
            return f"{arg.id!r} carries a numpy buffer"
        q = _call_qual(arg, imports)
        if _is_np_qual(q):
            return f"direct {q}(...) result"
        if isinstance(arg, ast.Subscript) and isinstance(
                arg.value, ast.Name) and arg.value.id in tainted:
            return f"subscript of numpy-tainted {arg.value.id!r}"
        return None

    def check(self, mod: Module) -> Iterable[Finding]:
        bindings = {n: i for n, i in collect_jit_bindings(mod).items()
                    if i.donate_nums}
        if not bindings:
            return
        for func, scope in iter_functions(mod.tree):
            tainted = self._np_tainted_names(func, mod.imports)
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                info = _called_binding(node, bindings)
                if info is None:
                    continue
                for pos in info.donate_nums:
                    if pos >= len(node.args):
                        continue
                    why = self._arg_taint(node.args[pos], tainted,
                                          mod.imports)
                    if why:
                        yield Finding(
                            self.name, mod.relpath, node.lineno, scope,
                            f"donated argument {pos} of "
                            f"{info.name!r} (jitted with "
                            f"donate_argnums at line {info.line}): "
                            f"{why} — XLA will donate numpy-owned "
                            "memory (PR-2 heap corruption); copy with "
                            "jnp.array(...) first")


# ---------------------------------------------------------------------------
# 4. retrace-hazard
# ---------------------------------------------------------------------------

class RetraceHazardRule(Rule):
    """Static jit operands must be hashable and per-call-stable — the
    zero-steady-state-recompile discipline (PR 11 pins
    jit_compiles==0 at runtime; this rule catches the hazard at review
    time). Flags non-hashable literals (list/dict/set/comprehension),
    f-strings, str.format()/%-formatting results, and known per-call-
    varying calls (time.time, id, random.*, uuid.*) flowing into
    static_argnums/static_argnames positions at call sites of
    module-local jitted bindings."""

    name = "retrace-hazard"
    description = ("static jit operands hashable and per-call-stable "
                   "— no f-strings/literals that retrace every step")

    _UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                   ast.DictComp, ast.SetComp, ast.GeneratorExp)
    _VARYING_CALLS = ("time.time", "time.monotonic", "time.perf_counter",
                      "id", "uuid.uuid4", "os.getpid", "random.random",
                      "random.randint")

    @staticmethod
    def _formatted_names(func: ast.AST,
                         imports: Dict[str, str]) -> Set[str]:
        """Names assigned from f-strings / .format() / str %-formatting
        within this function."""
        out: Set[str] = set()
        for stmt in ast.walk(func):
            targets = _assign_targets(stmt)
            if not targets:
                continue
            v = stmt.value
            fmt = isinstance(v, ast.JoinedStr) or (
                isinstance(v, ast.Call)
                and isinstance(v.func, ast.Attribute)
                and v.func.attr == "format") or (
                isinstance(v, ast.BinOp)
                and isinstance(v.op, ast.Mod)
                and isinstance(v.left, (ast.Constant, ast.JoinedStr)))
            if fmt:
                out.update(_target_names(targets))
        return out

    def _operand_hazard(self, val: ast.AST, formatted: Set[str],
                        imports: Dict[str, str]) -> Optional[str]:
        if isinstance(val, self._UNHASHABLE):
            return ("non-hashable literal (retraces or TypeErrors "
                    "every call)")
        if isinstance(val, ast.JoinedStr):
            return "f-string (a new trace per distinct value)"
        if isinstance(val, ast.Call):
            q = qualified_name(val.func, imports)
            if isinstance(val.func, ast.Attribute) \
                    and val.func.attr == "format":
                return ".format() string (a new trace per value)"
            if q in self._VARYING_CALLS:
                return f"per-call-varying {q}() value"
        if isinstance(val, ast.Name) and val.id in formatted:
            return (f"{val.id!r} holds a formatted string "
                    "(a new trace per distinct value)")
        return None

    def check(self, mod: Module) -> Iterable[Finding]:
        bindings = {n: i for n, i in collect_jit_bindings(mod).items()
                    if i.static_nums or i.static_names}
        # hazard at declaration: static_argnums/names values must be
        # compile-time constants for THIS check to vouch for call sites
        for func, scope in iter_functions(mod.tree):
            formatted = self._formatted_names(func, mod.imports)
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                info = _called_binding(node, bindings) \
                    if bindings else None
                if info is None:
                    continue
                for pos in info.static_nums:
                    if pos >= len(node.args):
                        continue
                    why = self._operand_hazard(
                        node.args[pos], formatted, mod.imports)
                    if why:
                        yield Finding(
                            self.name, mod.relpath, node.lineno, scope,
                            f"static argument {pos} of {info.name!r}: "
                            f"{why} — steady state must not recompile "
                            "(jit_compiles==0 contract)")
                for kw in node.keywords:
                    if kw.arg not in info.static_names:
                        continue
                    why = self._operand_hazard(
                        kw.value, formatted, mod.imports)
                    if why:
                        yield Finding(
                            self.name, mod.relpath, node.lineno, scope,
                            f"static operand {kw.arg!r} of "
                            f"{info.name!r}: {why} — steady state "
                            "must not recompile (jit_compiles==0 "
                            "contract)")


# ---------------------------------------------------------------------------
# 5. leading-dim-agnostic
# ---------------------------------------------------------------------------

class LeadingDimRule(Rule):
    """In the contract scopes (policy.LEADING_DIM_SCOPES) field ops
    address the trailing y/x axes ONLY via ``...`` slicing and negative
    axis numbers, so one kernel serves uniform [Ny,Nx], fleet
    [B,Ny,Nx] and forest-lab [N,2,H,W] operands (the contract FleetSim
    and the PR-9 megakernel silently depend on). Flags positive
    ``axis=`` constants, ``.shape[k]`` with k >= 0, and hard
    positional ``[i, j]`` tuple indexing with neither ``...`` nor
    ``None`` (newaxis shaping and ``...``-anchored slices stay
    legal)."""

    name = "leading-dim"
    description = ("contract scopes index fields with '...' and "
                   "negative axes only — one kernel, any leading dims")

    @staticmethod
    def _annotation_nodes(tree: ast.AST) -> Set[int]:
        """ids of every node inside a type annotation —
        ``Callable[[jnp.ndarray], jnp.ndarray]`` is a tuple-subscript
        to the AST but not an array indexing."""
        out: Set[int] = set()

        def mark(node: Optional[ast.AST]) -> None:
            if node is None:
                return
            for sub in ast.walk(node):
                out.add(id(sub))

        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mark(node.returns)
                a = node.args
                for arg in (a.posonlyargs + a.args + a.kwonlyargs
                            + ([a.vararg] if a.vararg else [])
                            + ([a.kwarg] if a.kwarg else [])):
                    mark(arg.annotation)
            elif isinstance(node, ast.AnnAssign):
                mark(node.annotation)
        return out

    @staticmethod
    def _pos_axis(node: ast.Call) -> Optional[int]:
        for kw in node.keywords:
            if kw.arg not in ("axis", "axes", "dimension"):
                continue
            vals = _const_ints(kw.value)
            for v in vals:
                if v >= 0:
                    return v
        return None

    def check(self, mod: Module) -> Iterable[Finding]:
        scopes = policy.LEADING_DIM_SCOPES.get(mod.relpath)
        if not scopes:
            return
        whole_file = "*" in scopes
        in_annotation = self._annotation_nodes(mod.tree)
        for node, scope in iter_scoped(mod.tree):
            if not (whole_file or scope_matches(scope, scopes)):
                continue
            if id(node) in in_annotation:
                continue
            if isinstance(node, ast.Call):
                v = self._pos_axis(node)
                if v is not None:
                    yield Finding(
                        self.name, mod.relpath, node.lineno, scope,
                        f"positional axis={v} counts from the front — "
                        "the leading-dim contract requires negative "
                        "axes (-2/-1 for y/x)")
            elif isinstance(node, ast.Subscript):
                # .shape[k], k >= 0
                if isinstance(node.value, ast.Attribute) \
                        and node.value.attr == "shape":
                    idx = node.slice
                    if isinstance(idx, ast.Constant) \
                            and isinstance(idx.value, int) \
                            and idx.value >= 0:
                        yield Finding(
                            self.name, mod.relpath, node.lineno, scope,
                            f".shape[{idx.value}] counts from the "
                            "front — use negative indices "
                            "(shape[-2]/shape[-1]) so leading dims "
                            "pass through")
                    continue
                # hard [i, j] indexing without '...' anchor
                if isinstance(node.slice, ast.Tuple) \
                        and len(node.slice.elts) >= 2:
                    elts = node.slice.elts
                    has_ellipsis = any(
                        isinstance(e, ast.Constant) and e.value is ...
                        for e in elts)
                    has_newaxis = any(
                        isinstance(e, ast.Constant) and e.value is None
                        for e in elts)
                    if not has_ellipsis and not has_newaxis:
                        yield Finding(
                            self.name, mod.relpath, node.lineno, scope,
                            "hard positional [i, j] indexing — anchor "
                            "with '...' so the op stays leading-dim "
                            "agnostic")

    def finalize(self, modules: List[Module]) -> Iterable[Finding]:
        by_rel = {m.relpath: m for m in modules}
        for rel, scopes in policy.LEADING_DIM_SCOPES.items():
            mod = by_rel.get(rel)
            if mod is None:
                continue
            present = {s for _, s in iter_scoped(mod.tree)}
            for s in scopes:
                if s != "*" and s not in present:
                    yield Finding(
                        self.name, rel, 1, s,
                        f"stale policy row: contract scope {s!r} no "
                        f"longer exists in {rel} — "
                        "policy.LEADING_DIM_SCOPES must move with it")


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

ALL_RULES = (EnvLatchRule, HostSyncRule, DonationSafetyRule,
             RetraceHazardRule, LeadingDimRule)

RULE_NAMES = tuple(r.name for r in ALL_RULES)


def make_rules(only: Optional[Sequence[str]] = None,
               skip: Optional[Sequence[str]] = None) -> List[Rule]:
    """Instantiate the selected rules; unknown names are a
    LintConfigError (CLI rc 2)."""
    from .core import LintConfigError

    known = set(RULE_NAMES)
    for group in (only or ()), (skip or ()):
        for n in group:
            if n not in known:
                raise LintConfigError(
                    f"unknown rule {n!r} (known: {sorted(known)})")
    sel = []
    for cls in ALL_RULES:
        if only and cls.name not in only:
            continue
        if skip and cls.name in skip:
            continue
        sel.append(cls())
    if not sel:
        raise LintConfigError("rule selection is empty")
    return sel
