"""Matrix-free pressure-Poisson solver: block-preconditioned BiCGSTAB.

TPU-native replacement for the reference's GPU subsystem
(`/root/reference/cuda.cu:24-548` BiCGSTABSolver + the host COO assembly at
`main.cpp:5747-6020, 7031-7115`): instead of materializing a distributed
sparse matrix for cuSPARSE, the variable-resolution 5-point Laplacian is
applied *matrix-free* as a stencil (a function passed in by the caller), and
the whole Krylov iteration runs inside one `lax.while_loop` on device — no
host round-trips per iteration, no explicit halo staging (XLA inserts the
collectives when the operand arrays are sharded).

The preconditioner is the reference's exact block-Jacobi inverse
(`main.cpp:6451-6488`): P = -inv(A_local) where A_local is the BS^2 x BS^2
single-block 5-point Laplacian (`getA_local`, main.cpp:46-57), applied as a
batched [nblocks, BS^2] x [BS^2, BS^2] GEMM — MXU work, where the reference
used a batched cuBLAS GEMM (`cuda.cu:484-486`).

Algorithm: flexible BiCGSTAB with Linf convergence on max(tol_abs,
tol_rel * |r0|_inf), breakdown detection with re-orthogonalization restarts,
and best-solution tracking (`x_opt`, cuda.cu:525-542) — same control flow as
the reference's device loop (cuda.cu:403-548).
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


def block_precond_matrix(bs: int, dtype=np.float64) -> np.ndarray:
    """P_inv = -inv(A_local), the negated inverse of the bs^2 x bs^2
    single-block 5-point Laplacian with homogeneous Dirichlet truncation at
    the block edge (reference getA_local main.cpp:46-57 + Cholesky inversion
    main.cpp:6451-6488; dense inverse here — same matrix, host-side once)."""
    n = bs * bs
    ii = np.arange(n)
    xi, yi = ii % bs, ii // bs
    a = np.zeros((n, n), dtype=np.float64)
    dx = np.abs(xi[:, None] - xi[None, :])
    dy = np.abs(yi[:, None] - yi[None, :])
    a[(dx + dy) == 1] = -1.0
    np.fill_diagonal(a, 4.0)
    return (-np.linalg.inv(a)).astype(dtype)


def apply_block_precond(r: jnp.ndarray, p_inv: jnp.ndarray, bs: int) -> jnp.ndarray:
    """z = P_inv r applied per bs x bs tile of a [Ny, Nx] field (batched GEMM).

    Works for any [Ny, Nx] divisible by bs; the AMR path passes fields
    already shaped [N, bs, bs] via `apply_block_precond_blocks`.
    """
    ny, nx = r.shape[-2], r.shape[-1]
    nby, nbx = ny // bs, nx // bs
    tiles = r.reshape(*r.shape[:-2], nby, bs, nbx, bs)
    tiles = jnp.swapaxes(tiles, -3, -2).reshape(*r.shape[:-2], nby, nbx, bs * bs)
    z = tiles @ p_inv.T  # P_inv is symmetric; .T keeps intent explicit
    z = z.reshape(*r.shape[:-2], nby, nbx, bs, bs).swapaxes(-3, -2)
    return z.reshape(r.shape)


def apply_block_precond_blocks(r: jnp.ndarray, p_inv: jnp.ndarray) -> jnp.ndarray:
    """Same, for block-forest layout [N, bs, bs]."""
    n, bs, _ = r.shape
    return (r.reshape(n, bs * bs) @ p_inv.T).reshape(n, bs, bs)


# ---------------------------------------------------------------------------
# Geometric multigrid V-cycle preconditioner (uniform grids)
#
# The reference is stuck with single-level block-Jacobi because its solver
# needs an assembled sparse matrix on the GPU (cuda.cu); matrix-free on TPU
# we can do the textbook-right thing instead. With block-Jacobi, BiCGSTAB
# iteration counts grow ~linearly in N_1d/BS (measured: 11 at 1024^2 ->
# 174 at 4096^2); a V(2,2) cycle makes them O(1) in N. Used as the M of
# the flexible BiCGSTAB below; each cycle is a few 5-point stencil sweeps
# per level plus 2x2 mean restriction / nearest prolongation — pure
# VPU/HBM streaming work that XLA fuses well.
# ---------------------------------------------------------------------------

class MultigridPreconditioner:
    """V(nu1, nu2)-cycle for lap(e) = r on a [Ny, Nx] uniform grid.

    All operators are the *undivided* Laplacian (matching the solver's
    convention); the restricted residual is scaled by 4 per level because
    the undivided coarse operator is 4x the fine one (h_c^2 = 4 h_f^2).
    Damped Jacobi smoothing with the exact Neumann-aware diagonal,
    assembled on the fly from 1-D edge indicators — a materialized
    [Ny, Nx] diagonal would be baked into the jitted HLO as a
    full-field constant (268 MB at 8192^2, enough to break remote
    compilation), while two length-N 1-D constants fuse for free.
    """

    def __init__(self, ny: int, nx: int, dtype, nu1: int = 2,
                 nu2: int = 2, coarsest: int = 16, omega: float = 0.8,
                 cycle_dtype=None, spmd_safe: bool = False,
                 mesh=None, overlap_levels: int = 1,
                 edge_signs=None, leg_dtype=None,
                 smoother: str = "xla",
                 periodic=(False, False)):
        self.shapes = []
        self.nu1 = nu1
        self.nu2 = nu2
        self.omega = omega
        self.spmd_safe = spmd_safe
        # periodic (px, py) — bc.periodic_axes (ISSUE 20): the cycle's
        # operator uses wrap (roll) shifts along periodic axes at EVERY
        # level (periodicity persists under 2x coarsening) and the
        # matching edge signs come in as 0 through edge_signs, so the
        # Jacobi diagonal keeps the interior -4 on periodic rows.
        self.periodic = (bool(periodic[0]), bool(periodic[1]))
        if any(self.periodic):
            if edge_signs is None:
                raise ValueError(
                    "MultigridPreconditioner: periodic axes need the "
                    "BC table's edge_signs (bc.pressure_signs — the "
                    "periodic entries are 0); the legacy all-Neumann "
                    "default would paint wall corrections over wrap "
                    "rows")
            if smoother == "strip":
                # PR-16 refusal pattern: name the face/kind/token. The
                # fused strip pipeline synthesizes ghosts from edge
                # lines in-VMEM and has NO wrap form — silently running
                # its Neumann edge corrections over periodic rows would
                # smooth a different operator than the cycle corrects.
                faces = [n for n, p in zip(("x_lo/x_hi", "y_lo/y_hi"),
                                           self.periodic) if p]
                raise ValueError(
                    f"strip smoother does not support periodic faces "
                    f"({' and '.join(faces)}: kind='periodic', token "
                    f"'pd'): the fused sweep pipeline has no wrap-"
                    "ghost variant — use smoother='xla' (or drop "
                    "CUP2D_PALLAS) for periodic tables")
        # edge_signs: the BC table's per-face pressure-ghost signs
        # (sx_lo, sx_hi, sy_lo, sy_hi) from bc.pressure_signs — the
        # cycle's operator and Jacobi diagonal carry the same per-face
        # rows at EVERY level (face kinds persist under coarsening, the
        # wall diagonal stays in [-6, -2], never 0). None (default
        # table) keeps the legacy all-Neumann forms verbatim. The
        # overlapped sharded smoother (shard_halo) is free-slip-
        # specific, so signed hierarchies keep the GSPMD sweeps — the
        # Krylov/FAS outer loop drives the true per-face residual
        # either way.
        self.edge_signs = edge_signs
        if edge_signs is not None:
            overlap_levels = 0
        # mesh: opt-in comm/compute-overlapped smoothing for x-split
        # sharded fields (the FAS full-solver path, mesh.py): the
        # finest ``overlap_levels`` levels run their Jacobi sweeps
        # under shard_map with explicit per-offset ppermute edge-column
        # exchanges whose latency hides behind the interior update
        # (parallel.shard_halo.overlap_jacobi_sweeps — the
        # arXiv:1309.7128 schedule). Coarser levels are cheap and stay
        # on the GSPMD-partitioned form. None (default) = GSPMD
        # everywhere, bit-identical to the pre-mesh behavior.
        self.mesh = mesh
        self.overlap_levels = overlap_levels if mesh is not None else 0
        # The cycle runs in bf16 when the solver is f32: a preconditioner
        # only needs to capture the error's shape, flexible BiCGSTAB
        # absorbs the inexactness, and halving the bytes both doubles
        # effective HBM bandwidth and keeps the 8192^2 cycle inside HBM
        # (f32 temporaries alone exceeded it). f64 solves (CPU validation)
        # keep an f64 cycle for convergence-order tests.
        #
        # leg_dtype (ISSUE 19): the memory-tiered FAS-solver variant of
        # the same tradeoff — when the cycle IS the solver
        # (CUP2D_POIS=fas, cycle_dtype = the solver dtype), leg_dtype
        # puts ONLY the cycle interior (smoother/transfer legs) in bf16
        # while mg_solve's outer loop keeps the f32 true residual:
        # iterative refinement, so the bf16 legs cannot floor the
        # achievable residual the way a fully-bf16 solver does
        # (BASELINE: ~2e-4 rel floor). Takes precedence over
        # cycle_dtype when set.
        self.leg_dtype = leg_dtype
        self.dtype = leg_dtype or cycle_dtype or (
            jnp.bfloat16 if jnp.dtype(dtype) == jnp.float32 else dtype)
        self.out_dtype = dtype
        ny0, nx0 = ny, nx
        while ny >= coarsest and nx >= coarsest \
                and ny % 2 == 0 and nx % 2 == 0:
            self.shapes.append((ny, nx))
            ny //= 2
            nx //= 2
        self.shapes.append((ny, nx))
        # smoother (ISSUE 19): "strip" fuses each sweep chain into one
        # ring-buffered VMEM strip pipeline (pallas_kernels.
        # fused_jacobi_sweeps — n sweeps = one HBM read + one write).
        # Demoted to "xla" HERE when the finest level fails the shape
        # gate, so the reported smoother_tier is always truthful;
        # coarse levels and the 24-sweep coarsest chain fall back
        # per-level inside _smooth (identical results either way).
        if smoother == "strip":
            from .ops.pallas_kernels import jacobi_strip_supported
            if not jacobi_strip_supported(ny0, nx0, self.dtype,
                                          max(nu1, nu2, 1)):
                smoother = "xla"
        self.smoother = smoother

    @property
    def smoother_tier(self) -> str:
        """Telemetry label of the sweep-chain implementation: "xla" or
        "strip", with "+bf16" suffixed when the cycle legs store bf16
        (so a shape-gate demotion of the strip pipeline cannot hide an
        armed bf16 leg tier — e.g. "xla+bf16"). The default bf16
        PRECONDITIONER cycles (cycle_dtype=None under Krylov) keep the
        bare label: that tier predates this latch and is carried by
        poisson_mode."""
        base = "strip" if self.smoother == "strip" else "xla"
        if jnp.dtype(self.dtype) == jnp.bfloat16 and (
                self.leg_dtype is not None or base == "strip"):
            return base + "+bf16"
        return base

    def _lap(self, p):
        """Undivided 5-point Laplacian, zero-Neumann edge ghosts —
        fused-BC form (zero-ghost shifts + rank-1 edge correction)
        instead of an edge-mode pad, whose concatenate lowering
        materialized ~4.5 ms/step of bf16 strips inside the V-cycle at
        8192^2 (round-3 trace)."""
        if self.edge_signs is not None:
            from .ops.stencil import laplacian5_bc
            sx_lo, sx_hi, sy_lo, sy_hi = self.edge_signs
            px, py = self.periodic
            return laplacian5_bc(p, sx_lo, sx_hi, sy_lo, sy_hi,
                                 self.spmd_safe, px, py)
        from .ops.stencil import laplacian5_neumann
        return laplacian5_neumann(p, self.spmd_safe)

    def _inv_diag(self, lvl):
        """1/(-4 + signed wall-side count), from broadcast 1-D iota
        indicators (in-register, not DMA-staged constants — see
        stencil._edge_ones). Per-face signs keep the diagonal in
        [-6, -2]: always invertible."""
        from .ops.stencil import _edge_ones
        ny, nx = self.shapes[lvl]
        if self.edge_signs is not None:
            sx_lo, sx_hi, sy_lo, sy_hi = self.edge_signs
            ex = _edge_ones(nx, self.dtype, lo=sx_lo, hi=sx_hi)
            ey = _edge_ones(ny, self.dtype, lo=sy_lo, hi=sy_hi)
        else:
            ex = _edge_ones(nx, self.dtype)
            ey = _edge_ones(ny, self.dtype)
        return 1.0 / (ey[:, None] + ex[None, :] - 4.0)

    def _smooth(self, e, r, lvl, n, from_zero=False):
        sharded = n > 0 and lvl < self.overlap_levels and r.ndim == 2
        if self.smoother == "strip" and n > 0 and not sharded:
            # strip tier (ISSUE 19): the whole sweep chain as ONE
            # time-skewed strip pipeline — n sweeps cost one HBM read
            # of (e, r) and one write instead of ~2n+1 field passes.
            # Unsupported levels (coarse shapes, the 24-sweep coarsest
            # chain) fall back to the identical-result XLA loop below.
            from .ops.pallas_kernels import (fused_jacobi_sweeps,
                                             jacobi_strip_supported)
            ny, nx = self.shapes[lvl]
            if jacobi_strip_supported(ny, nx, self.dtype, n):
                return fused_jacobi_sweeps(e, r, self.omega, n,
                                           edge_signs=self.edge_signs,
                                           from_zero=from_zero)
        inv_d = self._inv_diag(lvl)
        # fori_loop (not Python unroll) so XLA reuses one sweep's buffers
        # across sweeps — unrolled at 8192^2 the live temporaries of all
        # sweeps stack up and buffer assignment exceeds HBM
        if from_zero and n > 0:
            # first sweep from e=0 is e = omega r / d — skip the full
            # lap(0) stencil pass it would otherwise spend
            e = self.omega * r * inv_d
            n = n - 1
        if n > 0 and lvl < self.overlap_levels and r.ndim == 2:
            # sharded finest level(s): explicit edge-column ppermutes
            # overlapped with the interior sweep (see __init__); the
            # strip tier rides the same halo form (ISSUE 19)
            from .parallel.shard_halo import overlap_jacobi_sweeps
            return overlap_jacobi_sweeps(e, r, inv_d, self.omega, n,
                                         self.mesh, tier=self.smoother)
        return jax.lax.fori_loop(
            0, n,
            lambda _, ee: ee + self.omega * (r - self._lap(ee)) * inv_d,
            e,
        )

    def __call__(self, r):
        return self._cycle(r.astype(self.dtype), 0).astype(self.out_dtype)

    def fcycle(self, r):
        """One F(ull)MG cycle: recurse to the coarsest level FIRST,
        prolongate each coarse solution as the next-finer level's
        initial guess, and run one V-cycle relaxation there. ~2x a
        V-cycle's cost for a much better cold-start correction — the
        opening move of the FAS solver's ``fmg`` mode (mg_solve)."""
        return self._fcycle(r.astype(self.dtype), 0).astype(self.out_dtype)

    def _fcycle(self, r, lvl):
        if lvl == len(self.shapes) - 1:
            return self._smooth(jnp.zeros_like(r), r, lvl, 24,
                                from_zero=True)
        # same full-weighting restriction (+x4 undivided scale) as the
        # V-cycle below
        rows = r[..., 0::2, :] + r[..., 1::2, :]
        rc = rows[..., :, 0::2] + rows[..., :, 1::2]
        ec = self._fcycle(rc, lvl + 1)
        e0 = jnp.repeat(jnp.repeat(ec, 2, axis=-2), 2, axis=-1)
        return self._cycle(r, lvl, e0=e0)

    def _cycle(self, r, lvl, e0=None):
        if lvl == len(self.shapes) - 1:
            # coarsest: enough Jacobi sweeps to wash out the local modes;
            # the global constant mode is BiCGSTAB's job, not M's
            if e0 is not None:
                return self._smooth(e0, r, lvl, 24)
            return self._smooth(jnp.zeros_like(r), r, lvl, 24,
                                from_zero=True)
        if e0 is not None:
            e = self._smooth(e0, r, lvl, self.nu1)
        else:
            e = self._smooth(jnp.zeros_like(r), r, lvl, self.nu1,
                             from_zero=True)
        res = r - self._lap(e)
        # full-weighting restriction (2x2 mean), x4 for the undivided
        # coarse operator scale, decomposed as row-pair sum then
        # column-pair sum. Neither reshape(ny/2,2,...).mean (tiny
        # trailing dims pad to the (8,128) TPU tile: 4 GB of temporaries
        # at 4096^2) nor a 4-way doubly-strided slice sum (measured
        # 1.8 s at 8192^2) — the two-stage form keeps each slice
        # single-strided and runs at the latency floor. `...` indexing:
        # the cycle is leading-dim agnostic so the fleet path can run
        # one V-cycle over a whole [B, Ny, Nx] member batch.
        rows = res[..., 0::2, :] + res[..., 1::2, :]
        rc = rows[..., :, 0::2] + rows[..., :, 1::2]
        ec = self._cycle(rc, lvl + 1)
        # nearest prolongation (2x2 replicate)
        e = e + jnp.repeat(jnp.repeat(ec, 2, axis=-2), 2, axis=-1)
        return self._smooth(e, r, lvl, self.nu2)


def dct_neumann_operators(ncy: int, ncx: int, dtype=np.float32):
    """Host-precomputed operators for the matmul form of
    ``coarse_neumann_solve``: DCT-II basis matrices (forward + exact
    inverse via the orthogonality weights), and the reciprocal
    eigenvalue grid with the constant nullspace mode zeroed.

    Why matmuls and not jnp.fft: the mirror-extension rfft2 lowers to
    an XLA FFT custom call whose operand staging dominated the entire
    coarse solve on TPU (r5 trace of the 1e4-block probe: ~3.7 ms per
    128 KB copy-start around each FFT, ~19 of them per step — more
    device time than the Krylov arithmetic). The same diagonalization
    as the even extension, cos(pi k (i+0.5)/n) with eigenvalues
    2cos(pi k/n) - 2 per axis, is four tiny matmuls on the MXU."""
    def fwd(n):
        k = np.arange(n)[:, None]
        i = np.arange(n)[None, :]
        return np.cos(np.pi * k * (i + 0.5) / n)

    cyf = fwd(ncy)
    cxf = fwd(ncx)
    # exact inverse from DCT-II row orthogonality: row norms are n (k=0)
    # and n/2 (k>0)
    wy = np.full(ncy, 2.0 / ncy); wy[0] = 1.0 / ncy
    wx = np.full(ncx, 2.0 / ncx); wx[0] = 1.0 / ncx
    cyi = (cyf * wy[:, None]).T
    cxi = (cxf * wx[:, None]).T
    ky = 2.0 * np.cos(np.pi * np.arange(ncy) / ncy) - 2.0
    kx = 2.0 * np.cos(np.pi * np.arange(ncx) / ncx) - 2.0
    lam = ky[:, None] + kx[None, :]
    ilam = np.where(lam < -1e-12, 1.0 / np.where(lam < -1e-12, lam, 1.0),
                    0.0)
    return (cyf.astype(dtype), cyi.astype(dtype),
            cxf.astype(dtype), cxi.astype(dtype), ilam.astype(dtype))


def coarse_neumann_solve_dct(rc: jnp.ndarray, ops, h2) -> jnp.ndarray:
    """Exact undivided-Neumann solve as 4 matmuls (see
    dct_neumann_operators); identical diagonalization to
    ``coarse_neumann_solve``, returning e * h2 with the constant mode
    projected out. HIGHEST matmul precision: the default bf16 MXU pass
    would corrupt the cosine bases exactly like it corrupted the
    structured-operator strip maps (flux.poisson_apply_structured)."""
    cyf, cyi, cxf, cxi, ilam = ops

    def mm(a, b):
        return jnp.matmul(a, b, precision=jax.lax.Precision.HIGHEST)

    F = mm(mm(cyf, rc), cxf.T)
    return h2 * mm(mm(cyi, F * ilam), cxi.T)


def coarse_neumann_solve(rc: jnp.ndarray, h2) -> jnp.ndarray:
    """Exact solve of the UNDIVIDED 5-point Neumann Laplacian on a small
    uniform grid, L e = rc, returning e * h2 (the divided-operator
    solution for spacing h = sqrt(h2)); the nullspace (constant mode) is
    projected out. Used as the coarse half of the exact-mode two-level
    preconditioner (VERDICT r2 #6): block-Jacobi alone leaves the global
    pressure modes to the Krylov iteration, which is exactly why cold
    startup solves burned hundreds of iterations.

    Mechanism: mirror (even) extension to a 2x grid turns the Neumann
    problem into a periodic one solved by FFT diagonalization — no
    precomputed factorization, works at any size, MXU/FFT-friendly.
    """
    ncy, ncx = rc.shape
    top = jnp.concatenate([rc, rc[:, ::-1]], axis=1)
    ext = jnp.concatenate([top, top[::-1, :]], axis=0)
    F = jnp.fft.rfft2(ext)
    ky = 2.0 * jnp.cos(jnp.pi * jnp.arange(2 * ncy) / ncy) - 2.0
    kx = 2.0 * jnp.cos(jnp.pi * jnp.arange(ncx + 1) / ncx) - 2.0
    lam = ky[:, None] + kx[None, :]
    E = jnp.where(lam < -1e-12, F / jnp.where(lam < -1e-12, lam, 1.0),
                  0.0)
    e = jnp.fft.irfft2(E, s=(2 * ncy, 2 * ncx))[:ncy, :ncx]
    return (h2 * e).astype(rc.dtype)


class BiCGSTABResult(NamedTuple):
    x: jnp.ndarray
    iters: jnp.ndarray
    residual: jnp.ndarray   # Linf of best residual seen
    converged: jnp.ndarray
    stalled: jnp.ndarray    # exited via the L2 stall detector


class _State(NamedTuple):
    x: jnp.ndarray
    r: jnp.ndarray
    rhat: jnp.ndarray
    p: jnp.ndarray
    v: jnp.ndarray
    rho: jnp.ndarray
    alpha: jnp.ndarray
    omega: jnp.ndarray
    it: jnp.ndarray          # global loop counter (scalar)
    restarts: jnp.ndarray
    x_opt: jnp.ndarray
    norm_opt: jnp.ndarray
    norm0: jnp.ndarray
    best_it: jnp.ndarray
    best_l2: jnp.ndarray
    impr_it: jnp.ndarray
    it_m: jnp.ndarray        # per-member iteration count (== it unbatched)
    done: jnp.ndarray


def bicgstab(
    A: Callable[[jnp.ndarray], jnp.ndarray],
    b: jnp.ndarray,
    M: Callable[[jnp.ndarray], jnp.ndarray] | None = None,
    x0: jnp.ndarray | None = None,
    tol: float = 1e-3,
    tol_rel: float = 1e-2,
    max_iter: int = 1000,
    max_restarts: int = 0,
    sum_dtype=None,
    refresh_every: int = 50,
    stall_iters: int = 120,
    stall_rtol: float = 0.999,
    member_axis: bool = False,
) -> BiCGSTABResult:
    """Preconditioned flexible BiCGSTAB, whole loop jitted on device.

    A, M are matrix-free operators on fields shaped like ``b``. Convergence
    is Linf(r) <= max(tol, tol_rel * Linf(r0)) — the reference's criterion
    (cuda.cu:434-436, 525-542). Inner products accumulate in ``sum_dtype``
    (default: b's dtype; pass jnp.float64 for compensated f32 runs).

    Beyond the reference's breakdown-restart (cuda.cu:457-477, budget
    ``max_restarts``), every ``refresh_every`` iterations the recursive
    residual is replaced by the true residual b - A(x) of the CURRENT
    iterate and the Krylov space restarted from there. This is the
    standard f32 mitigation: the recursive residual drifts from the true
    one after ~50-100 iterations, and the reference never needs it only
    because it iterates in f64. ``stall_iters`` bounds wasted work when the
    target sits below the precision floor (e.g. exact-mode solves with a
    warm initial guess): if the recursive L2 residual has not dropped by
    >= 0.1% below its running best for that many iterations, the solve
    exits with the best iterate instead of burning max_iter. L2 — not
    Linf — because BiCGSTAB's Linf is transiently non-monotonic by orders
    of magnitude (see below) while L2 decreases steadily; a stall in L2
    means the Krylov space is genuinely exhausted at this precision.
    The refresh must keep the current x — NOT
    jump back to the best-Linf iterate: BiCGSTAB's Linf residual
    transiently rises orders of magnitude above Linf(r0) while converging
    steadily in L2 (measured at 1024^2: Linf 0.04 -> 1.4 -> recovery over
    ~40 iterations), so any restart policy keyed on Linf improvement
    livelocks by restarting from x0 forever. Costs one extra operator
    application per refresh (lax.cond — not per iteration).

    ``member_axis`` (the fleet path, fleet.py): ``b`` carries a leading
    MEMBER axis of B independent systems solved in one fused loop. Every
    reduction becomes per-member (axes 1..; Krylov scalars are [B,1,..]
    broadcastables), convergence is a per-member ``done`` mask, the
    while-loop predicate is "any member unconverged", and a converged
    member's ENTIRE iteration state is frozen via select — the extra
    sweeps the loop runs for the slowest member are bit-exact identity
    for the converged ones, so each member's solution equals its solo
    solve (tests/test_fleet.py pins this). ``iters``/``residual``/
    ``converged``/``stalled`` come back per-member [B].
    """
    # trace-time only: tags the enclosing named executable's compile-
    # ledger entry with this solver component (tracing.py); a no-op
    # inside an already-compiled launch
    from . import tracing
    tracing.note_component("poisson.bicgstab")
    if M is None:
        M = lambda v: v
    dt_ = b.dtype
    sd = sum_dtype or dt_

    if member_axis:
        raxes = tuple(range(1, b.ndim))

        def dot(a_, b_):
            return jnp.sum((a_ * b_).astype(sd), axis=raxes,
                           keepdims=True).astype(dt_)

        def linf(a_):
            return jnp.max(jnp.abs(a_), axis=raxes, keepdims=True)
    else:
        def dot(a_, b_):
            return jnp.sum((a_ * b_).astype(sd)).astype(dt_)

        def linf(a_):
            return jnp.max(jnp.abs(a_))

    if x0 is None:
        # A is linear (a Laplacian), so A(0) = 0: starting from zero
        # the initial residual IS b — skip a full operator application
        # and the zeros broadcast feeding it
        x0 = jnp.zeros_like(b)
        r0 = b
    else:
        r0 = b - A(x0)
    norm0 = linf(r0)
    target = jnp.maximum(jnp.asarray(tol, dt_), tol_rel * norm0)
    # per-member-shaped constants under member_axis ([B,1,..], so the
    # while-loop carry shapes are stable); plain scalars otherwise
    one = jnp.ones_like(norm0)
    i0 = jnp.zeros_like(norm0, dtype=jnp.int32) if member_axis \
        else jnp.asarray(0, jnp.int32)

    init = _State(
        x=x0, r=r0, rhat=r0, p=jnp.zeros_like(b), v=jnp.zeros_like(b),
        rho=one, alpha=one, omega=one,
        it=jnp.asarray(0, jnp.int32), restarts=i0,
        x_opt=x0, norm_opt=norm0, norm0=norm0,
        best_it=i0,
        best_l2=jnp.sqrt(dot(r0, r0)),
        impr_it=i0,
        it_m=i0,
        done=norm0 <= target,
    )

    breakdown_eps = jnp.asarray(1e-21 if dt_ == jnp.float64 else 1e-30, dt_)

    def cond(s: _State):
        # member_axis: run while ANY member is unconverged (each member
        # freezes independently in the body below)
        return jnp.any(~s.done) & (s.it < max_iter)

    def body(s: _State):
        frozen = s.done   # members already converged at loop entry
        rho_probe = dot(s.rhat, s.r)
        # serious breakdown -> restart with rhat = r (cuda.cu:457-477)
        norm_r = jnp.sqrt(dot(s.r, s.r))
        norm_rhat = jnp.sqrt(dot(s.rhat, s.rhat))
        breakdown = jnp.abs(rho_probe) < (
            jnp.asarray(1e-16, dt_) * norm_r * norm_rhat + breakdown_eps
        )
        can_restart = s.restarts < max_restarts
        refresh = (s.it - s.best_it) >= refresh_every
        if member_axis:
            # a frozen member's best_it stops moving while the global
            # it keeps climbing, so its refresh flag would latch true
            # and force the expensive true-residual cond branch on
            # every remaining iteration of the fused loop — for state
            # the freeze discards anyway. Mask it: only ACTIVE members
            # request refreshes.
            refresh = refresh & ~frozen
        do_restart = (breakdown & can_restart) | refresh
        give_up = breakdown & ~can_restart & ~refresh

        # periodic true-residual refresh from the CURRENT iterate (see
        # docstring for why never from a "best" iterate). The refresh
        # also re-grounds (x_opt, norm_opt) in TRUE residuals: between
        # refreshes they are tracked by the recursive norm, which can
        # drift low and would otherwise freeze x_opt at a stale iterate
        # while reporting a spuriously small residual.
        x = s.x
        def refreshed():
            r_true = b - A(s.x)
            n_true = linf(r_true)
            n_opt_true = linf(b - A(s.x_opt))
            take_x = n_true <= n_opt_true
            return (r_true,
                    jnp.where(take_x, s.x, s.x_opt),
                    jnp.where(take_x, n_true, n_opt_true))

        if member_axis:
            # refresh is a per-member vector: pay the true-residual
            # operator applications only when ANY member refreshes, and
            # select per member inside
            def refreshed_m():
                r_t, xo_t, no_t = refreshed()
                return (jnp.where(refresh, r_t, s.r),
                        jnp.where(refresh, xo_t, s.x_opt),
                        jnp.where(refresh, no_t, s.norm_opt))

            r, x_opt0, norm_opt0 = jax.lax.cond(
                jnp.any(refresh),
                refreshed_m,
                lambda: (s.r, s.x_opt, s.norm_opt),
            )
        else:
            r, x_opt0, norm_opt0 = jax.lax.cond(
                refresh,
                refreshed,
                lambda: (s.r, s.x_opt, s.norm_opt),
            )
        rhat = jnp.where(do_restart, r, s.rhat)
        rho_new = jnp.where(do_restart, dot(rhat, r), rho_probe)
        beta = jnp.where(
            do_restart, jnp.zeros_like(rho_new),
            (rho_new / (s.rho + breakdown_eps)) * (s.alpha / (s.omega + breakdown_eps)),
        )
        p = r + beta * (s.p - s.omega * s.v)
        z = M(p)
        v = A(z)
        alpha = rho_new / (dot(rhat, v) + breakdown_eps)
        h = x + alpha * z
        sres = r - alpha * v
        zs = M(sres)
        t = A(zs)
        omega = dot(t, sres) / (dot(t, t) + breakdown_eps)
        x = h + omega * zs
        r = sres - omega * t

        norm = linf(r)
        better = norm < norm_opt0
        x_opt = jnp.where(better, x, x_opt0)
        norm_opt = jnp.where(better, norm, norm_opt0)
        # stall exit keyed on the L2 norm, sampled ONLY at refresh
        # iterations: r was re-grounded on the TRUE residual this
        # iteration (one Krylov update ago), so consecutive samples are
        # like-for-like. Comparing per-iteration recursive norms against
        # a refresh-corrected history would latch a drifted-low floor
        # that true residuals can never beat, firing mid-convergence.
        # stall_rtol sets what counts as progress: 0.999 (production)
        # keeps grinding for any 0.1%/window gain; exact mode passes
        # 0.99 so windows improving < 1% stop the solve — a
        # diminishing-returns cut that trims the tol-0 startup tail
        # (71 -> ~40 iterations on the canonical probe) at the cost of
        # one order of residual depth nobody consumes
        l2_now = jnp.sqrt(dot(r, r))
        improved = refresh & (l2_now < stall_rtol * s.best_l2)
        best_l2 = jnp.where(refresh, jnp.minimum(s.best_l2, l2_now),
                            s.best_l2)
        impr_it = jnp.where(improved, s.it, s.impr_it)
        stalled = (s.it - impr_it) >= stall_iters
        done = (norm <= target) | give_up | stalled

        # only breakdown-triggered restarts consume the reference's
        # max_restarts budget; periodic refreshes are unbudgeted.
        # best_it here records the last refresh iteration.
        new = _State(
            x=x, r=r, rhat=rhat, p=p, v=v,
            rho=rho_new, alpha=alpha, omega=omega,
            it=s.it + 1,
            restarts=s.restarts + (breakdown & can_restart).astype(jnp.int32),
            x_opt=x_opt, norm_opt=norm_opt, norm0=s.norm0,
            best_it=jnp.where(do_restart, s.it, s.best_it),
            best_l2=best_l2,
            impr_it=impr_it,
            it_m=s.it_m + 1,
            done=done,
        )
        if not member_axis:
            return new
        # per-member convergence mask: a member that was done at loop
        # entry FREEZES its entire iteration state — the sweeps the
        # loop keeps running for slower members are exact identity for
        # it, so its solution is bit-equal to its solo solve
        keep = lambda old, cur: jnp.where(frozen, old, cur)
        return _State(
            x=keep(s.x, new.x), r=keep(s.r, new.r),
            rhat=keep(s.rhat, new.rhat), p=keep(s.p, new.p),
            v=keep(s.v, new.v), rho=keep(s.rho, new.rho),
            alpha=keep(s.alpha, new.alpha), omega=keep(s.omega, new.omega),
            it=new.it,
            restarts=keep(s.restarts, new.restarts),
            x_opt=keep(s.x_opt, new.x_opt),
            norm_opt=keep(s.norm_opt, new.norm_opt), norm0=s.norm0,
            best_it=keep(s.best_it, new.best_it),
            best_l2=keep(s.best_l2, new.best_l2),
            impr_it=keep(s.impr_it, new.impr_it),
            it_m=keep(s.it_m, new.it_m),
            done=frozen | new.done,
        )

    final = jax.lax.while_loop(cond, body, init)
    # the loop may exit on the CURRENT residual crossing target while
    # x_opt still holds an older iterate — return whichever is better
    final_norm = linf(final.r)
    use_x = final_norm <= final.norm_opt
    converged = jnp.minimum(final_norm, final.norm_opt) <= target
    # stall classification against the member's OWN frozen counter
    # (it_m == it unbatched): under member_axis the global it keeps
    # climbing after a member froze, which would misclassify an early
    # give-up exit as a stall
    stalled = ~converged & ((final.it_m - final.impr_it) >= stall_iters)
    sq = (lambda v: v.reshape(-1)) if member_axis else (lambda v: v)
    return BiCGSTABResult(
        x=jnp.where(use_x, final.x, final.x_opt),
        iters=sq(final.it_m) if member_axis else final.it,
        residual=sq(jnp.where(use_x, final_norm, final.norm_opt)),
        converged=sq(converged),
        stalled=sq(stalled),
    )


# ---------------------------------------------------------------------------
# Matrix-free multigrid as a FULL solver (not a preconditioner)
#
# For uniform / sharded-uniform / fleet-batched grids the MG hierarchy
# is strong enough to BE the solver: each iteration is one V-cycle
# correction x += M(b - A x) with the true residual recomputed at full
# precision (iterative refinement — the bf16 cycle interior cannot
# limit the achievable residual), so the production tolerances are
# reached in ~2-3 cycles from a warm deltap guess where Krylov spends
# 2 operator + 2 preconditioner applications per iteration on dot
# products the cycle never needs. Linear problem, exactly-represented
# coarse operators: the FAS formulation (arXiv:2510.11152) reduces to
# the correction scheme, implemented here directly. Kept as a LATCHED
# alternative (CUP2D_POIS=fas) — BiCGSTAB stays the default and the
# robustness backstop (exact/escalation solves always run Krylov).
# ---------------------------------------------------------------------------

class _MGSolveState(NamedTuple):
    x: jnp.ndarray
    r: jnp.ndarray
    norm: jnp.ndarray
    best: jnp.ndarray     # running best Linf (the stall baseline: at
    #                       the precision floor the per-cycle norm
    #                       wanders, so consecutive-cycle comparison
    #                       would keep resetting the counter)
    it: jnp.ndarray       # global cycle counter (scalar)
    it_m: jnp.ndarray     # per-member cycle count (== it unbatched)
    no_impr: jnp.ndarray  # consecutive cycles without stall_rtol gain
    done: jnp.ndarray


def mg_solve(
    A: Callable[[jnp.ndarray], jnp.ndarray],
    b: jnp.ndarray,
    mg: "MultigridPreconditioner",
    x0: jnp.ndarray | None = None,
    tol: float = 1e-3,
    tol_rel: float = 1e-2,
    max_cycles: int = 50,
    stall_cycles: int = 4,
    stall_rtol: float = 0.999,
    member_axis: bool = False,
    fmg: bool = False,
) -> BiCGSTABResult:
    """Solve A x = b by repeated multigrid cycles, whole loop on device.

    Same result contract and convergence criterion as ``bicgstab``
    (Linf(r) <= max(tol, tol_rel * Linf(r0))), so every driver/telemetry
    consumer reads it unchanged; ``iters`` counts CYCLES — one operator
    application and one V-cycle each, vs Krylov's 2 A + 2 M per
    iteration.

    ``fmg``: open with one F-cycle (coarsest-first, prolongated initial
    guesses — ``MultigridPreconditioner.fcycle``) before the V-cycle
    loop; counted as a cycle in ``iters``. Worth it on cold RHSes; the
    warm per-step production solves don't need it.

    ``stall_cycles``/``stall_rtol``: a cycle that fails to shrink the
    Linf residual by stall_rtol for that many consecutive cycles exits
    ``stalled`` — the solver's precision floor (the health verdict
    treats a stalled exit as benign, resilience.health_verdict), so a
    target below what the cycle can reach degrades gracefully instead
    of burning max_cycles. Unlike BiCGSTAB no refresh bookkeeping is
    needed: the residual here is always the TRUE residual.

    ``member_axis``: leading member axis of B independent systems, one
    fused cycle loop; a converged member's state is frozen via select
    (extra cycles are bit-exact identity — the fleet freeze contract,
    tests/test_fleet.py / test_poisson.py), and
    iters/residual/converged/stalled come back per-member [B].
    """
    # trace-time only — see the bicgstab note
    from . import tracing
    tracing.note_component("poisson.mg_solve")
    dt_ = b.dtype
    if member_axis:
        raxes = tuple(range(1, b.ndim))

        def linf(a_):
            return jnp.max(jnp.abs(a_), axis=raxes, keepdims=True)
    else:
        def linf(a_):
            return jnp.max(jnp.abs(a_))

    if x0 is None:
        # A(0) = 0: the initial residual IS b (same skip as bicgstab)
        x0 = jnp.zeros_like(b)
        r0 = b
    else:
        r0 = b - A(x0)
    norm0 = linf(r0)
    target = jnp.maximum(jnp.asarray(tol, dt_), tol_rel * norm0)
    i0 = jnp.zeros_like(norm0, dtype=jnp.int32) if member_axis \
        else jnp.asarray(0, jnp.int32)

    if fmg:
        x0 = x0 + mg.fcycle(r0)
        r0 = b - A(x0)
        norm0_f = linf(r0)
        init_norm = norm0_f
        it0 = jnp.asarray(1, jnp.int32)
        itm0 = i0 + 1
    else:
        init_norm = norm0
        it0 = jnp.asarray(0, jnp.int32)
        itm0 = i0

    init = _MGSolveState(
        x=x0, r=r0, norm=init_norm, best=init_norm,
        it=it0, it_m=itm0, no_impr=i0,
        done=init_norm <= target,
    )

    def cond(s: _MGSolveState):
        return jnp.any(~s.done) & (s.it < max_cycles)

    def body(s: _MGSolveState):
        frozen = s.done
        x = s.x + mg(s.r)
        r = b - A(x)            # TRUE residual, solver precision
        norm = linf(r)
        improved = norm < stall_rtol * s.best
        best = jnp.minimum(s.best, norm)
        no_impr = jnp.where(improved, jnp.zeros_like(s.no_impr),
                            s.no_impr + 1)
        done = (norm <= target) | (no_impr >= stall_cycles)
        new = _MGSolveState(
            x=x, r=r, norm=norm, best=best,
            it=s.it + 1, it_m=s.it_m + 1, no_impr=no_impr, done=done,
        )
        if not member_axis:
            return new
        keep = lambda old, cur: jnp.where(frozen, old, cur)
        return _MGSolveState(
            x=keep(s.x, new.x), r=keep(s.r, new.r),
            norm=keep(s.norm, new.norm),
            best=keep(s.best, new.best),
            it=new.it,
            it_m=keep(s.it_m, new.it_m),
            no_impr=keep(s.no_impr, new.no_impr),
            done=frozen | new.done,
        )

    final = jax.lax.while_loop(cond, body, init)
    converged = final.norm <= target
    stalled = ~converged & (final.no_impr >= stall_cycles)
    sq = (lambda v: v.reshape(-1)) if member_axis else (lambda v: v)
    return BiCGSTABResult(
        x=final.x,
        iters=sq(final.it_m) if member_axis else final.it,
        residual=sq(final.norm),
        converged=sq(converged),
        stalled=sq(stalled),
    )


# ---------------------------------------------------------------------------
# FFT-diagonalized DIRECT solve (ISSUE 20, CUP2D_POIS=fftd)
#
# A periodic direction's wrap second difference is diagonalized by the
# real FFT into per-mode eigenvalues lam(k) = 2 cos(2 pi k / n) - 2
# (the structural template is arXiv:2106.03583's FFT-accelerated
# multi-block solver). Both directions periodic -> pointwise spectral
# divide; one periodic -> an independent tridiagonal system per mode
# along the wall axis, whose Neumann/Dirichlet rows come from the BC
# table's pressure signs. Either way the per-step V-cycle train
# collapses into ONE direct solve.
# ---------------------------------------------------------------------------

class FFTDiagPlan:
    """Host-precomputed plan for the FFT-diagonalized direct Poisson
    solve of the undivided per-face Laplacian (bc.py periodic kind).

    * ``px and py`` (fully-periodic box): 2D real FFT, pointwise
      divide by lam_y(m) + lam_x(k), inverse FFT. The (0, 0) nullspace
      mode is pinned to zero, so the returned solution is exactly
      mean-free (the projection's mean removal is then a no-op).
    * one periodic direction: real FFT along it, then one TRIDIAGONAL
      system per mode along the other (wall) axis — unit off-diagonals
      and diagonal lam(k) - 2 + wall sign at the edge rows, solved by
      the Thomas algorithm as two length-n first-order scans batched
      over all modes and fleet members. The elimination coefficients
      depend only on the STATIC diagonal, so they are precomputed here
      in f64 numpy and baked as two [n, nmodes] device constants; the
      per-solve work is the two complex recurrences plus the
      transforms. The py-only case runs as the TRANSPOSED px-only
      problem (the 5-point operator is symmetric under transposing the
      grid), so one kernel serves both orientations.

    Nullspace (periodic channel): the k=0 mode of all-Neumann walls is
    the singular 1D Neumann Laplacian. Its RHS is mean-removed and the
    first row pinned to x[0] = 0; the singular matrix's columns sum to
    zero, so the pinned solve satisfies the original system EXACTLY
    for a mean-free RHS — no residual leaks into the reported Linf.
    Dirichlet walls (outflow faces) are non-singular and skip the pin.

    Sharding: the transform and the tridiagonal scan are whole-array
    sequential along their axes — there is no shard_map form, and the
    mesh's x-split always shards one of the two (periodic x: the
    transform axis; periodic y only: the scan axis).
    ``UniformGrid.attach_mesh`` refuses the fftd latch outright (see
    also parallel/shard_halo.py); sharded periodic cases run under
    bicgstab/fas, whose wrap stencils GSPMD partitions correctly.
    """

    def __init__(self, ny: int, nx: int, dtype, px: bool, py: bool,
                 edge_signs):
        if not (px or py):
            raise ValueError(
                "FFTDiagPlan needs at least one periodic direction "
                "(got px=False, py=False): with walls on all four "
                "faces there is nothing to diagonalize — use "
                "bicgstab/mg_solve")
        self.ny, self.nx = ny, nx
        self.px, self.py = px, py
        self.dtype = jnp.dtype(dtype)
        sx_lo, sx_hi, sy_lo, sy_hi = edge_signs
        if px and py:
            lx = 2.0 * np.cos(
                2.0 * np.pi * np.arange(nx // 2 + 1) / nx) - 2.0
            ly = 2.0 * np.cos(2.0 * np.pi * np.arange(ny) / ny) - 2.0
            lam = ly[:, None] + lx[None, :]
            mask = lam < -1e-12
            ilam = np.where(mask, 1.0 / np.where(mask, lam, 1.0), 0.0)
            self.ilam = jnp.asarray(ilam, self.dtype)
            self.pin = True     # the zeroed (0,0) mode IS the pin
            return
        # single periodic direction: transform length n_t, tridiagonal
        # system length n_s with the wall axis's signs
        if px:
            n_t, n_s = nx, ny
            s_lo, s_hi = sy_lo, sy_hi
        else:
            n_t, n_s = ny, nx
            s_lo, s_hi = sx_lo, sx_hi
        nk = n_t // 2 + 1
        lam = 2.0 * np.cos(2.0 * np.pi * np.arange(nk) / n_t) - 2.0
        d = np.tile(lam[None, :], (n_s, 1)) - 2.0
        d[0, :] += s_lo    # lint: allow[leading-dim] -- host numpy precompute, fixed [n_s, nk] matrix rows, never batched
        d[-1, :] += s_hi   # lint: allow[leading-dim] -- host numpy precompute, fixed [n_s, nk] matrix rows, never batched
        c = np.ones((n_s, nk))
        c[-1, :] = 0.0     # lint: allow[leading-dim] -- host numpy precompute: no superdiagonal on the last row
        self.pin = (s_lo == 1.0) and (s_hi == 1.0)
        if self.pin:
            # singular k=0 all-Neumann mode: row 0 -> identity
            d[0, 0] = 1.0  # lint: allow[leading-dim] -- host numpy precompute of the k=0 nullspace pin
            c[0, 0] = 0.0  # lint: allow[leading-dim] -- host numpy precompute of the k=0 nullspace pin
        # Thomas forward elimination on the static matrix (unit
        # subdiagonal): denom_j = d_j - cp_{j-1}, cp_j = c_j / denom_j
        denom = np.empty((n_s, nk))
        cp = np.empty((n_s, nk))
        denom[0] = d[0]
        cp[0] = c[0] / denom[0]
        for j in range(1, n_s):
            denom[j] = d[j] - cp[j - 1]
            cp[j] = c[j] / denom[j]
        self.cp = jnp.asarray(cp, self.dtype)
        self.inv_denom = jnp.asarray(1.0 / denom, self.dtype)

    def solve(self, b: jnp.ndarray) -> jnp.ndarray:
        """Direct solve lap(x) = b (undivided per-face operator).
        Leading axes (the fleet's member batch) ride the same
        transforms — the mode axis is embarrassingly parallel."""
        if self.px and self.py:
            F = jnp.fft.rfft2(b)
            x = jnp.fft.irfft2(F * self.ilam, s=(self.ny, self.nx))
            return x.astype(b.dtype)
        swap = not self.px        # py-only: transposed px-only problem
        if swap:
            b = jnp.swapaxes(b, -1, -2)
        n_t = b.shape[-1]
        bh = jnp.fft.rfft(b, axis=-1)          # [..., n_s, nk]
        if self.pin:
            # mean-free RHS for the singular k=0 mode, then pin row 0
            col0 = bh[..., :, 0]
            col0 = col0 - jnp.mean(col0, axis=-1, keepdims=True)
            bh = bh.at[..., :, 0].set(col0)
            bh = bh.at[..., 0, 0].set(0.0)
        bt = jnp.moveaxis(bh, -2, 0)           # [n_s, ..., nk]

        def fwd(dp_prev, xs):
            bj, idj = xs
            dp = (bj - dp_prev) * idj
            return dp, dp

        _, dps = jax.lax.scan(fwd, jnp.zeros_like(bt[0]),
                              (bt, self.inv_denom))

        def bwd(x_next, xs):
            dpj, cpj = xs
            xj = dpj - cpj * x_next
            return xj, xj

        _, xt = jax.lax.scan(bwd, jnp.zeros_like(bt[0]),
                             (dps, self.cp), reverse=True)
        x = jnp.fft.irfft(jnp.moveaxis(xt, 0, -2), n=n_t, axis=-1)
        if swap:
            x = jnp.swapaxes(x, -1, -2)
        return x.astype(b.dtype)


def fft_diag_solve(
    A: Callable[[jnp.ndarray], jnp.ndarray],
    b: jnp.ndarray,
    plan: "FFTDiagPlan",
    tol: float = 1e-3,
    tol_rel: float = 1e-2,
    member_axis: bool = False,
) -> BiCGSTABResult:
    """One-shot FFT-diagonalized direct solve with the SAME result/
    stall/telemetry contract as ``bicgstab``/``mg_solve``, so drivers,
    health verdicts and ``poisson_mode`` attribution read it
    unchanged: ``x`` from :meth:`FFTDiagPlan.solve`, ``residual`` the
    TRUE Linf residual of that x, ``converged`` against the shared
    criterion Linf(r) <= max(tol, tol_rel * Linf(b)), ``iters`` = 1
    unconditionally. A tol-0 "exact" request reports the direct
    solve's precision floor through the benign ``stalled`` bit —
    exactly how bicgstab's stall detector classifies its own tol-0
    exits (resilience.health_verdict treats it as benign).

    ``member_axis``: the fleet's B independent systems batch through
    ONE transform (the mode axis is embarrassingly parallel); every
    member reports iters == 1, so the converged-member freeze contract
    of the iterative solvers is trivially inert — there are no extra
    sweeps a frozen member could diverge under (tests/test_fleet.py).
    """
    from . import tracing
    tracing.note_component("poisson.fft_diag_solve")
    dt_ = b.dtype
    if member_axis:
        raxes = tuple(range(1, b.ndim))

        def linf(a_):
            return jnp.max(jnp.abs(a_), axis=raxes)
    else:
        def linf(a_):
            return jnp.max(jnp.abs(a_))

    x = plan.solve(b)
    residual = linf(b - A(x))
    target = jnp.maximum(jnp.asarray(tol, dt_), tol_rel * linf(b))
    converged = residual <= target
    return BiCGSTABResult(
        x=x,
        iters=jnp.ones_like(converged, dtype=jnp.int32)
        if member_axis else jnp.asarray(1, jnp.int32),
        residual=residual,
        converged=converged,
        stalled=~converged,
    )


# ---------------------------------------------------------------------------
# Forest-native FAS hierarchy (the composite forest's own refinement
# levels as the multigrid levels)
# ---------------------------------------------------------------------------

def _up2_bilinear(a: jnp.ndarray) -> jnp.ndarray:
    """Cell-centered 2x bilinear upsample of a [H, W] image with edge
    clamp: fine centers sit at quarter offsets, so the separable
    weights are (3/4, 1/4). Pure slice/stack arithmetic — the ladder
    step of the structured two-level transfers (no per-cell indices).
    Lives here (with ``_down2_mean``) since the forest FAS cycle below
    walks the same ladder; ``amr`` re-imports both."""
    def up1(v):
        vm = jnp.concatenate([v[:1], v[:-1]], axis=0)
        vp = jnp.concatenate([v[1:], v[-1:]], axis=0)
        even = 0.75 * v + 0.25 * vm
        odd = 0.75 * v + 0.25 * vp
        return jnp.stack([even, odd], axis=1).reshape(
            2 * v.shape[0], *v.shape[1:])
    return up1(up1(a).T).T


def _down2_mean(a: jnp.ndarray) -> jnp.ndarray:
    """2x2 mean coarsening of a [H, W] image (full-weighting adjoint
    of nearest prolongation; each fine cell carries weight 1/4)."""
    rows = a[0::2, :] + a[1::2, :]
    return 0.25 * (rows[:, 0::2] + rows[:, 1::2])


def _img_lap_neumann(a: jnp.ndarray) -> jnp.ndarray:
    """Undivided 5-point Laplacian of a [H, W] image with ZERO-GRADIENT
    (edge-replicate) ghosts. The intermediate-level smoothing operator
    of the forest FAS cycle: a window edge is either a domain wall
    (truly Neumann) or a refinement interface to coarser blocks, where
    zero-gradient extrapolation is the consistent approximation for
    the SMOOTH error the coarser rungs carry. The zero-ghost
    (Dirichlet) variant is NOT usable here: it reads the O(1) boundary
    values of the prolonged base correction as O(1) artificial edge
    residuals, and on a multi-rung ladder (forest levels several steps
    above c) that injection compounds per cycle into divergence —
    measured on the deep-ladder probe (rate 1.5+ Dirichlet vs 0.13
    Neumann), while single-rung forests are insensitive."""
    p = jnp.pad(a, 1, mode="edge")
    return (p[2:, 1:-1] + p[:-2, 1:-1]
            + p[1:-1, 2:] + p[1:-1, :-2]) - 4.0 * a


class ForestFASCycle:
    """One multigrid cycle over the composite forest's OWN refinement
    levels — the ``mg`` object of :func:`mg_solve` for the
    ``CUP2D_POIS=fas`` forest path (linear problem, so the FAS
    formulation of arXiv:2510.11152 reduces to the correction scheme,
    same as the uniform solver above).

    Level structure (finest first):

    * the COMPOSITE level: all active blocks at their native
      resolutions, smoothed by damped block-Jacobi (the exact-inverse
      single-block preconditioner — ``apply_block_precond_blocks``)
      through ``smooth_blocks``; on the sharded forest this is the
      comm/compute-overlapped block-surface sweep
      (``shard_halo.overlap_block_jacobi_sweeps``);
    * one WINDOW-image level per forest refinement level above the
      coarse level c (the PR-4/PR-6 cropped active-tile windows —
      ``paint_fine`` deposits each block's residual at its own level),
      smoothed by damped Jacobi on ``_img_lap_neumann`` and walked
      2x sum/bilinear ladder;
    * the uniform BASE level c, solved EXACTLY by the DCT-II spectral
      Neumann solve (``base_solve`` — the PR-6 machinery, with the
      below-c block deposits folded in).

    All transfer closures are built by ``AMRSim._fas_transfers`` from
    the same ``_build_coarse_maps`` pytree as the two-level
    preconditioner, so the executable is keyed on the level SET like
    every other consumer. ``__call__`` runs a V-cycle (block pre-smooth
    first); ``fcycle`` opens base-level-first (no pre-smooth) for cold
    RHSes — ``mg_solve(fmg=True)``, the ``fas-f`` latch."""

    def __init__(self, A, smooth_blocks, paint_fine, base_solve,
                 extract_all, cih2, nu_img: int = 2,
                 omega: float = 0.8, nu_pre: int = 1, nu_post: int = 1,
                 leg_dtype=None):
        self.A = A
        self.smooth_blocks = smooth_blocks
        self.paint_fine = paint_fine
        self.base_solve = base_solve
        self.extract_all = extract_all
        self.cih2 = cih2
        self.nu_img = nu_img
        self.omega = omega
        self.nu_pre = nu_pre
        self.nu_post = nu_post
        # leg_dtype (ISSUE 19): storage dtype of the window-image
        # ladder legs ONLY — the V-down/V-up smooths, restrictions and
        # prolongations run in bf16 while mg_solve's outer loop keeps
        # the f32 true residual (iterative refinement absorbs the leg
        # rounding). The composite block smooth stays at solver
        # precision (its P_inv GEMM is the accuracy-critical finest
        # leg) and the DCT base solve stays f32 HIGHEST — the ladder
        # casts its restricted RHS back up before entering it. None =
        # solver-precision legs, bit-identical to the pre-tier cycle.
        self.leg_dtype = leg_dtype

    def _img_smooth(self, e, r, n: int, from_zero: bool = False):
        # damped Jacobi on the Neumann-ghost window image; interior
        # diag of the undivided 5-point operator is -4
        if from_zero and n > 0:
            e = (-0.25 * self.omega) * r
            n -= 1
        for _ in range(n):
            e = e - 0.25 * self.omega * (r - _img_lap_neumann(e))
        return e

    def _cycle(self, r, pre: bool):
        if pre:
            e = self.smooth_blocks(None, r, self.nu_pre, from_zero=True)
            r1 = r - self.A(e)
        else:
            e = None
            r1 = r
        rdiv = r1 * self.cih2            # divided residual per block
        rimgs = self.paint_fine(rdiv)    # finest -> c+1, undivided
        if self.leg_dtype is not None:
            # bf16 ladder legs: one downcast per painted level; the
            # whole V-down/V-up below then runs at leg precision
            rimgs = [R.astype(self.leg_dtype) for R in rimgs]
        # V-down over the window-image levels: smooth, restrict the
        # smoothed residual one ladder step, fold in the next level's
        # own deposit (undivided restriction = sum-of-4)
        es, accs = [], []
        racc = None
        for R in rimgs:
            racc = R if racc is None else R + racc
            accs.append(racc)
            el = self._img_smooth(None, racc, self.nu_img,
                                  from_zero=True)
            es.append(el)
            res = racc - _img_lap_neumann(el)
            rows = res[0::2, :] + res[1::2, :]
            racc = rows[:, 0::2] + rows[:, 1::2]
        # exact spectral base solve (folds the <= c deposits of rdiv
        # in); awin = the window slice of the base correction. The
        # base solve is precision-critical (f32 HIGHEST DCT) — leg
        # storage casts back up at its door.
        if self.leg_dtype is not None and racc is not None:
            racc = racc.astype(rdiv.dtype)
        ec, awin = self.base_solve(rdiv, racc)
        # V-up: prolongate, add the stored level error, post-smooth
        # against the stored accumulated RHS
        if self.leg_dtype is not None and len(rimgs) > 0:
            awin = awin.astype(self.leg_dtype)
        for i in range(len(rimgs) - 1, -1, -1):
            a = _up2_bilinear(awin) + es[i]
            awin = self._img_smooth(a, accs[i], self.nu_img)
            es[i] = awin
        if self.leg_dtype is not None:
            es = [el.astype(rdiv.dtype) for el in es]
        corr = self.extract_all(ec, es)
        e = corr if e is None else e + corr
        return self.smooth_blocks(e, r, self.nu_post)

    def __call__(self, r):
        return self._cycle(r, pre=True)

    def fcycle(self, r):
        # coarse-first opening for cold RHSes (fas-f): the base modes
        # dominate a cold deltap RHS (VERDICT r3 #9), so spend the
        # first correction on them before any fine smoothing
        return self._cycle(r, pre=False)


# ---------------------------------------------------------------------------
# Shared projection-correction epilogue (PR 9)
# ---------------------------------------------------------------------------

def project_correct(x, pres_old, vel, h, dt, *, spmd_safe=False,
                    mean_axes=None, tier="xla", remove_mean=True,
                    grad_signs=None, periodic=None):
    """Post-solve projection epilogue shared by the uniform and fleet
    drivers: ``pres = (x - mean x) + pres_old - mean pres_old`` and
    ``vel += -dt/(2h) * grad_neumann(pres) / h^2``.

    x: the solver's deltap field; vel: [..., 2, Ny, Nx]; dt: scalar
    (uniform) or a flat per-member vector (fleet, with
    ``mean_axes=(-2, -1)`` selecting per-member means). ``tier`` is the
    caller's kernel-tier latch: on the fused tiers (f32 state) the
    whole epilogue after the means runs as ONE Pallas kernel
    (ops/pallas_kernels.fused_correction — one read of x/pold/vel, one
    write of pres/vel) instead of the XLA mean-subtract + gradient +
    update chain. The XLA branch is the historical expression verbatim,
    so tier="xla" callers are bit-identical to pre-PR-9 code.

    ``remove_mean=False`` (per-face BC engine, bc.py): tables with an
    outflow face carry a Dirichlet pressure row, the operator is
    non-singular and the mean subtraction would shift the anchored
    level — the epilogue then uses dp/pres as-is. ``grad_signs`` is
    the table's (sx_lo, sx_hi, sy_lo, sy_hi) pressure-ghost sign tuple;
    None keeps the legacy all-Neumann gradient verbatim. BOTH branches
    carry it (ISSUE 16): the XLA chain routes it to
    pressure_gradient_update_bc, the fused kernel bakes the static
    signs into its rank-1 edge correction (the all-Neumann default is
    bit-identical to the PR-9 kernel — mean subtraction of the zeros
    mx/mp is the identity, and gs=(1,1,1,1) reproduces the hard-coded
    edge constants).

    ``periodic`` is the table's (px, py) axis flags (bc.periodic_axes,
    ISSUE 20): the gradient's shifts wrap along periodic axes. Only
    the XLA branch carries it — the fused correction kernel has no
    wrap form, and periodic tables can never arm the fused tier
    (ops/pallas_kernels.kernel_supports refuses the pd token), so the
    kernel branch is statically unreachable for them; the guard here
    keeps that invariant structural rather than assumed.

    Returns (vel, pres).
    """
    from .ops.stencil import (pressure_gradient_update_bc,
                              pressure_gradient_update_fused)

    ih2 = 1.0 / (h * h)
    if not remove_mean:
        mx = jnp.zeros((), x.dtype)
        mp = jnp.zeros((), x.dtype)
    elif mean_axes is None:
        mx = jnp.mean(x)
        mp = jnp.mean(pres_old)
    else:
        mx = jnp.mean(x, axis=mean_axes, keepdims=True)
        mp = jnp.mean(pres_old, axis=mean_axes, keepdims=True)
    px, py = periodic if periodic is not None else (False, False)
    if tier != "xla" and x.dtype == jnp.float32 and not (px or py):
        from .ops.pallas_kernels import fused_correction
        lead = x.shape[:-2]
        ny, nx = x.shape[-2:]
        L = 1
        for d in lead:
            L *= int(d)
        L = max(L, 1)
        flat = lambda a: jnp.broadcast_to(
            jnp.asarray(a, jnp.float32), lead + (1, 1)).reshape((L,))
        dtv = jnp.broadcast_to(
            jnp.asarray(dt, jnp.float32), lead).reshape((L,))
        pres, velc = fused_correction(
            x.reshape((L, ny, nx)), pres_old.reshape((L, ny, nx)),
            vel.reshape((L, 2, ny, nx)),
            flat(mx), flat(mp), -0.5 * dtv * h, ih2,
            grad_signs=grad_signs)
        return velc.reshape(vel.shape), pres.reshape(x.shape)
    dt_b = dt[:, None, None, None] if jnp.ndim(dt) == 1 else dt
    if not remove_mean:
        pres = x + pres_old
    else:
        dp = x - mx
        pres = dp + pres_old - mp
    if grad_signs is None:
        dv = pressure_gradient_update_fused(pres, h, dt_b, spmd_safe)
    else:
        sx_lo, sx_hi, sy_lo, sy_hi = grad_signs
        dv = pressure_gradient_update_bc(pres, h, dt_b, sx_lo, sx_hi,
                                         sy_lo, sy_hi, spmd_safe,
                                         px, py)
    return vel + dv * ih2, pres
