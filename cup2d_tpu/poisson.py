"""Matrix-free pressure-Poisson solver: block-preconditioned BiCGSTAB.

TPU-native replacement for the reference's GPU subsystem
(`/root/reference/cuda.cu:24-548` BiCGSTABSolver + the host COO assembly at
`main.cpp:5747-6020, 7031-7115`): instead of materializing a distributed
sparse matrix for cuSPARSE, the variable-resolution 5-point Laplacian is
applied *matrix-free* as a stencil (a function passed in by the caller), and
the whole Krylov iteration runs inside one `lax.while_loop` on device — no
host round-trips per iteration, no explicit halo staging (XLA inserts the
collectives when the operand arrays are sharded).

The preconditioner is the reference's exact block-Jacobi inverse
(`main.cpp:6451-6488`): P = -inv(A_local) where A_local is the BS^2 x BS^2
single-block 5-point Laplacian (`getA_local`, main.cpp:46-57), applied as a
batched [nblocks, BS^2] x [BS^2, BS^2] GEMM — MXU work, where the reference
used a batched cuBLAS GEMM (`cuda.cu:484-486`).

Algorithm: flexible BiCGSTAB with Linf convergence on max(tol_abs,
tol_rel * |r0|_inf), breakdown detection with re-orthogonalization restarts,
and best-solution tracking (`x_opt`, cuda.cu:525-542) — same control flow as
the reference's device loop (cuda.cu:403-548).
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


def block_precond_matrix(bs: int, dtype=np.float64) -> np.ndarray:
    """P_inv = -inv(A_local), the negated inverse of the bs^2 x bs^2
    single-block 5-point Laplacian with homogeneous Dirichlet truncation at
    the block edge (reference getA_local main.cpp:46-57 + Cholesky inversion
    main.cpp:6451-6488; dense inverse here — same matrix, host-side once)."""
    n = bs * bs
    ii = np.arange(n)
    xi, yi = ii % bs, ii // bs
    a = np.zeros((n, n), dtype=np.float64)
    dx = np.abs(xi[:, None] - xi[None, :])
    dy = np.abs(yi[:, None] - yi[None, :])
    a[(dx + dy) == 1] = -1.0
    np.fill_diagonal(a, 4.0)
    return (-np.linalg.inv(a)).astype(dtype)


def apply_block_precond(r: jnp.ndarray, p_inv: jnp.ndarray, bs: int) -> jnp.ndarray:
    """z = P_inv r applied per bs x bs tile of a [Ny, Nx] field (batched GEMM).

    Works for any [Ny, Nx] divisible by bs; the AMR path passes fields
    already shaped [N, bs, bs] via `apply_block_precond_blocks`.
    """
    ny, nx = r.shape[-2], r.shape[-1]
    nby, nbx = ny // bs, nx // bs
    tiles = r.reshape(*r.shape[:-2], nby, bs, nbx, bs)
    tiles = jnp.swapaxes(tiles, -3, -2).reshape(*r.shape[:-2], nby, nbx, bs * bs)
    z = tiles @ p_inv.T  # P_inv is symmetric; .T keeps intent explicit
    z = z.reshape(*r.shape[:-2], nby, nbx, bs, bs).swapaxes(-3, -2)
    return z.reshape(r.shape)


def apply_block_precond_blocks(r: jnp.ndarray, p_inv: jnp.ndarray) -> jnp.ndarray:
    """Same, for block-forest layout [N, bs, bs]."""
    n, bs, _ = r.shape
    return (r.reshape(n, bs * bs) @ p_inv.T).reshape(n, bs, bs)


class BiCGSTABResult(NamedTuple):
    x: jnp.ndarray
    iters: jnp.ndarray
    residual: jnp.ndarray   # Linf of best residual seen
    converged: jnp.ndarray


class _State(NamedTuple):
    x: jnp.ndarray
    r: jnp.ndarray
    rhat: jnp.ndarray
    p: jnp.ndarray
    v: jnp.ndarray
    rho: jnp.ndarray
    alpha: jnp.ndarray
    omega: jnp.ndarray
    it: jnp.ndarray
    restarts: jnp.ndarray
    x_opt: jnp.ndarray
    norm_opt: jnp.ndarray
    norm0: jnp.ndarray
    best_it: jnp.ndarray
    done: jnp.ndarray


def bicgstab(
    A: Callable[[jnp.ndarray], jnp.ndarray],
    b: jnp.ndarray,
    M: Callable[[jnp.ndarray], jnp.ndarray] | None = None,
    x0: jnp.ndarray | None = None,
    tol: float = 1e-3,
    tol_rel: float = 1e-2,
    max_iter: int = 1000,
    max_restarts: int = 0,
    sum_dtype=None,
    stall_window: int = 25,
) -> BiCGSTABResult:
    """Preconditioned flexible BiCGSTAB, whole loop jitted on device.

    A, M are matrix-free operators on fields shaped like ``b``. Convergence
    is Linf(r) <= max(tol, tol_rel * Linf(r0)) — the reference's criterion
    (cuda.cu:434-436, 525-542). Inner products accumulate in ``sum_dtype``
    (default: b's dtype; pass jnp.float64 for compensated f32 runs).

    Beyond the reference's breakdown-restart (cuda.cu:457-477, budget
    ``max_restarts``), stagnation triggers an unconditional *true-residual
    restart*: if Linf(r) hasn't improved for ``stall_window`` iterations,
    the recursive residual is replaced by b - A(x_opt) and the Krylov space
    rebuilt from there. The reference never needs this because it iterates
    in f64; the TPU production path is f32, where the recursive residual
    drifts from the true one after ~50-100 iterations and the un-restarted
    iteration flatlines above tolerance. Costs one extra operator
    application per restart (lax.cond — not per iteration).
    """
    if M is None:
        M = lambda v: v
    dt_ = b.dtype
    sd = sum_dtype or dt_

    def dot(a_, b_):
        return jnp.sum((a_ * b_).astype(sd)).astype(dt_)

    def linf(a_):
        return jnp.max(jnp.abs(a_))

    x0 = jnp.zeros_like(b) if x0 is None else x0
    r0 = b - A(x0)
    norm0 = linf(r0)
    target = jnp.maximum(jnp.asarray(tol, dt_), tol_rel * norm0)
    one = jnp.asarray(1.0, dt_)

    init = _State(
        x=x0, r=r0, rhat=r0, p=jnp.zeros_like(b), v=jnp.zeros_like(b),
        rho=one, alpha=one, omega=one,
        it=jnp.asarray(0, jnp.int32), restarts=jnp.asarray(0, jnp.int32),
        x_opt=x0, norm_opt=norm0, norm0=norm0,
        best_it=jnp.asarray(0, jnp.int32),
        done=norm0 <= target,
    )

    breakdown_eps = jnp.asarray(1e-21 if dt_ == jnp.float64 else 1e-30, dt_)

    def cond(s: _State):
        return (~s.done) & (s.it < max_iter)

    def body(s: _State):
        rho_probe = dot(s.rhat, s.r)
        # serious breakdown -> restart with rhat = r (cuda.cu:457-477)
        norm_r = jnp.sqrt(dot(s.r, s.r))
        norm_rhat = jnp.sqrt(dot(s.rhat, s.rhat))
        breakdown = jnp.abs(rho_probe) < (
            jnp.asarray(1e-16, dt_) * norm_r * norm_rhat + breakdown_eps
        )
        can_restart = s.restarts < max_restarts
        stalled = (s.it - s.best_it) >= stall_window
        do_restart = (breakdown & can_restart) | stalled
        give_up = breakdown & ~can_restart & ~stalled

        # true-residual restart from the best solution seen; norm_opt is
        # refreshed from the TRUE residual so a drifted-low recursive norm
        # can't freeze x_opt and replay identical stall cycles
        x, r = jax.lax.cond(
            do_restart,
            lambda: (s.x_opt, b - A(s.x_opt)),
            lambda: (s.x, s.r),
        )
        norm_opt0 = jnp.where(do_restart, linf(r), s.norm_opt)
        rhat = jnp.where(do_restart, r, s.rhat)
        rho_new = jnp.where(do_restart, dot(rhat, r), rho_probe)
        beta = jnp.where(
            do_restart, jnp.zeros_like(rho_new),
            (rho_new / (s.rho + breakdown_eps)) * (s.alpha / (s.omega + breakdown_eps)),
        )
        p = r + beta * (s.p - s.omega * s.v)
        z = M(p)
        v = A(z)
        alpha = rho_new / (dot(rhat, v) + breakdown_eps)
        h = x + alpha * z
        sres = r - alpha * v
        zs = M(sres)
        t = A(zs)
        omega = dot(t, sres) / (dot(t, t) + breakdown_eps)
        x = h + omega * zs
        r = sres - omega * t

        norm = linf(r)
        better = norm < norm_opt0
        x_opt = jnp.where(better, x, s.x_opt)
        norm_opt = jnp.where(better, norm, norm_opt0)
        done = (norm <= target) | give_up

        # only breakdown-triggered restarts consume the reference's
        # max_restarts budget; stall restarts are unbudgeted
        return _State(
            x=x, r=r, rhat=rhat, p=p, v=v,
            rho=rho_new, alpha=alpha, omega=omega,
            it=s.it + 1,
            restarts=s.restarts + (breakdown & can_restart).astype(jnp.int32),
            x_opt=x_opt, norm_opt=norm_opt, norm0=s.norm0,
            best_it=jnp.where(better | do_restart, s.it, s.best_it),
            done=done,
        )

    final = jax.lax.while_loop(cond, body, init)
    return BiCGSTABResult(
        x=final.x_opt,
        iters=final.it,
        residual=final.norm_opt,
        converged=final.norm_opt <= target,
    )
