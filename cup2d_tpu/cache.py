"""Persistent XLA compilation cache for every entry point.

Adaptive runs compile one executable per (bucket, window-capacity)
combination — tens of multi-second TPU compiles that are identical
across process restarts of the same case. The CLI, bench and driver
entry points all funnel through here; library users can call it once
before building a sim. Safe to call repeatedly.
"""

from __future__ import annotations

import os


def enable_compilation_cache() -> None:
    import jax
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("CUP2D_CACHE",
                           os.path.expanduser("~/.cache/cup2d_tpu_xla")))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # older jax without the knob: run uncached
