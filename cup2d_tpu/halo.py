"""Ghost-cell assembly as gather tables: the TPU form of BlockLab.

The reference assembles per-block ghost-padded scratch tiles with 770
lines of branchy pointer code + an MPI message schedule
(`/root/reference/main.cpp:2231-3000` BlockLab, `909-2142` Setup/sync1).
The key observation (SURVEY.md §7): every ghost value is a fixed LINEAR
combination of stored cell values — same-level copies (weight 1),
fine-to-coarse 2x2 averages (weight 1/4), coarse-to-fine interpolation
(TestInterp 2nd-order Taylor, the 1-D directional variant on faces, and
the LI/LE blends toward interior fine cells, main.cpp:2203-2230 +
2689-2999), and the free-slip / Neumann wall ghosts.

So each (grid topology, stencil width, field kind) pair compiles ONCE —
on the host, at regrid time — into three arrays:

    dest [G]      flat index into the lab array [n_active * L * L]
    idx  [G, K]   flat indices into field storage [capacity * BS * BS]
    w    [G, K, dim] weights (vector fields carry per-component signs
                  from the free-slip mirror)

and the per-step device work is one batched gather + weighted sum —
no message passing, no branches, MXU/VPU-friendly. The table builder
below IS the specification of the reference's interpolation, written
against global cell coordinates instead of lab-pointer arithmetic; the
weights are validated cell-for-cell against polynomial reproduction
tests (exact for degree <= 2 where the reference is 2nd order).
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .forest import Forest


def _cdiv(a: int, b: int) -> int:
    """C-style truncating integer division (the reference's `/`)."""
    return int(math.trunc(a / b))


class Expr(dict):
    """Linear expression: {(slot, cy, cx): weight_vec ndarray[dim]}."""

    def scaled(self, f):
        return Expr({k: w * f for k, w in self.items()})

    def add(self, other, f=1.0):
        for k, w in other.items():
            cur = self.get(k)
            self[k] = w * f if cur is None else cur + w * f

    @staticmethod
    def combo(*pairs):
        e = Expr()
        for other, f in pairs:
            e.add(other, f)
        return e


class HaloTables(NamedTuple):
    """Registered as a jax pytree with the int metadata as static aux
    data, so jitted functions taking tables as arguments re-use compiled
    executables whenever a regrid reproduces previously-seen shapes.

    Rows are split by structure: the vast majority of ghost cells are
    single-source copies with a per-component sign (same-level neighbor
    copies + free-slip/Neumann wall mirrors) — those live in the
    ``simple`` arrays as one gather + sign multiply; only the
    coarse-fine interpolation rows carry the [K, dim] weight matrices.
    This shrinks table memory and the per-step einsum ~5-10x vs padding
    every row to the interpolation width."""

    # simple rows: lab[dest_s] = field[src] * sign
    dest_s: jnp.ndarray   # [Gs] int32 into labs flat [n_active*L*L]
    src: jnp.ndarray      # [Gs] int32 into fields flat [cap*BS*BS]
    src_ord: jnp.ndarray  # [Gs] int32 into SFC-ordered [n_active*BS*BS]
    sign: jnp.ndarray     # [Gs, dim]
    # general rows: lab[dest] = sum_k field[idx[k]] * w[k]
    dest: jnp.ndarray     # [Gg] int32 into labs flat [n_active*L*L]
    idx: jnp.ndarray      # [Gg, K] int32 into fields flat [cap*BS*BS]
    idx_ord: jnp.ndarray  # [Gg, K] int32 into SFC-ordered [n_active*BS*BS]
    w: jnp.ndarray        # [Gg, K, dim]
    n_active: int
    L: int
    g: int
    dim: int


jax.tree_util.register_pytree_node(
    HaloTables,
    lambda t: ((t.dest_s, t.src, t.src_ord, t.sign,
                t.dest, t.idx, t.idx_ord, t.w),
               (t.n_active, t.L, t.g, t.dim)),
    lambda aux, ch: HaloTables(*ch, *aux),
)


# cross-regrid template memo for build_tables: group keys are
# position-independent, so a pattern built once serves every later
# regrid (entries are verified against each member's topology trace
# before use — see build_tables)
_TEMPLATE_CACHE: dict = {}
# bound on templates summed across ALL keys (each template is a tuple
# of ~13 small numpy arrays; see the eviction note in build_tables)
_TEMPLATE_TOTAL_CAP = 8192


class _TopoIndex:
    """Dense per-level topology arrays for vectorized group keys,
    template-trace verification and role instantiation.

    The reference answers its per-block topology queries through hash
    maps under OpenMP (treef/getf, main.cpp:672-738); the builder's
    Python equivalents (forest.slot / owner_relation dict probes) were
    the regrid-time bottleneck at 1e4+ blocks. A level-l grid has
    (bpdx<<l) x (bpdy<<l) positions — small enough (sum ~4/3 * finest)
    to materialize as flat arrays once per regrid, turning every query
    batch into one fancy-indexed gather."""

    def __init__(self, forest: Forest, order: np.ndarray):
        cfg = forest.cfg
        L = cfg.level_max
        self.lmax = L
        self.nbx = np.array([cfg.bpdx << l for l in range(L)], np.int64)
        self.nby = np.array([cfg.bpdy << l for l in range(L)], np.int64)
        sizes = self.nbx * self.nby
        self.off = np.zeros(L + 1, np.int64)
        np.cumsum(sizes, out=self.off[1:])
        tot = int(self.off[-1])
        self.slot = np.full(tot, -1, np.int32)
        lv = forest.level[order].astype(np.int64)
        bi = forest.bi[order].astype(np.int64)
        bj = forest.bj[order].astype(np.int64)
        self.slot[self.off[lv] + bj * self.nbx[lv] + bi] = order

        # owner_relation codes per position: 0 active, -1 refined,
        # -2 parent active, -3 nothing (forest.owner_relation)
        self.rel = np.full(tot, -3, np.int8)
        act = [self.slot[self.off[l]:self.off[l + 1]].reshape(
            int(self.nby[l]), int(self.nbx[l])) >= 0 for l in range(L)]
        for l in range(L):
            r = np.full(act[l].shape, -3, np.int8)
            if l + 1 < L:
                a1 = act[l + 1]
                refined = (a1.reshape(int(self.nby[l]), 2,
                                      int(self.nbx[l]), 2)
                           .any(axis=(1, 3)))
                r[refined] = -1
            if l > 0:
                pa = np.repeat(np.repeat(act[l - 1], 2, 0), 2, 1)
                r[pa & (r == -3)] = -2
            r[act[l]] = 0
            self.rel[self.off[l]:self.off[l + 1]] = r.ravel()

    def _flat(self, l, i, j):
        """Flat index + validity mask for (possibly out-of-range)
        coordinate arrays; invalid positions index 0 with mask False."""
        ok = (l >= 0) & (l < self.lmax)
        lc = np.clip(l, 0, self.lmax - 1)
        # not &=: l may broadcast against wider i/j (e.g. [M,1] vs [M,T])
        ok = ok & (i >= 0) & (i < self.nbx[lc]) \
            & (j >= 0) & (j < self.nby[lc])
        idx = np.where(ok, self.off[lc] + j * self.nbx[lc] + i, 0)
        return idx, ok

    def slot_at(self, l, i, j):
        idx, ok = self._flat(l, i, j)
        return np.where(ok, self.slot[idx], -1)

    def rel_at(self, l, i, j):
        idx, ok = self._flat(l, i, j)
        return np.where(ok, self.rel[idx], np.int8(-3))

    def abs_of(self, l0, bi0, bj0, dl, ri, rj):
        """Vectorized _abs_of over member arrays [M] x rel arrays [T]:
        returns [M, T] absolute (l, i, j)."""
        al = l0[:, None] + dl[None, :]
        up = np.maximum(dl, 0)[None, :]
        dn = np.maximum(-dl, 0)[None, :]
        ai = (bi0[:, None] << up >> dn) + ri[None, :]
        aj = (bj0[:, None] << up >> dn) + rj[None, :]
        return al, ai, aj


def _rel_of(l, bi, bj, sl, si, sj):
    """Relative coords of source block (sl, si, sj) wrt block (l, bi, bj).
    dl >= -1 always (the builder only reaches the parent level)."""
    dl = sl - l
    if dl >= 0:
        return (dl, si - (bi << dl), sj - (bj << dl))
    return (dl, si - (bi >> -dl), sj - (bj >> -dl))


def _abs_of(l, bi, bj, dl, ri, rj):
    if dl >= 0:
        return (l + dl, (bi << dl) + ri, (bj << dl) + rj)
    return (l + dl, (bi >> -dl) + ri, (bj >> -dl) + rj)


class _RecordingForest:
    """Forest view that records every topology query the lab builder
    makes, so blocks whose local patterns answer identically can reuse
    the (expensive) ghost-expression structure with slots translated."""

    def __init__(self, f: Forest, l: int, bi: int, bj: int):
        self.f = f
        self.cfg = f.cfg
        self.bs = f.bs
        self.blocks = f.blocks
        self.level = f.level
        self.bi = f.bi
        self.bj = f.bj
        self._base = (l, bi, bj)
        self.trace: dict[tuple, int] = {}

    def nblocks_at(self, l):
        return self.f.nblocks_at(l)

    def slot(self, l, i, j):
        s = self.f.slot(l, i, j)
        self.trace[("s",) + _rel_of(*self._base, l, i, j)] = s >= 0
        return s

    def owner_relation(self, l, i, j):
        r = self.f.owner_relation(l, i, j)
        self.trace[("r",) + _rel_of(*self._base, l, i, j)] = r
        return r


def build_tables(forest: Forest, order: np.ndarray, g: int,
                 tensorial: bool, dim: int, builder_cls=None,
                 topo: "_TopoIndex | None" = None) -> HaloTables:
    """Build gather tables for all ghost cells of all active blocks.

    The expression builder is O(ghost cells x interpolation depth) of
    Python per block — prohibitive at the reference case's 1e4-1e5
    blocks (VERDICT r1 Weak #3). But a block's ghost expressions depend
    only on its LOCAL pattern: wall sides, position parity within the
    parent, and the refinement relations of every block the builder
    consults — not on absolute position or level (the weights carry no
    h). So blocks are grouped by a cheap 3x3-relation key (computed
    vectorized over a dense per-level topology index, _TopoIndex), the
    expressions are built ONCE per distinct pattern (on a recording
    view that captures the full query trace), every member verifies the
    trace in one batched gather (guarding rare deeper-refinement
    differences the key can't see — each such variant gets its own
    cached template), and instantiation is a numpy role->slot gather.
    Typical adapted forests have tens of distinct patterns across
    thousands of blocks.

    ``builder_cls`` swaps the ghost-expression specification: the
    default `_LabBuilder` is the reference BlockLab; `flux.py` passes a
    builder producing the makeFlux variable-resolution Poisson ghosts
    (same (forest, g, tensorial, dim) constructor + `block_ghosts`).

    Templates are memoized ACROSS regrids (module cache keyed by the
    position-independent group key, holding one template per observed
    deep variant): the same patterns recur at every regrid, so
    steady-state rebuilds skip all expression construction. The
    per-member trace verification still runs, so a cached template is
    never applied to a block whose deeper neighborhood differs.
    """
    builder_cls = builder_cls or _LabBuilder
    bs = forest.bs
    L = bs + 2 * g
    # keyed on the class OBJECT: two builders sharing a name must not
    # exchange templates (trace replay checks topology, not weights)
    cache_base = (builder_cls, bs, g, tensorial, dim,
                  forest.cfg.bpdx, forest.cfg.bpdy, forest.cfg.level_max)
    n_act = len(order)
    lv, bia, bja = forest.level, forest.bi, forest.bj

    # ---- group by local-pattern key (vectorized over the topo index;
    # callers building several table sets per regrid pass one shared
    # index instead of rebuilding it per call) ------------------------------
    if topo is None:
        topo = _TopoIndex(forest, order)
    lvo = lv[order].astype(np.int64)
    bio = bia[order].astype(np.int64)
    bjo = bja[order].astype(np.int64)
    nbxv = np.int64(forest.cfg.bpdx) << lvo
    nbyv = np.int64(forest.cfg.bpdy) << lvo
    keyv = ((bio & 1)
            | (bjo & 1) << 1
            | (bio == 0).astype(np.int64) << 2
            | (bio == nbxv - 1).astype(np.int64) << 3
            | (bjo == 0).astype(np.int64) << 4
            | (bjo == nbyv - 1).astype(np.int64) << 5)
    shift = 6
    for cy in (-1, 0, 1):
        for cx in (-1, 0, 1):
            if cx == 0 and cy == 0:
                continue
            r = topo.rel_at(lvo, bio + cx, bjo + cy).astype(np.int64)
            keyv |= (-r) << shift     # rel in {0,-1,-2,-3} -> 2 bits
            shift += 2
    uniq, inv = np.unique(keyv, return_inverse=True)
    by_group = np.argsort(inv, kind="stable")
    bounds = np.searchsorted(inv[by_group], np.arange(len(uniq) + 1))
    groups = {int(uniq[q]): by_group[bounds[q]:bounds[q + 1]]
              for q in range(len(uniq))}

    # accumulators: simple rows (dest, src, sign) / general rows
    sd_parts, ss_parts, sg_parts = [], [], []
    gd_parts, gi_parts, gw_parts = [], [], []

    def classify_template(exprs, l0, bi0, bj0):
        """Split a block's expressions into a simple template
        (1 term, |w| == 1 componentwise) and a general template, with
        sources as (role, cellofs)."""
        roles: dict[tuple, int] = {}
        s_dest, s_role, s_cell, s_sign = [], [], [], []
        g_dest, g_rows = [], []
        kmax_g = 1
        for (ly, lx), e in exprs.items():
            items = list(e.items())
            if len(items) == 1 and np.all(np.abs(items[0][1]) == 1.0):
                (slot, cy, cx), wv = items[0]
                rel = _rel_of(l0, bi0, bj0, int(lv[slot]),
                              int(bia[slot]), int(bja[slot]))
                s_dest.append(ly * L + lx)
                s_role.append(roles.setdefault(rel, len(roles)))
                s_cell.append(cy * bs + cx)
                s_sign.append(wv)
            else:
                row = []
                for (slot, cy, cx), wv in items:
                    rel = _rel_of(l0, bi0, bj0, int(lv[slot]),
                                  int(bia[slot]), int(bja[slot]))
                    row.append((roles.setdefault(rel, len(roles)),
                                cy * bs + cx, wv))
                kmax_g = max(kmax_g, len(row))
                g_dest.append(ly * L + lx)
                g_rows.append(row)
        Gg = len(g_dest)
        role_m = np.zeros((Gg, kmax_g), np.int64)
        cell_m = np.zeros((Gg, kmax_g), np.int64)
        w_m = np.zeros((Gg, kmax_g, dim), np.float64)
        valid = np.zeros((Gg, kmax_g), bool)
        for r, row in enumerate(g_rows):
            for kk, (ro, ce, wv) in enumerate(row):
                role_m[r, kk] = ro
                cell_m[r, kk] = ce
                w_m[r, kk] = wv
                valid[r, kk] = True
        return (roles,
                np.asarray(s_dest, np.int64), np.asarray(s_role, np.int64),
                np.asarray(s_cell, np.int64),
                np.asarray(s_sign, np.float64).reshape(len(s_dest), dim),
                np.asarray(g_dest, np.int64), role_m, cell_m, w_m, valid)

    def make_template(rep: int):
        """Record + classify the ghost expressions of block ``rep``."""
        s0 = int(order[rep])
        l0, bi0, bj0 = int(lvo[rep]), int(bio[rep]), int(bjo[rep])
        rec = _RecordingForest(forest, l0, bi0, bj0)
        exprs = builder_cls(rec, g, tensorial, dim).block_ghosts(s0)
        (roles, s_dest, s_role, s_cell, s_sign,
         g_dest, role_m, cell_m, w_m, valid) = classify_template(
            exprs, l0, bi0, bj0)
        role_arr = np.array(list(roles.keys()), np.int64).reshape(
            len(roles), 3)
        tr = list(rec.trace.items())
        tr_kind = np.array([0 if k[0] == "s" else 1 for k, _ in tr],
                           np.int8)
        tr_rel = np.array([k[1:] for k, _ in tr],
                          np.int64).reshape(len(tr), 3)
        tr_ans = np.array([int(v) for _, v in tr], np.int64)
        return (role_arr, s_dest, s_role, s_cell, s_sign,
                g_dest, role_m, cell_m, w_m, valid,
                tr_kind, tr_rel, tr_ans)

    for key, members in groups.items():
        # each key holds a LIST of templates: the common pattern plus
        # any deeper-refinement variants the key can't distinguish.
        # Every member instantiates from the first template whose full
        # topology trace it matches; members matching none get their own
        # template appended (a member always matches the template built
        # from itself, so the loop always terminates). This replaces the
        # r2 per-regrid naive fallback — deep variants now cost the
        # expression build ONCE instead of at every regrid.
        ck = cache_base + (key,)
        cands = _TEMPLATE_CACHE.get(ck)
        if cands is None:
            # bounded LRU: evict oldest (insertion-ordered dict) so the
            # steady-state hot set survives the cap, unlike a clear()
            while len(_TEMPLATE_CACHE) >= 2048:
                del _TEMPLATE_CACHE[next(iter(_TEMPLATE_CACHE))]
            cands = _TEMPLATE_CACHE[ck] = []
        else:
            # refresh recency — reads must protect the every-regrid hot
            # set from both eviction paths (key-count and total-template)
            _TEMPLATE_CACHE[ck] = _TEMPLATE_CACHE.pop(ck)

        remaining = np.asarray(members)
        ti = 0
        while len(remaining):
            if ti < len(cands):
                tpl = cands[ti]
                ti += 1
            else:
                # built from remaining[0], so it always matches at least
                # that member — guaranteed progress. The 64-variant cap
                # bounds pathological caches; uncached templates still
                # serve the current call.
                tpl = make_template(int(remaining[0]))
                if len(cands) < 64:
                    # cap TOTAL templates, not just keys: each key may
                    # hold up to 64 variants of ~13 arrays, so a
                    # key-only bound admits a ~64x footprint blow-up on
                    # pathological forests (ADVICE r2). Evict whole
                    # oldest keys (LRU — reads refresh recency above),
                    # skipping the live list rather than stopping at it
                    # so the cap still binds when it happens to be
                    # oldest.
                    while (sum(len(v) for v in _TEMPLATE_CACHE.values())
                           >= _TEMPLATE_TOTAL_CAP):
                        victim = None
                        for k0, v in _TEMPLATE_CACHE.items():
                            if v is not cands:
                                victim = k0
                                break
                        if victim is None:
                            break          # only the live list remains
                        del _TEMPLATE_CACHE[victim]
                    cands.append(tpl)
                    ti += 1
            (role_arr, s_dest, s_role, s_cell, s_sign,
             g_dest, role_m, cell_m, w_m, valid,
             tr_kind, tr_rel, tr_ans) = tpl

            # verify the topology trace of all remaining members against
            # this template in one vectorized gather batch
            l0v, b0v, c0v = lvo[remaining], bio[remaining], bjo[remaining]
            al, ai, aj = topo.abs_of(
                l0v, b0v, c0v, tr_rel[:, 0], tr_rel[:, 1], tr_rel[:, 2])
            got = np.where(
                tr_kind[None, :] == 0,
                (topo.slot_at(al, ai, aj) >= 0).astype(np.int64),
                topo.rel_at(al, ai, aj).astype(np.int64))
            ok = (got == tr_ans[None, :]).all(axis=1)
            rl, rxi, ryj = topo.abs_of(
                l0v, b0v, c0v,
                role_arr[:, 0], role_arr[:, 1], role_arr[:, 2])
            role_slots_all = topo.slot_at(rl, rxi, ryj).astype(np.int64)
            ok &= (role_slots_all >= 0).all(axis=1)
            if not ok.any():
                continue

            # vectorized instantiation over the matching members
            M = int(ok.sum())
            role_slots = role_slots_all[ok]
            bases = remaining[ok].astype(np.int64) * (L * L)
            if len(s_dest):
                sd_parts.append(
                    (bases[:, None] + s_dest[None, :]).reshape(-1))
                ss_parts.append(
                    (role_slots[:, s_role] * bs * bs + s_cell).reshape(-1))
                sg_parts.append(np.broadcast_to(
                    s_sign, (M,) + s_sign.shape).reshape(-1, dim))
            if len(g_dest):
                gd_parts.append(
                    (bases[:, None] + g_dest[None, :]).reshape(-1))
                gi = np.where(
                    valid[None],
                    role_slots[:, role_m] * bs * bs + cell_m[None],
                    0)
                gi_parts.append(gi.reshape(-1, gi.shape[-1]))
                gw_parts.append(np.broadcast_to(
                    w_m, (M,) + w_m.shape).reshape(-1, *w_m.shape[1:]))
            remaining = remaining[~ok]

    # ---- assemble, padding general rows to the global K ------------------
    # single-pass preallocate-and-fill (cast on assignment): a
    # concatenate-then-astype chain copies every big array twice and was
    # ~40% of the warm rebuild
    f32 = jnp.dtype(forest.dtype).name
    kmax = max((a.shape[1] for a in gi_parts), default=1)

    def cat(parts, shape_tail, dtype, pad_k=False):
        n = sum(p.shape[0] for p in parts)
        out = np.zeros((n,) + shape_tail, dtype)
        o = 0
        for p in parts:
            if pad_k:
                out[o:o + p.shape[0], :p.shape[1]] = p
            else:
                out[o:o + p.shape[0]] = p
            o += p.shape[0]
        return out

    dest_s = cat(sd_parts, (), np.int32)
    src = cat(ss_parts, (), np.int32)
    sign = cat(sg_parts, (dim,), f32)
    dest = cat(gd_parts, (), np.int32)
    idx = cat(gi_parts, (kmax,), np.int32, pad_k=True)
    w = cat(gw_parts, (kmax, dim), f32, pad_k=True)

    # remap to the SFC-ordered compact layout (for operands stored as
    # [n_active, BS, BS], e.g. the Poisson Krylov vectors)
    ordpos_of = np.zeros(forest.capacity, np.int32)
    ordpos_of[order] = np.arange(n_act, dtype=np.int32)
    bs2 = bs * bs
    sq, sr = np.divmod(src, bs2)
    src_ord = ordpos_of[sq] * bs2 + sr
    iq, ir = np.divmod(idx, bs2)
    idx_ord = ordpos_of[iq] * bs2 + ir
    # HOST (numpy) leaves by design: tables are host-built metadata that
    # pad_tables post-processes and amr._refresh uploads in ONE async
    # device_put. Returning device arrays here made pad_tables pull
    # every leaf back across the TPU tunnel (~70 synchronous round
    # trips, ~8 s per regrid, measured). jit callers accept numpy
    # leaves directly (implicit transfer at call time).
    return HaloTables(
        dest_s=dest_s, src=src,
        src_ord=src_ord, sign=sign,
        dest=dest, idx=idx,
        idx_ord=idx_ord, w=w,
        n_active=n_act, L=L, g=g, dim=dim,
    )


def _bucket(n: int, lo: int = 64) -> int:
    return max(lo, 1 << max(0, (n - 1)).bit_length())


# ---------------------------------------------------------------------------
# Same-level face-copy fast path (round 5)
#
# On production forests the overwhelming majority of ghost rows are
# plain same-level neighbor copies (87% measured in the r4 overlap
# audit; ~97% of faces on the 1e4-block near-uniform probe). Their
# per-row scatter lowering is what makes lab assembly slow on TPU (the
# r5 trace put ~83 ms PER advect assembly at the 16k pad — 2M scalar
# scatter rows). But a same-level face strip is a RECTANGLE: one
# block-row gather per neighbor offset (embedding-style, the fast TPU
# gather pattern) plus one static-slice masked write paints every such
# strip for all blocks at once. The residual rows (coarse/fine
# interpolation, walls, skin blocks' BC overwrites) stay in the gather
# tables, whose row count collapses to the interface surface.
#
# Validity of the copy (the final lab value is exactly the neighbor's
# interior cell, weight +1, all components) holds precisely when the
# same-level neighbor block exists at that offset: pass 2 only touches
# coarse-face regions, and pass 3 (wall BC) only overwrites strips on
# wall sides, which by construction have no neighbor. The row filter
# below drops exactly the covered dest cells; equivalence is pinned by
# tests/test_flux.py.
# ---------------------------------------------------------------------------

# offset order: W, E, S, N, SW, SE, NW, NE — faces first so
# non-tensorial (face-only) sets use offsets [:4]
_FC_OFFSETS = ((-1, 0), (1, 0), (0, -1), (0, 1),
               (-1, -1), (1, -1), (-1, 1), (1, 1))


class FastHalo(NamedTuple):
    """A padded+filtered HaloTables plus the face-copy structure.
    ``corners`` is static aux data (tensorial sets paint 8 regions,
    face-only sets 4)."""

    t: HaloTables
    nb: jnp.ndarray     # [8, n_pad] int32 ordered positions
    mask: jnp.ndarray   # [8, n_pad] field-dtype 1.0/0.0
    corners: bool


jax.tree_util.register_pytree_node(
    FastHalo,
    lambda f: ((f.t, f.nb, f.mask), (f.corners,)),
    lambda aux, ch: FastHalo(*ch, *aux),
)


def build_face_copy(forest: Forest, order: np.ndarray, n_pad: int,
                    topo: "_TopoIndex | None" = None):
    """Host build of the per-offset same-level neighbor index + mask
    (one [8, n_pad] pair shared by every table set of a regrid)."""
    if topo is None:
        topo = _TopoIndex(forest, order)
    n_real = len(order)
    assert n_pad > n_real
    lv = forest.level[order].astype(np.int64)
    bi = forest.bi[order].astype(np.int64)
    bj = forest.bj[order].astype(np.int64)
    ordpos_of = np.full(forest.capacity, n_real, np.int64)
    ordpos_of[order] = np.arange(n_real)
    fdt = np.dtype(jnp.dtype(forest.dtype).name)
    nb = np.full((8, n_pad), n_real, np.int32)
    mask = np.zeros((8, n_pad), fdt)
    for o, (cx, cy) in enumerate(_FC_OFFSETS):
        s = topo.slot_at(lv, bi + cx, bj + cy)
        ok = s >= 0
        nb[o, :n_real] = np.where(ok, ordpos_of[np.maximum(s, 0)],
                                  n_real)
        mask[o, :n_real][ok] = 1.0
    return nb, mask


def _fc_regions(g: int, bs: int, corners: bool):
    """(dest-slice-y, dest-slice-x, src-slice-y, src-slice-x) per
    offset, in _FC_OFFSETS order."""
    L = bs + 2 * g
    lo = slice(0, g)
    hi = slice(g + bs, L)
    mid = slice(g, g + bs)
    s_lo = slice(bs - g, bs)     # source strip adjacent to the dest
    s_hi = slice(0, g)
    s_mid = slice(0, bs)
    regs = [
        (mid, lo, s_mid, s_lo),    # W
        (mid, hi, s_mid, s_hi),    # E
        (lo, mid, s_lo, s_mid),    # S
        (hi, mid, s_hi, s_mid),    # N
    ]
    if corners:
        regs += [
            (lo, lo, s_lo, s_lo),      # SW
            (lo, hi, s_lo, s_hi),      # SE
            (hi, lo, s_hi, s_lo),      # NW
            (hi, hi, s_hi, s_hi),      # NE
        ]
    return regs


def filter_face_rows(t: HaloTables, mask: np.ndarray,
                     corners: bool) -> HaloTables:
    """Drop table rows whose dest cell lies in a face-copy-covered
    region (the structured writes paint them). Host-side, before
    pad_tables."""
    bs = t.L - 2 * t.g
    cov_cell = np.zeros((t.L, t.L), bool)
    regions = _fc_regions(t.g, bs, corners)
    cell_of = {}
    for o, (sy, sx, _, _) in enumerate(regions):
        m = np.zeros((t.L, t.L), bool)
        m[sy, sx] = True
        cell_of[o] = m.reshape(-1)
    # covered[dest] = mask of the offset owning that dest cell
    L2 = t.L * t.L
    blk = np.asarray(t.dest_s) // L2
    cell = np.asarray(t.dest_s) % L2
    drop = np.zeros(len(t.dest_s), bool)
    for o in range(len(regions)):
        drop |= cell_of[o][cell] & (mask[o][blk] > 0)
    keep = ~drop
    blk_g = np.asarray(t.dest) // L2
    cell_g = np.asarray(t.dest) % L2
    drop_g = np.zeros(len(t.dest), bool)
    for o in range(len(regions)):
        drop_g |= cell_of[o][cell_g] & (mask[o][blk_g] > 0)
    keep_g = ~drop_g
    return HaloTables(
        dest_s=t.dest_s[keep], src=t.src[keep],
        src_ord=t.src_ord[keep], sign=t.sign[keep],
        dest=t.dest[keep_g], idx=t.idx[keep_g],
        idx_ord=t.idx_ord[keep_g], w=t.w[keep_g],
        n_active=t.n_active, L=t.L, g=t.g, dim=t.dim,
    )


def make_fast_tables(t: HaloTables, nb: np.ndarray, mask: np.ndarray,
                     n_pad: int, corners: bool) -> FastHalo:
    """Filter covered rows, pad, and bundle with the face-copy arrays.
    Arrays stay numpy so the caller's single device_put ships them."""
    ft = pad_tables(filter_face_rows(t, mask, corners), n_pad)
    return FastHalo(t=ft, nb=nb, mask=mask, corners=corners)


def _paint_regions(x: jnp.ndarray, labs: jnp.ndarray, nb, mask,
                   g: int, bs: int, corners: bool):
    """The ONE paint body: masked structured writes of every
    same-level strip (uncovered blocks write zeros there; their rows
    remain in the tables and the scatters after the paint fill them).
    Shared by the single-device FastHalo path and the shard-local
    paint (parallel.shard_halo._assemble_sharded), so the two can
    never drift from the bit-exactness the equality tests pin."""
    regions = _fc_regions(g, bs, corners)
    for o, (sy, sx, ssy, ssx) in enumerate(regions):
        src = x[nb[o]][:, :, ssy, ssx] \
            * mask[o][:, None, None, None].astype(x.dtype)
        labs = labs.at[:, :, sy, sx].set(src)
    return labs


def _fast_paint(x: jnp.ndarray, labs: jnp.ndarray, fh: FastHalo,
                bs: int):
    return _paint_regions(x, labs, fh.nb, fh.mask, fh.t.g, bs,
                          fh.corners)


def pad_tables(t: HaloTables, n_pad: int) -> HaloTables:
    """Pad a table set so its array shapes are stable across regrids:
    the block axis to ``n_pad`` (> the real block count), row counts and
    the interpolation width K to power-of-two buckets. XLA keys compiled
    executables on argument shapes — without this every regrid would
    retrace the jitted step (the exact r1 cost block-bucketing was meant
    to remove, VERDICT weak #6). Pad rows write zeros into the first
    PAD-row lab cell (index n_real*L*L — valid precisely because
    n_pad > n_real) and gather field cell 0 with zero weight."""
    n_real = t.n_active
    assert n_pad > n_real
    dead = n_real * t.L * t.L

    def pad1(a, n, fill):
        return np.pad(np.asarray(a), (0, n - a.shape[0]),
                      constant_values=fill)

    gs = _bucket(t.dest_s.shape[0])
    gg = _bucket(t.dest.shape[0])
    k = max(4, 1 << max(0, (t.idx.shape[1] - 1)).bit_length())
    sign = np.zeros((gs, t.dim), np.asarray(t.sign).dtype)
    sign[:t.sign.shape[0]] = t.sign
    idx = np.zeros((gg, k), np.int32)
    idx[:t.idx.shape[0], :t.idx.shape[1]] = t.idx
    idx_ord = np.zeros((gg, k), np.int32)
    idx_ord[:t.idx.shape[0], :t.idx.shape[1]] = t.idx_ord
    w = np.zeros((gg, k, t.dim), np.asarray(t.w).dtype)
    w[:t.w.shape[0], :t.w.shape[1]] = t.w
    # numpy leaves on purpose: the caller device_puts the whole table
    # SET in one async transfer (per-array jnp.asarray costs one
    # synchronous round trip each — ~70 of them per regrid)
    return HaloTables(
        dest_s=pad1(t.dest_s, gs, dead),
        src=pad1(t.src, gs, 0),
        src_ord=pad1(t.src_ord, gs, 0),
        sign=sign,
        dest=pad1(t.dest, gg, dead),
        idx=idx, idx_ord=idx_ord,
        w=w,
        n_active=n_pad, L=t.L, g=t.g, dim=t.dim,
    )


def assemble_labs(field: jnp.ndarray, order, tables):
    """[cap, dim, BS, BS] field -> [n_active, dim, L, L] ghost-padded labs.

    One gather for the interiors (block reorder), one signed gather for
    the copy-type ghosts, and one batched gather-matmul for the
    (minority) interpolation ghosts.
    """
    fh = tables if isinstance(tables, FastHalo) else None
    t = fh.t if fh is not None else tables
    cap, dim, bs, _ = field.shape
    flat = field.transpose(1, 0, 2, 3).reshape(dim, cap * bs * bs)
    simple = flat[:, t.src].T * t.sign                      # [Gs, dim]
    general = jnp.einsum("dgk,gkd->gd", flat[:, t.idx], t.w)
    return _place(field[order], simple, general, t, bs, fh=fh)


def assemble_labs_ordered(x: jnp.ndarray, tables):
    """Same, for an operand already in SFC-ordered compact layout
    [n_active, dim, BS, BS] (Poisson Krylov vectors). Dispatches to the
    shard-local assembly when given per-device tables
    (parallel.shard_halo.ShardTables — explicit surface exchange
    instead of a GSPMD whole-field all-gather)."""
    if hasattr(tables, "assemble"):
        return tables.assemble(x)
    fh = tables if isinstance(tables, FastHalo) else None
    t = fh.t if fh is not None else tables
    n, dim, bs, _ = x.shape
    flat = x.transpose(1, 0, 2, 3).reshape(dim, n * bs * bs)
    simple = flat[:, t.src_ord].T * t.sign
    general = jnp.einsum("dgk,gkd->gd", flat[:, t.idx_ord], t.w)
    return _place(x, simple, general, t, bs, fh=fh)


def _place(interior, simple, general, t: HaloTables, bs: int,
           fh: "FastHalo | None" = None):
    dim = interior.shape[1]
    labs = jnp.zeros((t.n_active, dim, t.L, t.L), dtype=interior.dtype)
    labs = labs.at[:, :, t.g:t.g + bs, t.g:t.g + bs].set(interior)
    if fh is not None:
        # structured same-level strips first; the (filtered) scatters
        # below only touch cells the paint left to the tables
        labs = _fast_paint(interior, labs, fh, bs)
    labs_flat = labs.transpose(1, 0, 2, 3).reshape(dim, -1)
    labs_flat = labs_flat.at[:, t.dest_s].set(simple.T.astype(labs.dtype))
    labs_flat = labs_flat.at[:, t.dest].set(general.T.astype(labs.dtype))
    return labs_flat.reshape(dim, t.n_active, t.L, t.L).transpose(1, 0, 2, 3)


class _LabBuilder:
    """Builds ghost-cell linear expressions for one block at a time,
    following the reference's BlockLab passes in order."""

    def __init__(self, forest: Forest, g: int, tensorial: bool, dim: int):
        self.f = forest
        self.bs = forest.bs
        self.g = g
        self.dim = dim
        # reference stencil convention: start = -g, end = g + 1
        self.start = -g
        self.end = g + 1
        self.offset = _cdiv(self.start - 1, 2) - 1
        self.nc_hi = self.bs // 2 + _cdiv(self.end, 2) + 1   # excl. offset
        self.tensorial = tensorial
        # use_averages (main.cpp:2266-2267)
        self.use_averages = tensorial or self.start < -2 or self.end > 3

    # -- cell resolution against the forest ----------------------------
    def cell(self, slot: int, cy: int, cx: int) -> Expr:
        return Expr({(slot, cy, cx): np.ones(self.dim)})

    def resolve_fine(self, l: int, X: int, Y: int) -> Expr | None:
        """Value of global level-l cell (X, Y) from level-l or finer
        data (2x2 averages, recursively)."""
        f = self.f
        bs = self.bs
        s = f.slot(l, X // bs, Y // bs)
        if s >= 0:
            return self.cell(s, Y % bs, X % bs)
        if l + 1 < f.cfg.level_max:
            parts = [self.resolve_fine(l + 1, 2 * X + a, 2 * Y + b)
                     for a in (0, 1) for b in (0, 1)]
            if all(p is not None for p in parts):
                return Expr.combo(*[(p, 0.25) for p in parts])
        return None

    # -- tile (coarse version of the neighborhood) ----------------------
    def tile_expr(self, blk, ci: int, cj: int) -> Expr:
        """Coarse-tile cell (ci, cj) in block-local coarse coords.

        Interior tile cells resolve against the forest at level l-1
        (direct coarse cell, or averaged-down finer data — the
        load()/FillCoarseVersion fills). Cells beyond a DOMAIN wall get
        the zeroth-order BC: clamp to the tile-interior edge in the wall
        direction with the vector normal component negated (Neumann2D /
        applyBCface coarse variants, main.cpp:3153-3183, 3216-3246)."""
        l, bi, bj = blk
        bs2 = self.bs // 2
        nbx, nby = self.f.nblocks_at(l)
        flip = np.ones(self.dim)
        if bi == 0 and ci < 0:
            ci = 0
            if self.dim == 2:
                flip[0] = -1.0
        if bi == nbx - 1 and ci >= bs2:
            ci = bs2 - 1
            if self.dim == 2:
                flip[0] = -1.0
        if bj == 0 and cj < 0:
            cj = 0
            if self.dim == 2:
                flip[1] = -1.0
        if bj == nby - 1 and cj >= bs2:
            cj = bs2 - 1
            if self.dim == 2:
                flip[1] = -1.0
        e = self.resolve_fine(l - 1, bi * bs2 + ci, bj * bs2 + cj)
        if e is None:
            # unreachable on a 2:1-balanced forest; clamp into the own
            # footprint as a defensive fallback
            e = self.resolve_fine(
                l - 1, bi * bs2 + min(max(ci, 0), bs2 - 1),
                bj * bs2 + min(max(cj, 0), bs2 - 1))
            assert e is not None
        if (flip != 1.0).any():
            e = Expr({k: w * flip for k, w in e.items()})
        return e

    # -- main entry ------------------------------------------------------
    def block_ghosts(self, slot: int):
        f = self.f
        bs = self.bs
        g = self.g
        l = int(f.level[slot])
        bi = int(f.bi[slot])
        bj = int(f.bj[slot])
        nbx, nby = f.nblocks_at(l)
        blk = (l, bi, bj)

        out: dict[tuple[int, int], Expr] = {}

        def lab_get(ix: int, iy: int):
            """Current lab value at block-local fine coords (may be an
            interior cell or an already-built ghost); None if that lab
            cell has no value yet."""
            key = (iy + g, ix + g)
            if key in out:
                return out[key]
            if 0 <= ix < bs and 0 <= iy < bs:
                return self.cell(slot, iy, ix)
            return None

        xskin = bi == 0 or bi == nbx - 1
        yskin = bj == 0 or bj == nby - 1
        xskip = -1 if bi == 0 else 1
        yskip = -1 if bj == 0 else 1

        coarser_codes = []
        # pass 1: same-level and finer neighbors, resolved per ghost cell
        # (icode order of the reference: y outer, x inner)
        for cy in (-1, 0, 1):
            for cx in (-1, 0, 1):
                if cx == 0 and cy == 0:
                    continue
                if cx == xskip and xskin:
                    continue
                if cy == yskip and yskin:
                    continue
                if (not self.tensorial and not self.use_averages
                        and abs(cx) + abs(cy) > 1):
                    continue
                rel = f.owner_relation(l, bi + cx, bj + cy)
                if rel == -2:
                    coarser_codes.append((cx, cy))
                    continue
                s0 = self.start if cx < 0 else (0 if cx == 0 else bs)
                e0 = 0 if cx < 0 else (bs if cx == 0 else bs + self.end - 1)
                s1 = self.start if cy < 0 else (0 if cy == 0 else bs)
                e1 = 0 if cy < 0 else (bs if cy == 0 else bs + self.end - 1)
                for iy in range(s1, e1):
                    for ix in range(s0, e0):
                        X = bi * bs + ix
                        Y = bj * bs + iy
                        e = self.resolve_fine(l, X, Y)
                        if e is not None:
                            out[(iy + g, ix + g)] = e

        # pass 2: coarser neighbors (tile + interpolation)
        for (cx, cy) in coarser_codes:
            self._coarse_ghosts(blk, (cx, cy), out, lab_get)

        # pass 3: wall BCs overwrite skin ghosts (applied last, like
        # post_load's final _apply_bc)
        self._apply_bc(blk, out)
        return out

    # -- coarse-neighbor interpolation ----------------------------------
    def _coarse_ghosts(self, blk, code, out, lab_get):
        bs = self.bs
        g = self.g
        cx, cy = code
        s0 = self.start if cx < 0 else (0 if cx == 0 else bs)
        e0 = 0 if cx < 0 else (bs if cx == 0 else bs + self.end - 1)
        s1 = self.start if cy < 0 else (0 if cy == 0 else bs)
        e1 = 0 if cy < 0 else (bs if cy == 0 else bs + self.end - 1)
        sC0 = _cdiv(self.start - 1, 2) if cx < 0 else (
            0 if cx == 0 else bs // 2)
        sC1 = _cdiv(self.start - 1, 2) if cy < 0 else (
            0 if cy == 0 else bs // 2)

        def coarse_xx(ix):
            return (ix - s0 - min(0, cx) * ((e0 - s0) % 2)) // 2 + sC0

        def coarse_yy(iy):
            return (iy - s1 - min(0, cy) * ((e1 - s1) % 2)) // 2 + sC1

        def parity_x(ix):
            return abs(ix - s0 - min(0, cx) * ((e0 - s0) % 2)) % 2

        def parity_y(iy):
            return abs(iy - s1 - min(0, cy) * ((e1 - s1) % 2)) % 2

        # (a) TestInterp everywhere in the region (use_averages path,
        # main.cpp:2741-2766)
        if self.use_averages:
            for iy in range(s1, e1):
                YY = coarse_yy(iy)
                for ix in range(s0, e0):
                    XX = coarse_xx(ix)
                    tile = {}
                    for a in (-1, 0, 1):
                        for b in (-1, 0, 1):
                            tile[(a, b)] = self.tile_expr(
                                blk, XX + a, YY + b)
                    out[(iy + g, ix + g)] = _test_interp(
                        tile, parity_x(ix), parity_y(iy))

        if abs(cx) + abs(cy) != 1:
            return

        # (b) 1-D directional Taylor on the face (main.cpp:2767-2861)
        bs2 = bs // 2
        for iy in range(s1, e1, 2):
            YY = coarse_yy(iy)
            y = parity_y(iy)
            iyp = -1 if abs(iy) % 2 == 1 else 1
            dy = 0.25 * (2 * y - 1)
            for ix in range(s0, e0, 2):
                XX = coarse_xx(ix)
                x = parity_x(ix)
                ixp = -1 if abs(ix) % 2 == 1 else 1
                dx = 0.25 * (2 * x - 1)
                if ix < -2 or iy < -2 or ix > bs + 1 or iy > bs + 1:
                    continue
                c1 = self.tile_expr(blk, XX, YY)
                if cx != 0:
                    # vary along y
                    if YY == 0:
                        cp2 = self.tile_expr(blk, XX, YY + 2)
                        cp1 = self.tile_expr(blk, XX, YY + 1)
                        dudy = Expr.combo((cp2, -0.5), (c1, -1.5), (cp1, 2.0))
                        dudy2 = Expr.combo((cp2, 1.0), (c1, 1.0), (cp1, -2.0))
                    elif YY == bs2 - 1:
                        cm2 = self.tile_expr(blk, XX, YY - 2)
                        cm1 = self.tile_expr(blk, XX, YY - 1)
                        dudy = Expr.combo((cm2, 0.5), (c1, 1.5), (cm1, -2.0))
                        dudy2 = Expr.combo((cm2, 1.0), (c1, 1.0), (cm1, -2.0))
                    else:
                        cp1 = self.tile_expr(blk, XX, YY + 1)
                        cm1 = self.tile_expr(blk, XX, YY - 1)
                        dudy = Expr.combo((cp1, 0.5), (cm1, -0.5))
                        dudy2 = Expr.combo((cp1, 1.0), (cm1, 1.0), (c1, -2.0))
                    d1, d2 = dudy, dudy2

                    def val(sgn):
                        return Expr.combo((c1, 1.0), (d1, sgn * dy),
                                          (d2, 0.5 * dy * dy))
                    quads = [(ix, iy, val(+1)), (ix, iy + iyp, val(-1)),
                             (ix + ixp, iy, val(+1)),
                             (ix + ixp, iy + iyp, val(-1))]
                else:
                    if XX == 0:
                        cp2 = self.tile_expr(blk, XX + 2, YY)
                        cp1 = self.tile_expr(blk, XX + 1, YY)
                        dudx = Expr.combo((cp2, -0.5), (c1, -1.5), (cp1, 2.0))
                        dudx2 = Expr.combo((cp2, 1.0), (c1, 1.0), (cp1, -2.0))
                    elif XX == bs2 - 1:
                        cm2 = self.tile_expr(blk, XX - 2, YY)
                        cm1 = self.tile_expr(blk, XX - 1, YY)
                        dudx = Expr.combo((cm2, 0.5), (c1, 1.5), (cm1, -2.0))
                        dudx2 = Expr.combo((cm2, 1.0), (c1, 1.0), (cm1, -2.0))
                    else:
                        cp1 = self.tile_expr(blk, XX + 1, YY)
                        cm1 = self.tile_expr(blk, XX - 1, YY)
                        dudx = Expr.combo((cp1, 0.5), (cm1, -0.5))
                        dudx2 = Expr.combo((cp1, 1.0), (cm1, 1.0), (c1, -2.0))
                    d1, d2 = dudx, dudx2

                    def val(sgn):
                        return Expr.combo((c1, 1.0), (d1, sgn * dx),
                                          (d2, 0.5 * dx * dx))
                    quads = [(ix, iy, val(+1)), (ix, iy + iyp, val(+1)),
                             (ix + ixp, iy, val(-1)),
                             (ix + ixp, iy + iyp, val(-1))]
                for (jx, jy, e) in quads:
                    if jx == ix and jy == iy:
                        out[(jy + self.g, jx + self.g)] = e
                    elif s0 <= jx < e0 and s1 <= jy < e1:
                        out[(jy + self.g, jx + self.g)] = e

        # (c) LI/LE corrections toward interior fine cells, sequential in
        # loop order (main.cpp:2862-2931)
        def li(a, b, c):
            # kappa = (4a + 6c - 10b)/15; lambda = b - c - kappa
            # out = 4 kappa + 2 lambda + c
            k = Expr.combo((a, 4 / 15), (c, 6 / 15), (b, -10 / 15))
            lam = Expr.combo((b, 1.0), (c, -1.0), (k, -1.0))
            return Expr.combo((k, 4.0), (lam, 2.0), (c, 1.0))

        def le(a, b, c):
            k = Expr.combo((a, 4 / 15), (c, 6 / 15), (b, -10 / 15))
            lam = Expr.combo((b, 1.0), (c, -1.0), (k, -1.0))
            return Expr.combo((k, 9.0), (lam, 3.0), (c, 1.0))

        for iy in range(s1, e1):
            for ix in range(s0, e0):
                if ix < -2 or iy < -2 or ix > bs + 1 or iy > bs + 1:
                    continue
                x = parity_x(ix)
                y = parity_y(iy)
                a = out.get((iy + g, ix + g))
                if a is None:
                    continue
                if cx == 0 and cy == 1:
                    args = (li, (ix, iy - 1), (ix, iy - 2)) if y == 0 \
                        else (le, (ix, iy - 2), (ix, iy - 3))
                elif cx == 0 and cy == -1:
                    args = (li, (ix, iy + 1), (ix, iy + 2)) if y == 1 \
                        else (le, (ix, iy + 2), (ix, iy + 3))
                elif cy == 0 and cx == 1:
                    args = (li, (ix - 1, iy), (ix - 2, iy)) if x == 0 \
                        else (le, (ix - 2, iy), (ix - 3, iy))
                else:
                    args = (li, (ix + 1, iy), (ix + 2, iy)) if x == 1 \
                        else (le, (ix + 2, iy), (ix + 3, iy))
                fn, pb, pc = args
                b_e = lab_get(*pb)
                c_e = lab_get(*pc)
                if b_e is None or c_e is None:
                    continue
                out[(iy + g, ix + g)] = fn(a, b_e, c_e)

    # -- wall BCs --------------------------------------------------------
    def _apply_bc(self, blk, out):
        """Reference _apply_bc (main.cpp:3126-3256): ghost = value at the
        wall-adjacent cell with the SAME tangential coordinate (which may
        itself be a ghost filled earlier); vector normal component flips
        sign. Faces applied in x0, x1, y0, y1 order, later passes
        overwriting corners — exactly the reference's sequence."""
        l, bi, bj = blk
        bs = self.bs
        g = self.g
        nbx, nby = self.f.nblocks_at(l)
        lo0, hi0 = self.start, bs + self.end - 1
        slot = self.f.blocks[(l, bi, bj)]
        sides = []
        if bi == 0:
            sides.append(("x", 0))
        if bi == nbx - 1:
            sides.append(("x", 1))
        if bj == 0:
            sides.append(("y", 0))
        if bj == nby - 1:
            sides.append(("y", 1))
        for (dir_, side) in sides:
            if dir_ == "x":
                xs = range(lo0, 0) if side == 0 else range(bs, hi0)
                ys = range(lo0, hi0)
                edge = 0 if side == 0 else bs - 1
            else:
                xs = range(lo0, hi0)
                ys = range(lo0, 0) if side == 0 else range(bs, hi0)
                edge = 0 if side == 0 else bs - 1
            flip = np.ones(self.dim)
            if self.dim == 2:
                flip[0 if dir_ == "x" else 1] = -1.0
            for iy in ys:
                for ix in xs:
                    sx, sy = (edge, iy) if dir_ == "x" else (ix, edge)
                    if 0 <= sx < bs and 0 <= sy < bs:
                        base = Expr({(slot, sy, sx): np.ones(self.dim)})
                    else:
                        base = out.get((sy + g, sx + g))
                        if base is None:
                            continue
                    out[(iy + g, ix + g)] = Expr(
                        {k: w * flip for k, w in base.items()})


def _test_interp(tile, x: int, y: int) -> Expr:
    """2nd-order Taylor prolongation of a 3x3 coarse neighborhood to the
    fine cell with parity (x, y) (TestInterp, main.cpp:2220-2230)."""
    dx = 0.25 * (2 * x - 1)
    dy = 0.25 * (2 * y - 1)
    c = tile
    dudx = Expr.combo((c[(1, 0)], 0.5), (c[(-1, 0)], -0.5))
    dudy = Expr.combo((c[(0, 1)], 0.5), (c[(0, -1)], -0.5))
    dudxdy = Expr.combo((c[(-1, -1)], 0.25), (c[(1, 1)], 0.25),
                        (c[(1, -1)], -0.25), (c[(-1, 1)], -0.25))
    dudx2 = Expr.combo((c[(-1, 0)], 1.0), (c[(1, 0)], 1.0), (c[(0, 0)], -2.0))
    dudy2 = Expr.combo((c[(0, -1)], 1.0), (c[(0, 1)], 1.0), (c[(0, 0)], -2.0))
    return Expr.combo(
        (c[(0, 0)], 1.0), (dudx, dx), (dudy, dy),
        (dudx2, 0.5 * dx * dx), (dudy2, 0.5 * dy * dy), (dudxdy, dx * dy),
    )
