"""Ghost-cell assembly as gather tables: the TPU form of BlockLab.

The reference assembles per-block ghost-padded scratch tiles with 770
lines of branchy pointer code + an MPI message schedule
(`/root/reference/main.cpp:2231-3000` BlockLab, `909-2142` Setup/sync1).
The key observation (SURVEY.md §7): every ghost value is a fixed LINEAR
combination of stored cell values — same-level copies (weight 1),
fine-to-coarse 2x2 averages (weight 1/4), coarse-to-fine interpolation
(TestInterp 2nd-order Taylor, the 1-D directional variant on faces, and
the LI/LE blends toward interior fine cells, main.cpp:2203-2230 +
2689-2999), and the free-slip / Neumann wall ghosts.

So each (grid topology, stencil width, field kind) pair compiles ONCE —
on the host, at regrid time — into three arrays:

    dest [G]      flat index into the lab array [n_active * L * L]
    idx  [G, K]   flat indices into field storage [capacity * BS * BS]
    w    [G, K, dim] weights (vector fields carry per-component signs
                  from the free-slip mirror)

and the per-step device work is one batched gather + weighted sum —
no message passing, no branches, MXU/VPU-friendly. The table builder
below IS the specification of the reference's interpolation, written
against global cell coordinates instead of lab-pointer arithmetic; the
weights are validated cell-for-cell against polynomial reproduction
tests (exact for degree <= 2 where the reference is 2nd order).
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .forest import Forest


def _cdiv(a: int, b: int) -> int:
    """C-style truncating integer division (the reference's `/`)."""
    return int(math.trunc(a / b))


class Expr(dict):
    """Linear expression: {(slot, cy, cx): weight_vec ndarray[dim]}."""

    def scaled(self, f):
        return Expr({k: w * f for k, w in self.items()})

    def add(self, other, f=1.0):
        for k, w in other.items():
            cur = self.get(k)
            self[k] = w * f if cur is None else cur + w * f

    @staticmethod
    def combo(*pairs):
        e = Expr()
        for other, f in pairs:
            e.add(other, f)
        return e


class HaloTables(NamedTuple):
    """Registered as a jax pytree with the int metadata as static aux
    data, so jitted functions taking tables as arguments re-use compiled
    executables whenever a regrid reproduces previously-seen shapes."""

    dest: jnp.ndarray     # [G] int32 into labs flat [n_active*L*L]
    idx: jnp.ndarray      # [G, K] int32 into fields flat [cap*BS*BS]
    idx_ord: jnp.ndarray  # [G, K] int32 into SFC-ordered [n_active*BS*BS]
    w: jnp.ndarray        # [G, K, dim]
    n_active: int
    L: int
    g: int
    dim: int


jax.tree_util.register_pytree_node(
    HaloTables,
    lambda t: ((t.dest, t.idx, t.idx_ord, t.w),
               (t.n_active, t.L, t.g, t.dim)),
    lambda aux, ch: HaloTables(*ch, *aux),
)


def build_tables(forest: Forest, order: np.ndarray, g: int,
                 tensorial: bool, dim: int) -> HaloTables:
    """Build gather tables for all ghost cells of all active blocks."""
    bs = forest.bs
    L = bs + 2 * g
    builder = _LabBuilder(forest, g, tensorial, dim)
    dest, idx_rows, w_rows = [], [], []
    kmax = 1
    for ordpos, s in enumerate(order):
        exprs = builder.block_ghosts(int(s))
        for (ly, lx), e in exprs.items():
            dest.append(ordpos * L * L + ly * L + lx)
            ks = list(e.items())
            kmax = max(kmax, len(ks))
            idx_rows.append([slot * bs * bs + cy * bs + cx
                             for (slot, cy, cx), _ in ks])
            w_rows.append([w for _, w in ks])
    G = len(dest)
    idx = np.zeros((G, kmax), np.int32)
    w = np.zeros((G, kmax, dim), np.float64)
    for r in range(G):
        n = len(idx_rows[r])
        idx[r, :n] = idx_rows[r]
        for k in range(n):
            w[r, k] = w_rows[r][k]
    # idx remapped to the SFC-ordered compact layout (for operands that
    # live as [n_active, BS, BS], e.g. the Poisson Krylov vectors)
    ordpos = np.zeros(forest.capacity, np.int64)
    ordpos[order] = np.arange(len(order))
    slot_of = idx // (bs * bs)
    idx_ord = (ordpos[slot_of] * bs * bs + idx % (bs * bs)).astype(np.int32)
    return HaloTables(
        dest=jnp.asarray(np.asarray(dest, np.int32)),
        idx=jnp.asarray(idx),
        idx_ord=jnp.asarray(idx_ord),
        w=jnp.asarray(w, dtype=forest.dtype),
        n_active=len(order), L=L, g=g, dim=dim,
    )


def assemble_labs(field: jnp.ndarray, order, tables: HaloTables):
    """[cap, dim, BS, BS] field -> [n_active, dim, L, L] ghost-padded labs.

    One gather for the interiors (block reorder) + one batched
    gather-matmul for every ghost cell of every block.
    """
    cap, dim, bs, _ = field.shape
    t = tables
    flat = field.transpose(1, 0, 2, 3).reshape(dim, cap * bs * bs)
    ghosts = jnp.einsum("dgk,gkd->gd", flat[:, t.idx], t.w)  # [G, dim]
    return _place(field[order], ghosts, t, bs)


def assemble_labs_ordered(x: jnp.ndarray, tables: HaloTables):
    """Same, for an operand already in SFC-ordered compact layout
    [n_active, dim, BS, BS] (Poisson Krylov vectors)."""
    n, dim, bs, _ = x.shape
    t = tables
    flat = x.transpose(1, 0, 2, 3).reshape(dim, n * bs * bs)
    ghosts = jnp.einsum("dgk,gkd->gd", flat[:, t.idx_ord], t.w)
    return _place(x, ghosts, t, bs)


def _place(interior, ghosts, t: HaloTables, bs: int):
    dim = interior.shape[1]
    labs = jnp.zeros((t.n_active, dim, t.L, t.L), dtype=interior.dtype)
    labs = labs.at[:, :, t.g:t.g + bs, t.g:t.g + bs].set(interior)
    labs_flat = labs.transpose(1, 0, 2, 3).reshape(dim, -1)
    labs_flat = labs_flat.at[:, t.dest].set(ghosts.T)
    return labs_flat.reshape(dim, t.n_active, t.L, t.L).transpose(1, 0, 2, 3)


class _LabBuilder:
    """Builds ghost-cell linear expressions for one block at a time,
    following the reference's BlockLab passes in order."""

    def __init__(self, forest: Forest, g: int, tensorial: bool, dim: int):
        self.f = forest
        self.bs = forest.bs
        self.g = g
        self.dim = dim
        # reference stencil convention: start = -g, end = g + 1
        self.start = -g
        self.end = g + 1
        self.offset = _cdiv(self.start - 1, 2) - 1
        self.nc_hi = self.bs // 2 + _cdiv(self.end, 2) + 1   # excl. offset
        self.tensorial = tensorial
        # use_averages (main.cpp:2266-2267)
        self.use_averages = tensorial or self.start < -2 or self.end > 3

    # -- cell resolution against the forest ----------------------------
    def cell(self, slot: int, cy: int, cx: int) -> Expr:
        return Expr({(slot, cy, cx): np.ones(self.dim)})

    def resolve_fine(self, l: int, X: int, Y: int) -> Expr | None:
        """Value of global level-l cell (X, Y) from level-l or finer
        data (2x2 averages, recursively)."""
        f = self.f
        bs = self.bs
        s = f.slot(l, X // bs, Y // bs)
        if s >= 0:
            return self.cell(s, Y % bs, X % bs)
        if l + 1 < f.cfg.level_max:
            parts = [self.resolve_fine(l + 1, 2 * X + a, 2 * Y + b)
                     for a in (0, 1) for b in (0, 1)]
            if all(p is not None for p in parts):
                return Expr.combo(*[(p, 0.25) for p in parts])
        return None

    # -- tile (coarse version of the neighborhood) ----------------------
    def tile_expr(self, blk, ci: int, cj: int) -> Expr:
        """Coarse-tile cell (ci, cj) in block-local coarse coords.

        Interior tile cells resolve against the forest at level l-1
        (direct coarse cell, or averaged-down finer data — the
        load()/FillCoarseVersion fills). Cells beyond a DOMAIN wall get
        the zeroth-order BC: clamp to the tile-interior edge in the wall
        direction with the vector normal component negated (Neumann2D /
        applyBCface coarse variants, main.cpp:3153-3183, 3216-3246)."""
        l, bi, bj = blk
        bs2 = self.bs // 2
        nbx, nby = self.f.nblocks_at(l)
        flip = np.ones(self.dim)
        if bi == 0 and ci < 0:
            ci = 0
            if self.dim == 2:
                flip[0] = -1.0
        if bi == nbx - 1 and ci >= bs2:
            ci = bs2 - 1
            if self.dim == 2:
                flip[0] = -1.0
        if bj == 0 and cj < 0:
            cj = 0
            if self.dim == 2:
                flip[1] = -1.0
        if bj == nby - 1 and cj >= bs2:
            cj = bs2 - 1
            if self.dim == 2:
                flip[1] = -1.0
        e = self.resolve_fine(l - 1, bi * bs2 + ci, bj * bs2 + cj)
        if e is None:
            # unreachable on a 2:1-balanced forest; clamp into the own
            # footprint as a defensive fallback
            e = self.resolve_fine(
                l - 1, bi * bs2 + min(max(ci, 0), bs2 - 1),
                bj * bs2 + min(max(cj, 0), bs2 - 1))
            assert e is not None
        if (flip != 1.0).any():
            e = Expr({k: w * flip for k, w in e.items()})
        return e

    # -- main entry ------------------------------------------------------
    def block_ghosts(self, slot: int):
        f = self.f
        bs = self.bs
        g = self.g
        l = int(f.level[slot])
        bi = int(f.bi[slot])
        bj = int(f.bj[slot])
        nbx, nby = f.nblocks_at(l)
        blk = (l, bi, bj)

        out: dict[tuple[int, int], Expr] = {}

        def lab_get(ix: int, iy: int):
            """Current lab value at block-local fine coords (may be an
            interior cell or an already-built ghost); None if that lab
            cell has no value yet."""
            key = (iy + g, ix + g)
            if key in out:
                return out[key]
            if 0 <= ix < bs and 0 <= iy < bs:
                return self.cell(slot, iy, ix)
            return None

        xskin = bi == 0 or bi == nbx - 1
        yskin = bj == 0 or bj == nby - 1
        xskip = -1 if bi == 0 else 1
        yskip = -1 if bj == 0 else 1

        coarser_codes = []
        # pass 1: same-level and finer neighbors, resolved per ghost cell
        # (icode order of the reference: y outer, x inner)
        for cy in (-1, 0, 1):
            for cx in (-1, 0, 1):
                if cx == 0 and cy == 0:
                    continue
                if cx == xskip and xskin:
                    continue
                if cy == yskip and yskin:
                    continue
                if (not self.tensorial and not self.use_averages
                        and abs(cx) + abs(cy) > 1):
                    continue
                rel = f.owner_relation(l, bi + cx, bj + cy)
                if rel == -2:
                    coarser_codes.append((cx, cy))
                    continue
                s0 = self.start if cx < 0 else (0 if cx == 0 else bs)
                e0 = 0 if cx < 0 else (bs if cx == 0 else bs + self.end - 1)
                s1 = self.start if cy < 0 else (0 if cy == 0 else bs)
                e1 = 0 if cy < 0 else (bs if cy == 0 else bs + self.end - 1)
                for iy in range(s1, e1):
                    for ix in range(s0, e0):
                        X = bi * bs + ix
                        Y = bj * bs + iy
                        e = self.resolve_fine(l, X, Y)
                        if e is not None:
                            out[(iy + g, ix + g)] = e

        # pass 2: coarser neighbors (tile + interpolation)
        for (cx, cy) in coarser_codes:
            self._coarse_ghosts(blk, (cx, cy), out, lab_get)

        # pass 3: wall BCs overwrite skin ghosts (applied last, like
        # post_load's final _apply_bc)
        self._apply_bc(blk, out)
        return out

    # -- coarse-neighbor interpolation ----------------------------------
    def _coarse_ghosts(self, blk, code, out, lab_get):
        bs = self.bs
        g = self.g
        cx, cy = code
        s0 = self.start if cx < 0 else (0 if cx == 0 else bs)
        e0 = 0 if cx < 0 else (bs if cx == 0 else bs + self.end - 1)
        s1 = self.start if cy < 0 else (0 if cy == 0 else bs)
        e1 = 0 if cy < 0 else (bs if cy == 0 else bs + self.end - 1)
        sC0 = _cdiv(self.start - 1, 2) if cx < 0 else (
            0 if cx == 0 else bs // 2)
        sC1 = _cdiv(self.start - 1, 2) if cy < 0 else (
            0 if cy == 0 else bs // 2)

        def coarse_xx(ix):
            return (ix - s0 - min(0, cx) * ((e0 - s0) % 2)) // 2 + sC0

        def coarse_yy(iy):
            return (iy - s1 - min(0, cy) * ((e1 - s1) % 2)) // 2 + sC1

        def parity_x(ix):
            return abs(ix - s0 - min(0, cx) * ((e0 - s0) % 2)) % 2

        def parity_y(iy):
            return abs(iy - s1 - min(0, cy) * ((e1 - s1) % 2)) % 2

        # (a) TestInterp everywhere in the region (use_averages path,
        # main.cpp:2741-2766)
        if self.use_averages:
            for iy in range(s1, e1):
                YY = coarse_yy(iy)
                for ix in range(s0, e0):
                    XX = coarse_xx(ix)
                    tile = {}
                    for a in (-1, 0, 1):
                        for b in (-1, 0, 1):
                            tile[(a, b)] = self.tile_expr(
                                blk, XX + a, YY + b)
                    out[(iy + g, ix + g)] = _test_interp(
                        tile, parity_x(ix), parity_y(iy))

        if abs(cx) + abs(cy) != 1:
            return

        # (b) 1-D directional Taylor on the face (main.cpp:2767-2861)
        bs2 = bs // 2
        for iy in range(s1, e1, 2):
            YY = coarse_yy(iy)
            y = parity_y(iy)
            iyp = -1 if abs(iy) % 2 == 1 else 1
            dy = 0.25 * (2 * y - 1)
            for ix in range(s0, e0, 2):
                XX = coarse_xx(ix)
                x = parity_x(ix)
                ixp = -1 if abs(ix) % 2 == 1 else 1
                dx = 0.25 * (2 * x - 1)
                if ix < -2 or iy < -2 or ix > bs + 1 or iy > bs + 1:
                    continue
                c1 = self.tile_expr(blk, XX, YY)
                if cx != 0:
                    # vary along y
                    if YY == 0:
                        cp2 = self.tile_expr(blk, XX, YY + 2)
                        cp1 = self.tile_expr(blk, XX, YY + 1)
                        dudy = Expr.combo((cp2, -0.5), (c1, -1.5), (cp1, 2.0))
                        dudy2 = Expr.combo((cp2, 1.0), (c1, 1.0), (cp1, -2.0))
                    elif YY == bs2 - 1:
                        cm2 = self.tile_expr(blk, XX, YY - 2)
                        cm1 = self.tile_expr(blk, XX, YY - 1)
                        dudy = Expr.combo((cm2, 0.5), (c1, 1.5), (cm1, -2.0))
                        dudy2 = Expr.combo((cm2, 1.0), (c1, 1.0), (cm1, -2.0))
                    else:
                        cp1 = self.tile_expr(blk, XX, YY + 1)
                        cm1 = self.tile_expr(blk, XX, YY - 1)
                        dudy = Expr.combo((cp1, 0.5), (cm1, -0.5))
                        dudy2 = Expr.combo((cp1, 1.0), (cm1, 1.0), (c1, -2.0))
                    d1, d2 = dudy, dudy2

                    def val(sgn):
                        return Expr.combo((c1, 1.0), (d1, sgn * dy),
                                          (d2, 0.5 * dy * dy))
                    quads = [(ix, iy, val(+1)), (ix, iy + iyp, val(-1)),
                             (ix + ixp, iy, val(+1)),
                             (ix + ixp, iy + iyp, val(-1))]
                else:
                    if XX == 0:
                        cp2 = self.tile_expr(blk, XX + 2, YY)
                        cp1 = self.tile_expr(blk, XX + 1, YY)
                        dudx = Expr.combo((cp2, -0.5), (c1, -1.5), (cp1, 2.0))
                        dudx2 = Expr.combo((cp2, 1.0), (c1, 1.0), (cp1, -2.0))
                    elif XX == bs2 - 1:
                        cm2 = self.tile_expr(blk, XX - 2, YY)
                        cm1 = self.tile_expr(blk, XX - 1, YY)
                        dudx = Expr.combo((cm2, 0.5), (c1, 1.5), (cm1, -2.0))
                        dudx2 = Expr.combo((cm2, 1.0), (c1, 1.0), (cm1, -2.0))
                    else:
                        cp1 = self.tile_expr(blk, XX + 1, YY)
                        cm1 = self.tile_expr(blk, XX - 1, YY)
                        dudx = Expr.combo((cp1, 0.5), (cm1, -0.5))
                        dudx2 = Expr.combo((cp1, 1.0), (cm1, 1.0), (c1, -2.0))
                    d1, d2 = dudx, dudx2

                    def val(sgn):
                        return Expr.combo((c1, 1.0), (d1, sgn * dx),
                                          (d2, 0.5 * dx * dx))
                    quads = [(ix, iy, val(+1)), (ix, iy + iyp, val(+1)),
                             (ix + ixp, iy, val(-1)),
                             (ix + ixp, iy + iyp, val(-1))]
                for (jx, jy, e) in quads:
                    if jx == ix and jy == iy:
                        out[(jy + self.g, jx + self.g)] = e
                    elif s0 <= jx < e0 and s1 <= jy < e1:
                        out[(jy + self.g, jx + self.g)] = e

        # (c) LI/LE corrections toward interior fine cells, sequential in
        # loop order (main.cpp:2862-2931)
        def li(a, b, c):
            # kappa = (4a + 6c - 10b)/15; lambda = b - c - kappa
            # out = 4 kappa + 2 lambda + c
            k = Expr.combo((a, 4 / 15), (c, 6 / 15), (b, -10 / 15))
            lam = Expr.combo((b, 1.0), (c, -1.0), (k, -1.0))
            return Expr.combo((k, 4.0), (lam, 2.0), (c, 1.0))

        def le(a, b, c):
            k = Expr.combo((a, 4 / 15), (c, 6 / 15), (b, -10 / 15))
            lam = Expr.combo((b, 1.0), (c, -1.0), (k, -1.0))
            return Expr.combo((k, 9.0), (lam, 3.0), (c, 1.0))

        for iy in range(s1, e1):
            for ix in range(s0, e0):
                if ix < -2 or iy < -2 or ix > bs + 1 or iy > bs + 1:
                    continue
                x = parity_x(ix)
                y = parity_y(iy)
                a = out.get((iy + g, ix + g))
                if a is None:
                    continue
                if cx == 0 and cy == 1:
                    args = (li, (ix, iy - 1), (ix, iy - 2)) if y == 0 \
                        else (le, (ix, iy - 2), (ix, iy - 3))
                elif cx == 0 and cy == -1:
                    args = (li, (ix, iy + 1), (ix, iy + 2)) if y == 1 \
                        else (le, (ix, iy + 2), (ix, iy + 3))
                elif cy == 0 and cx == 1:
                    args = (li, (ix - 1, iy), (ix - 2, iy)) if x == 0 \
                        else (le, (ix - 2, iy), (ix - 3, iy))
                else:
                    args = (li, (ix + 1, iy), (ix + 2, iy)) if x == 1 \
                        else (le, (ix + 2, iy), (ix + 3, iy))
                fn, pb, pc = args
                b_e = lab_get(*pb)
                c_e = lab_get(*pc)
                if b_e is None or c_e is None:
                    continue
                out[(iy + g, ix + g)] = fn(a, b_e, c_e)

    # -- wall BCs --------------------------------------------------------
    def _apply_bc(self, blk, out):
        """Reference _apply_bc (main.cpp:3126-3256): ghost = value at the
        wall-adjacent cell with the SAME tangential coordinate (which may
        itself be a ghost filled earlier); vector normal component flips
        sign. Faces applied in x0, x1, y0, y1 order, later passes
        overwriting corners — exactly the reference's sequence."""
        l, bi, bj = blk
        bs = self.bs
        g = self.g
        nbx, nby = self.f.nblocks_at(l)
        lo0, hi0 = self.start, bs + self.end - 1
        slot = self.f.blocks[(l, bi, bj)]
        sides = []
        if bi == 0:
            sides.append(("x", 0))
        if bi == nbx - 1:
            sides.append(("x", 1))
        if bj == 0:
            sides.append(("y", 0))
        if bj == nby - 1:
            sides.append(("y", 1))
        for (dir_, side) in sides:
            if dir_ == "x":
                xs = range(lo0, 0) if side == 0 else range(bs, hi0)
                ys = range(lo0, hi0)
                edge = 0 if side == 0 else bs - 1
            else:
                xs = range(lo0, hi0)
                ys = range(lo0, 0) if side == 0 else range(bs, hi0)
                edge = 0 if side == 0 else bs - 1
            flip = np.ones(self.dim)
            if self.dim == 2:
                flip[0 if dir_ == "x" else 1] = -1.0
            for iy in ys:
                for ix in xs:
                    sx, sy = (edge, iy) if dir_ == "x" else (ix, edge)
                    if 0 <= sx < bs and 0 <= sy < bs:
                        base = Expr({(slot, sy, sx): np.ones(self.dim)})
                    else:
                        base = out.get((sy + g, sx + g))
                        if base is None:
                            continue
                    out[(iy + g, ix + g)] = Expr(
                        {k: w * flip for k, w in base.items()})


def _test_interp(tile, x: int, y: int) -> Expr:
    """2nd-order Taylor prolongation of a 3x3 coarse neighborhood to the
    fine cell with parity (x, y) (TestInterp, main.cpp:2220-2230)."""
    dx = 0.25 * (2 * x - 1)
    dy = 0.25 * (2 * y - 1)
    c = tile
    dudx = Expr.combo((c[(1, 0)], 0.5), (c[(-1, 0)], -0.5))
    dudy = Expr.combo((c[(0, 1)], 0.5), (c[(0, -1)], -0.5))
    dudxdy = Expr.combo((c[(-1, -1)], 0.25), (c[(1, 1)], 0.25),
                        (c[(1, -1)], -0.25), (c[(-1, 1)], -0.25))
    dudx2 = Expr.combo((c[(-1, 0)], 1.0), (c[(1, 0)], 1.0), (c[(0, 0)], -2.0))
    dudy2 = Expr.combo((c[(0, -1)], 1.0), (c[(0, 1)], 1.0), (c[(0, 0)], -2.0))
    return Expr.combo(
        (c[(0, 0)], 1.0), (dudx, dx), (dudy, dy),
        (dudx2, 0.5 * dx * dx), (dudy2, 0.5 * dy * dy), (dudxdy, dx * dy),
    )
