"""Pallas TPU kernel for the WENO5 advection-diffusion RHS.

Architecture: each grid step owns a row strip; x-chunks are
double-buffered HBM->VMEM (copy latency hides behind the previous
chunk's arithmetic), the whole 60-op WENO chain runs in VMEM, and the
RHS is written once. DMA slices must be tile-aligned, hence the y halo
padded 3 -> 4 (sublane 8) and the x halo 3 -> 64 (lane 128); the
alignment-only ghosts are never read. The kernel is bit-identical to
the XLA path (same jnp ops traced by Mosaic; tests compare exactly).

MEASURED VERDICT (v5e, f32, 8192^2): 38 ms vs XLA-fused 30 ms per
evaluation — both ~20x above the HBM roofline (~1.3 ms), i.e. the op is
bound by VPU divides (6 per WENO reconstruction) and lane-shift
permutes, not by the fusion/HBM traffic a Pallas rewrite eliminates.
Kept as OPT-IN (CUP2D_PALLAS=1 env, or UniformGrid(use_pallas=True)):
correct, tested, and the scaffolding for kernels where manual tiling
does win (bf16 variants, fused multi-stage updates), but NOT the
default — shipping a slower default to claim "has Pallas" would be
exactly the aspirational-README failure mode VERDICT r1 flagged.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .stencil import shift, weno_derivative

try:  # Pallas TPU backend; absent/broken on some hosts -> XLA fallback
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False

_G = 3    # WENO5 halo
_GX = 64  # x halo rounded up to lane alignment (128-multiple DMA widths)


def _core_seq(lab, afac, dfac):
    """advect_diffuse_core evaluated one velocity component at a time:
    same arithmetic (the correctness tests compare against the shared
    core bit-for-bit), but the live temporaries are [BY, BX] instead of
    [2, BY, BX], which lets Mosaic fit twice the tile in VMEM stack —
    fewer, larger chunks amortize the per-chunk DMA/loop overhead."""
    g = _G
    wind_u = shift(lab, g, 0, 0)[0]
    wind_v = shift(lab, g, 0, 0)[1]
    outs = []
    for c in (0, 1):
        q = lab[c]
        dx = weno_derivative(
            wind_u,
            shift(q, g, 0, -3), shift(q, g, 0, -2), shift(q, g, 0, -1),
            shift(q, g, 0, 0),
            shift(q, g, 0, 1), shift(q, g, 0, 2), shift(q, g, 0, 3))
        dy = weno_derivative(
            wind_v,
            shift(q, g, -3, 0), shift(q, g, -2, 0), shift(q, g, -1, 0),
            shift(q, g, 0, 0),
            shift(q, g, 1, 0), shift(q, g, 2, 0), shift(q, g, 3, 0))
        lap = (shift(q, g, 0, 1) + shift(q, g, 0, -1)
               + shift(q, g, 1, 0) + shift(q, g, -1, 0)
               - 4.0 * shift(q, g, 0, 0))
        outs.append(afac * (wind_u * dx + wind_v * dy) + dfac * lap)
    return jnp.stack(outs)


def _adv_kernel(by, bx, nch, fac_ref, vp_ref, out_ref, scratch, sem):
    """One y-strip per grid step; double-buffered DMA over x-chunks so
    copy latency hides behind the WENO chain of the previous chunk."""
    i = pl.program_id(0)

    def dma(slot, c):
        return pltpu.make_async_copy(
            vp_ref.at[:, pl.ds(i * by, by + 8),
                      pl.ds(c * bx, bx + 2 * _GX)],
            scratch.at[slot], sem.at[slot])

    dma(0, 0).start()

    def chunk(c, _):
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < nch)
        def _():
            dma(1 - slot, c + 1).start()

        dma(slot, c).wait()
        # alignment-only ghosts dropped by VALUE slices (only the DMA'd
        # memref shape must be tile-aligned): y 4 -> 3, x 64 -> 3
        lab = scratch[slot, :, 1:-1, _GX - _G:_GX + _G + bx]
        out_ref[:, :, pl.ds(c * bx, bx)] = _core_seq(
            lab, fac_ref[0], fac_ref[1])
        return 0

    jax.lax.fori_loop(0, nch, chunk, 0)


def _pick(n: int, pref) -> int:
    for b in pref:
        if n % b == 0:
            return b
    return 0


@functools.partial(jax.jit, static_argnames=("ny", "nx"))
def _advect_call(vlab_aligned, facs, ny, nx):
    by = _pick(ny, (32, 16, 8))
    # 1024 cap: the round-4 selection-form WENO (2 recons built from
    # 10 selects) carries more live VMEM temporaries than the r2 form;
    # 2048-wide chunks exceeded the 16M scoped-vmem limit by ~3M
    bx = _pick(nx, (1024, 512, 256, 128))
    nch = nx // bx
    kernel = functools.partial(_adv_kernel, by, bx, nch)
    return pl.pallas_call(
        kernel,
        grid=(ny // by,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            # explicit HBM: ANY may pull the whole lab into VMEM
            pl.BlockSpec(memory_space=pltpu.HBM),
        ],
        out_specs=pl.BlockSpec((2, by, nx), lambda i: (0, i, 0)),
        out_shape=jax.ShapeDtypeStruct((2, ny, nx), vlab_aligned.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, 2, by + 8, bx + 2 * _GX), vlab_aligned.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )(facs, vlab_aligned)


def advect_supported(ny: int, nx: int) -> bool:
    if not HAVE_PALLAS:
        return False
    try:
        # the kernel's DMA idioms are TPU Mosaic only — importing
        # pallas.tpu succeeds on CPU/GPU hosts, running does not.
        # (this image's TPU platform is named 'axon'.)
        if jax.devices()[0].platform not in ("tpu", "axon"):
            return False
    except Exception:
        return False
    return bool(_pick(ny, (32, 16, 8))) and bool(
        _pick(nx, (1024, 512, 256, 128)))


def advect_diffuse_rhs_pallas(vlab, h, nu, dt, nx):
    """Drop-in for `advect_diffuse_rhs(vlab, 3, h, nu, dt)` on a uniform
    grid. vlab: [2, Ny+6, Nx+6] ghost-padded lab."""
    ny = vlab.shape[-2] - 2 * _G
    # re-pad to the aligned halo layout: y 3->4, x 3->64 per side
    vlab = jnp.pad(vlab, ((0, 0), (1, 1), (_GX - _G, _GX - _G)))
    facs = jnp.stack([-dt * h, nu * dt]).astype(vlab.dtype)
    return _advect_call(vlab, facs, ny, nx)
