"""Pallas TPU kernels for the advection hot loop.

Two generations live here:

1. The round-4 single-op kernel (``advect_diffuse_rhs_pallas``): one
   WENO5 advect-diffuse RHS evaluation over a pre-padded lab, y-strip
   grid with double-buffered x-chunk DMA. MEASURED VERDICT (v5e, f32,
   8192^2): 38 ms vs XLA-fused 30 ms per evaluation — both ~20x above
   the HBM roofline (~1.3 ms), i.e. the single op is bound by VPU
   divides and lane-shift permutes, not by the HBM traffic a Pallas
   rewrite of ONE op can eliminate. Kept as the measured-history
   baseline and for the TPU bit-parity test.

2. The PR-9 **megakernel tier** (``fused_advect_heun`` /
   ``fused_lab_rhs`` / ``fused_correction``): one kernel per RK
   substage that reads the velocity from HBM ONCE, synthesizes the
   free-slip ghost halo in VMEM, runs the whole WENO5 + diffusion +
   Heun-update chain on double-buffered row strips, and writes the
   substage result once — attacking the per-op dispatch chain whose
   re-reads pinned BENCH_r04 at 12% HBM utilization. The divide-free
   WENO weight normalization (single reciprocal of the summed alpha
   per component, bit-trick reciprocal for the scale-invariant
   normalizer) is shared VERBATIM from ops/stencil._weno5_weights, so
   the kernel and the XLA chain cannot drift numerically.

   Strip DMA scheme: strips are DMA'd whole (sublane-aligned, each HBM
   row read exactly once) into a ring of FOUR VMEM slots; the halo
   rows of strip i are taken from the resident neighbor strips i-1 and
   i+1, and strip i+2 prefetches while i computes (4 slots because
   {i-1, i, i+1, i+2} must be distinct mod the ring size — a ring of 3
   lets the prefetch overwrite the live top-halo strip). Scratch and
   DMA semaphores persist across sequential grid steps on TPU and in
   interpret mode (probed), which is what makes the cross-program ring
   legal.

   The kernels are leading-dim agnostic like ops/stencil.py: operands
   are flattened to one leading batch axis L with per-batch
   (afac, dfac) scale rows, so the SAME kernel serves the solo
   UniformSim (L=1), member-batched FleetSim (L=B, per-member dt), and
   — in lab form — forest-block batches (L=N, per-block h). On
   non-TPU hosts the tier runs in Pallas interpret mode (validation
   speed, not performance). ISSUE 16 closed the last two refusals:
   non-free-slip BC tables ride the in-VMEM affine ghost synthesis
   (one executable per BC token), and the sharded x-split rides the
   halo-mode kernel (_fused_substage_sharded) behind shard_halo.
   fused_advect_heun_sharded's ppermute-before-interior exchange.

   bf16 storage tier: operands stored bf16 in HBM, every VMEM
   accumulation in f32 (strips are upcast on entry, the final substage
   result is written back f32). Storage halves the bytes the roofline
   charges for the dominant reads; the f32 path stays bit-pinned by
   the goldens.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .stencil import advect_diffuse_core, heun_substage, shift, weno_derivative

try:  # Pallas TPU backend; absent/broken on some hosts -> XLA fallback
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False

_G = 3    # WENO5 halo
_GX = 64  # x halo rounded up to lane alignment (128-multiple DMA widths)

# fused-tier strip heights: the strip DMA slices [k*by, by) must be
# sublane-aligned, so by is the storage dtype's sublane tile (f32: 8,
# bf16: 16) — every uniform grid in the repo (bs=8 blocks) divides it
_BY_F32 = 8
_BY_BF16 = 16


def _rem(k, m):
    """``k % m`` with both operands pinned i32: program_id is i32, and
    under x64 (the CPU test harness) a bare Python modulus constant
    promotes to i64 — interpret-mode stablehlo rejects the mix."""
    return jax.lax.rem(jnp.asarray(k, jnp.int32), jnp.int32(m))


def _on_accel() -> bool:
    """True when the default device compiles Mosaic kernels (this
    image's TPU platform registers as 'axon')."""
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:  # pragma: no cover
        return False


def _core_seq(lab, afac, dfac):
    """advect_diffuse_core evaluated one velocity component at a time:
    same arithmetic (the correctness tests compare against the shared
    core bit-for-bit), but the live temporaries are [BY, BX] instead of
    [2, BY, BX], which lets Mosaic fit twice the tile in VMEM stack —
    fewer, larger chunks amortize the per-chunk DMA/loop overhead."""
    g = _G
    wind_u = shift(lab, g, 0, 0)[0]
    wind_v = shift(lab, g, 0, 0)[1]
    outs = []
    for c in (0, 1):
        q = lab[c]
        dx = weno_derivative(
            wind_u,
            shift(q, g, 0, -3), shift(q, g, 0, -2), shift(q, g, 0, -1),
            shift(q, g, 0, 0),
            shift(q, g, 0, 1), shift(q, g, 0, 2), shift(q, g, 0, 3))
        dy = weno_derivative(
            wind_v,
            shift(q, g, -3, 0), shift(q, g, -2, 0), shift(q, g, -1, 0),
            shift(q, g, 0, 0),
            shift(q, g, 1, 0), shift(q, g, 2, 0), shift(q, g, 3, 0))
        lap = (shift(q, g, 0, 1) + shift(q, g, 0, -1)
               + shift(q, g, 1, 0) + shift(q, g, -1, 0)
               - 4.0 * shift(q, g, 0, 0))
        outs.append(afac * (wind_u * dx + wind_v * dy) + dfac * lap)
    return jnp.stack(outs)


# ===========================================================================
# round-4 single-op kernel (pre-padded lab, opt-in history baseline)
# ===========================================================================

def _adv_kernel(by, bx, nch, fac_ref, vp_ref, out_ref, scratch, sem):
    """One y-strip per grid step; double-buffered DMA over x-chunks so
    copy latency hides behind the WENO chain of the previous chunk."""
    i = pl.program_id(0)

    def dma(slot, c):
        return pltpu.make_async_copy(
            vp_ref.at[:, pl.ds(i * by, by + 8),
                      pl.ds(c * bx, bx + 2 * _GX)],
            scratch.at[slot], sem.at[slot])

    dma(0, 0).start()

    def chunk(c, _):
        slot = _rem(c, 2)

        @pl.when(c + 1 < nch)
        def _():
            dma(1 - slot, c + 1).start()

        dma(slot, c).wait()
        # alignment-only ghosts dropped by VALUE slices (only the DMA'd
        # memref shape must be tile-aligned): y 4 -> 3, x 64 -> 3
        lab = scratch[slot, :, 1:-1, _GX - _G:_GX + _G + bx]
        out_ref[:, :, pl.ds(c * bx, bx)] = _core_seq(
            lab, fac_ref[0], fac_ref[1])
        return 0

    jax.lax.fori_loop(0, nch, chunk, 0)


def _pick(n: int, pref) -> int:
    for b in pref:
        if n % b == 0:
            return b
    return 0


@functools.partial(jax.jit, static_argnames=("ny", "nx"))
def _advect_call(vlab_aligned, facs, ny, nx):
    by = _pick(ny, (32, 16, 8))
    # 1024 cap: the round-4 selection-form WENO (2 recons built from
    # 10 selects) carries more live VMEM temporaries than the r2 form;
    # 2048-wide chunks exceeded the 16M scoped-vmem limit by ~3M
    bx = _pick(nx, (1024, 512, 256, 128))
    nch = nx // bx
    kernel = functools.partial(_adv_kernel, by, bx, nch)
    return pl.pallas_call(
        kernel,
        grid=(ny // by,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            # ANY leaves the lab where it lives (HBM at these sizes);
            # this jax version has no pltpu.HBM token
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec((2, by, nx), lambda i: (0, i, 0)),
        out_shape=jax.ShapeDtypeStruct((2, ny, nx), vlab_aligned.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, 2, by + 8, bx + 2 * _GX), vlab_aligned.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )(facs, vlab_aligned)


def advect_supported(ny: int, nx: int) -> bool:
    """Gate for the round-4 single-op kernel: TPU-compiled only (its
    DMA idioms are Mosaic-specific and it exists for the measured
    history + parity test, not as a fallback tier)."""
    if not HAVE_PALLAS or not _on_accel():
        return False
    return bool(_pick(ny, (32, 16, 8))) and bool(
        _pick(nx, (1024, 512, 256, 128)))


def advect_diffuse_rhs_pallas(vlab, h, nu, dt, nx):
    """Drop-in for `advect_diffuse_rhs(vlab, 3, h, nu, dt)` on a uniform
    grid. vlab: [2, Ny+6, Nx+6] ghost-padded lab."""
    ny = vlab.shape[-2] - 2 * _G
    # re-pad to the aligned halo layout: y 3->4, x 3->64 per side
    vlab = jnp.pad(vlab, ((0, 0), (1, 1), (_GX - _G, _GX - _G)))
    facs = jnp.stack([-dt * h, nu * dt]).astype(vlab.dtype)
    return _advect_call(vlab, facs, ny, nx)


# ===========================================================================
# PR-9 megakernel tier
# ===========================================================================

def fused_tier_supported(ny: int, nx: int, prec: str = "f32") -> bool:
    """Shape-level gate for the fused substage/correction kernels.
    Platform-independent: non-TPU hosts run the SAME kernels in
    interpret mode (the tier is opt-in via CUP2D_PALLAS, so a CPU user
    who latches it gets correctness-validation speed on purpose). On a
    compiled TPU the strip DMA additionally needs lane-aligned rows."""
    if not HAVE_PALLAS:
        return False
    by = _BY_BF16 if prec == "bf16" else _BY_F32
    if ny < by or ny % by:
        return False
    if _on_accel() and nx % 128:
        return False
    return True


def lab_tier_supported(dtype) -> bool:
    """Gate for the forest-lab RHS kernel: f32 storage only (Mosaic has
    no f64; the f64 forest validation path stays on XLA)."""
    return HAVE_PALLAS and jnp.dtype(dtype) == jnp.float32


# ghost kinds the megakernel synthesizes in VMEM (all of bc.py's
# current vocabulary; a future kind — e.g. periodic — must be added
# here WITH its in-kernel ghost form, or the tier refuses loudly)
_KERNEL_BC_KINDS = ("free_slip", "no_slip", "inflow", "outflow")


def kernel_supports(bc) -> None:
    """Kernel-tier capability check for the per-face BC tables (bc.py):
    every ghost kind in bc.py's current vocabulary (free-slip mirror,
    no-slip antireflection, Dirichlet inflow incl. the parabolic
    profile, convective outflow) reduces to an affine combination of
    the edge/inner lines and is synthesized in VMEM from global
    position plus static per-face coefficients — one executable per BC
    token, no in-kernel branching. Only a genuinely unsupported kind
    (a future ``periodic``) refuses, loudly and naming the token, so a
    silent wrong-physics fallback is impossible."""
    if bc is None:
        return
    for name, f in zip(("x_lo", "x_hi", "y_lo", "y_hi"), bc):
        if f.kind not in _KERNEL_BC_KINDS:
            raise ValueError(
                f"CUP2D_PALLAS=1: BCTable ({bc.token}) face {name} has "
                f"kind {f.kind!r}, which has no in-VMEM ghost synthesis "
                f"in the fused kernel (supported: "
                f"{', '.join(_KERNEL_BC_KINDS)}). Unset CUP2D_PALLAS "
                "for this table; it runs on the XLA tier.")


# ---------------------------------------------------------------------------
# in-VMEM BC ghost synthesis (tentpole, ISSUE 16): the staged twin of
# bc.pad_vector_bc's ghost() closure. The FaceBC is STATIC at trace
# time (the BCTable is latched per grid and already the executable's
# hash/admit key), so each helper emits only the selected face's
# affine arithmetic — the kernel never branches on table kind.
# ---------------------------------------------------------------------------

def _bc_uw_y(face, w, nx_tot, col0):
    """Wall velocity (u, v) of a y face over ``w`` columns whose global
    interior column index starts at ``col0`` (0 solo; the shard/halo
    offset under the x-split). Mirrors bc._face_wall/_profile_1d:
    scalars, or val * 4s(1-s) lines with s = (col + 0.5)/nx."""
    if face.kind not in ("no_slip", "inflow"):
        return (0.0, 0.0)
    prof = None
    if face.kind == "inflow" and face.profile != "uniform":
        col = jax.lax.broadcasted_iota(jnp.int32, (1, 1, w), 2) + col0
        s = (col.astype(jnp.float32) + 0.5) / nx_tot
        prof = 4.0 * s * (1.0 - s)
    return tuple((v if prof is None or v == 0.0 else v * prof)
                 for v in face.u_wall)


def _bc_uw_x(face, rows, row0, ny_tot):
    """Wall velocity of an x face over ``rows`` PADDED rows starting at
    global padded row ``row0`` (= strip_index * by; the x strips paint
    full rows so corners compose with the y ghosts). Mirrors
    bc._x_face_wall_padded: profile coordinates clamped to the face, so
    a parabolic inflow closes to 0 at the wall corners."""
    if face.kind not in ("no_slip", "inflow"):
        return (0.0, 0.0)
    if face.profile == "uniform" or face.kind == "no_slip":
        return face.u_wall
    row = jax.lax.broadcasted_iota(jnp.int32, (1, rows, 1), 1) + row0
    s = (row.astype(jnp.float32) - _G + 0.5) / ny_tot
    s = jnp.clip(s, 0.0, 1.0)
    prof = 4.0 * s * (1.0 - s)
    return tuple((v * prof if v != 0.0 else 0.0) for v in face.u_wall)


def _bc_ghost(face, edge, inner, normal_comp, outward_sign, uw, dtf, h):
    """One painted ghost line per bc.pad_vector_bc's ghost() closure,
    identical arithmetic and evaluation order (the ~1-ulp equivalence
    contract): edge/inner are [2, 1, W] (y faces) or [2, R, 1] (x
    faces) f32 line pairs; ``uw`` the (possibly profiled) wall
    velocity; ``dtf`` the per-member dt scalar feeding the convective-
    outflow speed c = clip(sign * edge_n * dt/h, 0, 1)."""
    eu, ev = edge[0:1], edge[1:2]
    if face.kind == "free_slip":
        return (jnp.concatenate([-eu, ev], axis=0) if normal_comp == 0
                else jnp.concatenate([eu, -ev], axis=0))
    if face.kind in ("no_slip", "inflow"):
        return jnp.concatenate(
            [2.0 * uw[0] - eu, 2.0 * uw[1] - ev], axis=0)
    en = eu if normal_comp == 0 else ev
    c = jnp.clip(outward_sign * en * dtf / h, 0.0, 1.0)
    return edge + c * (edge - inner)


def _substage_kernel(by, n, nx, cfac, ih2, has_vold, out_dtype, bc, hh,
                     facs_ref, vel_ref, *rest):
    """One Heun substage on one row strip of one batch member.

    Grid (L, n): batch-major, strips sequential within a member. The
    velocity is read from HBM exactly once per substage: whole strips
    (no halo overlap) DMA into a 4-slot ring; strip i's WENO halo rows
    come from the resident strips i-1 / i+1, or from the wall ghosts
    synthesized in VMEM (``bc is None``: the PR-9 free-slip mirror,
    kept verbatim so the default table stays bit-identical; else the
    BC'd affine ghost forms of _bc_ghost, with the per-member dt in
    facs column 2 feeding convective outflow). Strip i+2 prefetches
    during strip i's compute (the double-buffering; ring of 4 because
    strips {i-1..i+2} must occupy distinct slots). The lab tile is
    assembled as VALUES (concatenates), not scratch stores — no
    unaligned vector stores for Mosaic to choke on."""
    if has_vold:
        vold_ref, out_ref, ring, sems, vring, vsems = rest
    else:
        out_ref, ring, sems = rest

    l = pl.program_id(0)
    i = pl.program_id(1)
    g = _G

    def dma(k):
        slot = _rem(k, 4)
        return pltpu.make_async_copy(
            vel_ref.at[l, :, pl.ds(k * by, by), :],
            ring.at[slot], sems.at[slot])

    # exactly-once start/wait discipline: dma(k) starts at program
    # max(0, k-2) and is waited at program max(0, k-1), one program
    # before its data is first consumed as a bottom halo
    @pl.when(i == 0)
    def _():
        dma(0).start()
        if n > 1:
            dma(1).start()

    @pl.when(i + 2 < n)
    def _():
        dma(i + 2).start()

    if has_vold:
        def vdma(k):
            slot = _rem(k, 2)
            return pltpu.make_async_copy(
                vold_ref.at[l, :, pl.ds(k * by, by), :],
                vring.at[slot], vsems.at[slot])

        @pl.when(i == 0)
        def _():
            vdma(0).start()

        @pl.when(i + 1 < n)
        def _():
            vdma(i + 1).start()

    @pl.when(i == 0)
    def _():
        dma(0).wait()
        if n > 1:
            dma(1).wait()

    @pl.when((i > 0) & (i + 1 < n))
    def _():
        dma(i + 1).wait()

    if has_vold:
        vdma(i).wait()

    f32 = jnp.float32
    cur = ring[_rem(i, 4)].astype(f32)               # [2, by, nx]
    # neighbor-strip halo rows; the untaken wall branch reads a ring
    # slot that may be uninitialized — jnp.where only selects, never
    # computes on the discarded operand
    prev_t = ring[_rem(i + 3, 4)][:, by - g:, :].astype(f32)
    next_h = ring[_rem(i + 1, 4)][:, :g, :].astype(f32)
    if bc is None:
        # free-slip mirror ghosts (uniform.pad_vector, zeroth-order):
        # all g ghost rows equal the edge row — u copied, v negated at
        # y walls. PR-9 path, verbatim (bit-identity contract).
        top_m = jnp.concatenate(
            [cur[0:1, 0:1, :], -cur[1:2, 0:1, :]], axis=0)
        bot_m = jnp.concatenate(
            [cur[0:1, by - 1:by, :], -cur[1:2, by - 1:by, :]], axis=0)
        top = jnp.where(i > 0, prev_t,
                        jnp.broadcast_to(top_m, (2, g, nx)))
        bot = jnp.where(i + 1 < n, next_h,
                        jnp.broadcast_to(bot_m, (2, g, nx)))
        ycol = jnp.concatenate([top, cur, bot], axis=1)     # [2, by+6, nx]
        # x ghosts read the y-completed columns so corners compose both
        # flips, exactly like pad_vector's two-pass sweep: u negated,
        # v copied at x walls
        left = jnp.concatenate(
            [-ycol[0:1, :, 0:1], ycol[1:2, :, 0:1]], axis=0)
        right = jnp.concatenate(
            [-ycol[0:1, :, nx - 1:nx], ycol[1:2, :, nx - 1:nx]], axis=0)
        lab = jnp.concatenate(
            [jnp.broadcast_to(left, (2, by + 2 * g, g)), ycol,
             jnp.broadcast_to(right, (2, by + 2 * g, g))], axis=2)
    else:
        # BC'd wall ghosts (bc.pad_vector_bc, staged): y faces first
        # over interior columns, then x faces over the y-completed
        # columns so corners compose in the same order
        dtf = facs_ref[l, 2]
        glo = _bc_ghost(bc.y_lo, cur[:, 0:1, :], cur[:, 1:2, :],
                        1, -1.0, _bc_uw_y(bc.y_lo, nx, nx, 0), dtf, hh)
        ghi = _bc_ghost(bc.y_hi, cur[:, by - 1:by, :],
                        cur[:, by - 2:by - 1, :],
                        1, 1.0, _bc_uw_y(bc.y_hi, nx, nx, 0), dtf, hh)
        top = jnp.where(i > 0, prev_t,
                        jnp.broadcast_to(glo, (2, g, nx)))
        bot = jnp.where(i + 1 < n, next_h,
                        jnp.broadcast_to(ghi, (2, g, nx)))
        ycol = jnp.concatenate([top, cur, bot], axis=1)     # [2, by+6, nx]
        rows = by + 2 * g
        gl = _bc_ghost(bc.x_lo, ycol[:, :, 0:1], ycol[:, :, 1:2],
                       0, -1.0, _bc_uw_x(bc.x_lo, rows, i * by, n * by),
                       dtf, hh)
        gr = _bc_ghost(bc.x_hi, ycol[:, :, nx - 1:nx],
                       ycol[:, :, nx - 2:nx - 1],
                       0, 1.0, _bc_uw_x(bc.x_hi, rows, i * by, n * by),
                       dtf, hh)
        lab = jnp.concatenate(
            [jnp.broadcast_to(gl, (2, rows, g)), ycol,
             jnp.broadcast_to(gr, (2, rows, g))], axis=2)

    af = facs_ref[l, 0]
    df = facs_ref[l, 1]
    rhs = _core_seq(lab, af, df)
    if has_vold:
        vold = vring[_rem(i, 2)].astype(f32)
    else:
        vold = cur  # substage 1: vold IS vel — zero extra HBM reads
    out_ref[0] = heun_substage(vold, cfac, rhs, ih2).astype(out_dtype)


def _fused_substage(v, vold, facs, cfac, ih2, out_dtype, interpret,
                    bc=None, hh=None):
    """One megakernel substage over flattened operands.
    v: [L, 2, ny, nx] (storage dtype); vold: same or None (substage 1,
    where vold==vel and the ring strip is reused); facs: [L, 2] f32
    (afac, dfac) per batch member, widened to [L, 3] with the raw dt
    in column 2 when a BC table rides along."""
    L, _, ny, nx = v.shape
    by = _BY_BF16 if v.dtype == jnp.bfloat16 else _BY_F32
    n = ny // by
    has_vold = vold is not None
    kern = functools.partial(_substage_kernel, by, n, nx,
                             cfac, ih2, has_vold, jnp.dtype(out_dtype),
                             bc, hh)
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.ANY)]
    ops = [facs, v]
    scratch = [pltpu.VMEM((4, 2, by, nx), v.dtype),
               pltpu.SemaphoreType.DMA((4,))]
    if has_vold:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))
        ops.append(vold)
        scratch += [pltpu.VMEM((2, 2, by, nx), vold.dtype),
                    pltpu.SemaphoreType.DMA((2,))]
    return pl.pallas_call(
        kern,
        grid=(L, n),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 2, by, nx), lambda l, i: (l, 0, i, 0)),
        out_shape=jax.ShapeDtypeStruct((L, 2, ny, nx), out_dtype),
        scratch_shapes=scratch,
        interpret=interpret,
    )(*ops)


def _flatten_lead(shape_lead):
    L = 1
    for d in shape_lead:
        L *= int(d)
    return max(L, 1)


def _per_member(x, lead, L, dtype=jnp.float32):
    """Normalize a scale factor to a flat [L] row: accepts a scalar, a
    leading-shaped vector (fleet per-member dt), or the leading shape
    with trailing singleton broadcast dims (the forest's [N,1,1,1]
    per-block h)."""
    x = jnp.asarray(x, dtype)
    if x.ndim > len(lead):
        x = x.reshape(x.shape[:len(lead)] + (-1,))[..., 0]
    return jnp.broadcast_to(x, lead).reshape((L,))


def fused_advect_heun(vel, h, nu, dt, *, bc=None, bf16: bool = False,
                      interpret=None):
    """Both Heun substages through the fused megakernel — the drop-in
    tier for ``UniformGrid.advect_heun`` and the fleet's inlined chain.

    vel: [..., 2, Ny, Nx] (any leading dims); dt: scalar or shaped like
    the leading dims (per-member fleet dt). f32 path: documented-ulp
    equivalent to the XLA chain (identical op sequence; the only
    deviation source is compiler FMA contraction, bound asserted in
    tests/test_megakernel.py). bf16: storage-precision tier — one
    upcast-free bf16 read per substage, f32 VMEM accumulation, f32
    final state. bc: optional BCTable (bc.py); a free-slip table is
    normalized to None so the default path stays bit-identical to
    PR 9, any other table rides the in-VMEM BC ghost synthesis (one
    executable per token — the FaceBCs are static trace constants)."""
    if bc is not None and bc.is_free_slip:
        bc = None
    kernel_supports(bc)
    lead = vel.shape[:-3]
    L = _flatten_lead(lead)
    v = vel.reshape((L,) + vel.shape[-3:])
    dtv = _per_member(dt, lead, L)
    hh = float(h)
    if bc is None:
        facs = jnp.stack([-dtv * hh, nu * dtv], axis=-1)    # [L, 2] f32
    else:
        # raw per-member dt rides column 2 (convective-outflow speed)
        facs = jnp.stack([-dtv * hh, nu * dtv, dtv], axis=-1)
    ih2 = 1.0 / (hh * hh)
    if interpret is None:
        interpret = not _on_accel()
    if bf16:
        vb = v.astype(jnp.bfloat16)
        v1 = _fused_substage(vb, None, facs, 0.5, ih2,
                             jnp.bfloat16, interpret, bc, hh)
        v2 = _fused_substage(v1, vb, facs, 1.0, ih2, v.dtype, interpret,
                             bc, hh)
    else:
        v1 = _fused_substage(v, None, facs, 0.5, ih2, v.dtype,
                             interpret, bc, hh)
        v2 = _fused_substage(v1, v, facs, 1.0, ih2, v.dtype, interpret,
                             bc, hh)
    return v2.reshape(vel.shape)


# ---------------------------------------------------------------------------
# sharded-x-split substage (tentpole, ISSUE 16): the halo-mode twin of
# _substage_kernel. The shard_map wrapper (parallel/shard_halo.
# fused_advect_heun_sharded) ppermutes the 3-wide WENO edge columns
# BEFORE dispatching this kernel, so the exchange latency hides behind
# the strip pipeline; the received columns arrive as a lane-padded
# ``aux`` operand and are fused as the boundary strips' ghost source —
# termwise-identical to the GSPMD chain.
# ---------------------------------------------------------------------------

def _sharded_substage_kernel(by, n, nxl, nx_tot, cfac, ih2, has_vold,
                             out_dtype, bc, hh, facs_ref, info_ref,
                             vel_ref, aux_ref, *rest):
    """Per-shard substage over the local x slab [2, ny, nxl].

    aux: [L, 2, ny, 2*_GX] halo operand — received left-neighbor edge
    columns in [:, :, :, 0:g], right-neighbor in [g:2g], zero elsewhere
    (lane padding, and zeros at the mesh walls where no neighbor
    sends). info (SMEM i32 [1, 3]): (is_lo, is_hi, col0 = idx*nxl) from
    the traced axis index — the ONLY per-shard values; everything else
    is static, so all shards share one executable. Both rings follow
    the solo kernel's exactly-once DMA discipline; the extended strip
    (halo + local + halo) runs the y-ghost pass at global column
    coordinates, then wall shards where-select the x-face BC paint over
    the (zero) non-received halo columns — the same corner composition
    order as bc.pad_vector_bc."""
    if has_vold:
        vold_ref, out_ref, ring, sems, aring, asems, vring, vsems = rest
    else:
        out_ref, ring, sems, aring, asems = rest

    l = pl.program_id(0)
    i = pl.program_id(1)
    g = _G

    def dma(k):
        slot = _rem(k, 4)
        return pltpu.make_async_copy(
            vel_ref.at[l, :, pl.ds(k * by, by), :],
            ring.at[slot], sems.at[slot])

    def adma(k):
        slot = _rem(k, 4)
        return pltpu.make_async_copy(
            aux_ref.at[l, :, pl.ds(k * by, by), :],
            aring.at[slot], asems.at[slot])

    @pl.when(i == 0)
    def _():
        dma(0).start()
        adma(0).start()
        if n > 1:
            dma(1).start()
            adma(1).start()

    @pl.when(i + 2 < n)
    def _():
        dma(i + 2).start()
        adma(i + 2).start()

    if has_vold:
        def vdma(k):
            slot = _rem(k, 2)
            return pltpu.make_async_copy(
                vold_ref.at[l, :, pl.ds(k * by, by), :],
                vring.at[slot], vsems.at[slot])

        @pl.when(i == 0)
        def _():
            vdma(0).start()

        @pl.when(i + 1 < n)
        def _():
            vdma(i + 1).start()

    @pl.when(i == 0)
    def _():
        dma(0).wait()
        adma(0).wait()
        if n > 1:
            dma(1).wait()
            adma(1).wait()

    @pl.when((i > 0) & (i + 1 < n))
    def _():
        dma(i + 1).wait()
        adma(i + 1).wait()

    if has_vold:
        vdma(i).wait()

    f32 = jnp.float32
    is_lo = info_ref[0, 0]
    is_hi = info_ref[0, 1]
    col0 = info_ref[0, 2]
    we = nxl + 2 * g

    def ext(k, rows):
        """Extended-width rows of strip k: received halo columns glued
        onto the local slab (value concatenate, f32 upcast)."""
        a = aring[_rem(k, 4)][:, rows, :].astype(f32)
        v = ring[_rem(k, 4)][:, rows, :].astype(f32)
        return jnp.concatenate(
            [a[:, :, 0:g], v, a[:, :, g:2 * g]], axis=2)

    cur = ext(i, slice(None))                        # [2, by, we]
    prev_t = ext(i + 3, slice(by - g, by))
    next_h = ext(i + 1, slice(0, g))
    dtf = facs_ref[l, 2]
    # y ghosts over the EXTENDED width at global column coordinates:
    # halo columns get the same paint the neighbor's own y pass gives
    # them (identical formula, identical edge/inner data); the unsent
    # wall-shard halo columns are junk here and are overwritten by the
    # x-face where-select below — corners compose in bc.py's order
    glo = _bc_ghost(bc.y_lo, cur[:, 0:1, :], cur[:, 1:2, :],
                    1, -1.0, _bc_uw_y(bc.y_lo, we, nx_tot, col0 - g),
                    dtf, hh)
    ghi = _bc_ghost(bc.y_hi, cur[:, by - 1:by, :], cur[:, by - 2:by - 1, :],
                    1, 1.0, _bc_uw_y(bc.y_hi, we, nx_tot, col0 - g),
                    dtf, hh)
    top = jnp.where(i > 0, prev_t, jnp.broadcast_to(glo, (2, g, we)))
    bot = jnp.where(i + 1 < n, next_h,
                    jnp.broadcast_to(ghi, (2, g, we)))
    ycol = jnp.concatenate([top, cur, bot], axis=1)  # [2, by+2g, we]
    rows = by + 2 * g
    gl = _bc_ghost(bc.x_lo, ycol[:, :, g:g + 1], ycol[:, :, g + 1:g + 2],
                   0, -1.0, _bc_uw_x(bc.x_lo, rows, i * by, n * by),
                   dtf, hh)
    gr = _bc_ghost(bc.x_hi, ycol[:, :, g + nxl - 1:g + nxl],
                   ycol[:, :, g + nxl - 2:g + nxl - 1],
                   0, 1.0, _bc_uw_x(bc.x_hi, rows, i * by, n * by),
                   dtf, hh)
    left = jnp.where(is_lo > 0, jnp.broadcast_to(gl, (2, rows, g)),
                     ycol[:, :, 0:g])
    right = jnp.where(is_hi > 0, jnp.broadcast_to(gr, (2, rows, g)),
                      ycol[:, :, g + nxl:])
    lab = jnp.concatenate([left, ycol[:, :, g:g + nxl], right], axis=2)

    af = facs_ref[l, 0]
    df = facs_ref[l, 1]
    rhs = _core_seq(lab, af, df)
    if has_vold:
        vold = vring[_rem(i, 2)].astype(f32)
    else:
        vold = cur[:, :, g:g + nxl]
    out_ref[0] = heun_substage(vold, cfac, rhs, ih2).astype(out_dtype)


def _fused_substage_sharded(v, vold, aux, info, facs, cfac, ih2,
                            out_dtype, bc, hh, nx_tot, interpret):
    """One halo-mode substage over flattened per-shard operands.
    v: [L, 2, ny, nxl]; aux: [L, 2, ny, 2*_GX] received halo columns;
    info: [1, 3] i32 (is_lo, is_hi, col0); facs: [L, 3]."""
    L, _, ny, nxl = v.shape
    by = _BY_BF16 if v.dtype == jnp.bfloat16 else _BY_F32
    n = ny // by
    has_vold = vold is not None
    kern = functools.partial(_sharded_substage_kernel, by, n, nxl,
                             nx_tot, cfac, ih2, has_vold,
                             jnp.dtype(out_dtype), bc, hh)
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.ANY),
                pl.BlockSpec(memory_space=pltpu.ANY)]
    ops = [facs, info, v, aux]
    scratch = [pltpu.VMEM((4, 2, by, nxl), v.dtype),
               pltpu.SemaphoreType.DMA((4,)),
               pltpu.VMEM((4, 2, by, 2 * _GX), aux.dtype),
               pltpu.SemaphoreType.DMA((4,))]
    if has_vold:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))
        ops.append(vold)
        scratch += [pltpu.VMEM((2, 2, by, nxl), vold.dtype),
                    pltpu.SemaphoreType.DMA((2,))]
    return pl.pallas_call(
        kern,
        grid=(L, n),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 2, by, nxl), lambda l, i: (l, 0, i, 0)),
        out_shape=jax.ShapeDtypeStruct((L, 2, ny, nxl), out_dtype),
        scratch_shapes=scratch,
        interpret=interpret,
    )(*ops)


# ---------------------------------------------------------------------------
# lab-mode RHS (forest blocks): the AMR stages interleave flux
# corrections between RHS and update, so the fusable unit is lab -> rhs
# ---------------------------------------------------------------------------

def _lab_kernel(g, facs_ref, lab_ref, out_ref):
    af = facs_ref[:, :, :, 0:1]                 # [cb, 1, 1, 1]
    df = facs_ref[:, :, :, 1:2]
    out_ref[...] = advect_diffuse_core(lab_ref[...], g, af, df)


def fused_lab_rhs(lab, h, nu, dt, *, interpret=None):
    """Fused advect-diffuse RHS over pre-assembled ghost labs
    [..., 2, H+2g, W+2g] -> [..., 2, H, W]; ``h`` may be per-block
    ([N, 1, 1] on the forest) and ``dt`` scalar. One HBM read of the
    lab per evaluation; block chunks ride the standard Pallas
    double-buffered pipeline (BlockSpec grid over the leading axis).
    Shares advect_diffuse_core verbatim with the XLA path."""
    g = _G
    lead = lab.shape[:-3]
    L = _flatten_lead(lead)
    Hp, Wp = lab.shape[-2:]
    lab2 = lab.reshape((L, 2, Hp, Wp))
    a = _per_member(-dt * h, lead, L, lab.dtype).reshape((L, 1, 1))
    d = _per_member(nu * dt, lead, L, lab.dtype).reshape((L, 1, 1))
    facs = jnp.stack([a, d], axis=-1)           # [L, 1, 1, 2]
    cb = _pick(L, (64, 32, 16, 8, 4, 2, 1))
    if interpret is None:
        interpret = not _on_accel()
    kern = functools.partial(_lab_kernel, g)
    out = pl.pallas_call(
        kern,
        grid=(L // cb,),
        in_specs=[
            pl.BlockSpec((cb, 1, 1, 2), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((cb, 2, Hp, Wp), lambda i: (i, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((cb, 2, Hp - 2 * g, Wp - 2 * g),
                               lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(
            (L, 2, Hp - 2 * g, Wp - 2 * g), lab.dtype),
        interpret=interpret,
    )(facs, lab2)
    return out.reshape(lead + out.shape[1:])


# ---------------------------------------------------------------------------
# fused projection correction: pres = ((x - mean x) + pold - mean pold),
# vel += pfac * grad_neumann(pres) * ih2 — one read of x/pold/vel, one
# write of pres/vel, replacing the XLA mean-free + gradient + update
# chain's separate passes (poisson.project_correct dispatches here)
# ---------------------------------------------------------------------------

def _correct_kernel(by, n, ny, nx, ih2, gs, scal_ref, x_ref, p_ref,
                    v_ref, pres_out, vel_out, xr, xs, pr, ps, vr, vs):
    l = pl.program_id(0)
    i = pl.program_id(1)

    def dstrip(ref, ring, sem, k, slots):
        slot = _rem(k, slots)
        return pltpu.make_async_copy(
            ref.at[l, pl.ds(k * by, by), :]
            if ref is not v_ref else
            ref.at[l, :, pl.ds(k * by, by), :],
            ring.at[slot], sem.at[slot])

    def dx(k):
        return dstrip(x_ref, xr, xs, k, 4)

    def dp(k):
        return dstrip(p_ref, pr, ps, k, 4)

    def dv(k):
        return dstrip(v_ref, vr, vs, k, 2)

    @pl.when(i == 0)
    def _():
        dx(0).start()
        dp(0).start()
        dv(0).start()
        if n > 1:
            dx(1).start()
            dp(1).start()

    @pl.when(i + 2 < n)
    def _():
        dx(i + 2).start()
        dp(i + 2).start()

    @pl.when(i + 1 < n)
    def _():
        dv(i + 1).start()

    @pl.when(i == 0)
    def _():
        dx(0).wait()
        dp(0).wait()
        if n > 1:
            dx(1).wait()
            dp(1).wait()

    @pl.when((i > 0) & (i + 1 < n))
    def _():
        dx(i + 1).wait()
        dp(i + 1).wait()

    dv(i).wait()

    f32 = jnp.float32
    mx = scal_ref[l, 0]
    mp = scal_ref[l, 1]
    pfac = scal_ref[l, 2]

    def pt(k, rows):
        """Mean-free pressure values of strip k's given rows — the
        exact XLA expression ((x - mx) + pold) - mp."""
        xv = xr[_rem(k, 4)][rows, :]
        pv = pr[_rem(k, 4)][rows, :]
        return ((xv - mx) + pv) - mp

    cur = pt(i, slice(None))                                # [by, nx]
    # zero-ghost shift rows (the fused-BC zero-shift form): wall rows
    # are zeros, interior rows come from the neighbor strips
    top = jnp.where(i > 0, pt(i + 3, slice(by - 1, by)), 0.0)
    bot = jnp.where(i + 1 < n, pt(i + 1, slice(0, 1)), 0.0)
    pcol = jnp.concatenate([top, cur, bot], axis=0)         # [by+2, nx]
    z = jnp.zeros((by + 2, 1), f32)
    pw = jnp.concatenate([z, pcol, z], axis=1)              # [by+2, nx+2]
    # rank-1 BC edge corrections from GLOBAL indices: -s at the low
    # wall, +s at the high wall with s the per-face pressure-row sign
    # (bc.BCTable.pressure_signs; +1 Neumann, -1 Dirichlet outflow) —
    # gs=(1,1,1,1) reproduces stencil._edge_ones' Neumann constants
    # bitwise (2-D iota — Mosaic has no 1-D iota)
    sx_lo, sx_hi, sy_lo, sy_hi = gs
    col = jax.lax.broadcasted_iota(jnp.int32, (by, nx), 1)
    row = jax.lax.broadcasted_iota(jnp.int32, (by, nx), 0) + i * by
    zero = jnp.zeros((), f32)
    gx = jnp.where(col == 0, jnp.asarray(-sx_lo, f32),
                   jnp.where(col == nx - 1, jnp.asarray(sx_hi, f32),
                             zero))
    gy = jnp.where(row == 0, jnp.asarray(-sy_lo, f32),
                   jnp.where(row == ny - 1, jnp.asarray(sy_hi, f32),
                             zero))
    dpx = (pw[1:-1, 2:] - pw[1:-1, :-2]) + cur * gx
    dpy = (pw[2:, 1:-1] - pw[:-2, 1:-1]) + cur * gy
    dv_ = pfac * jnp.stack([dpx, dpy], axis=0)              # [2, by, nx]
    pres_out[0] = cur
    vel_out[0] = vr[_rem(i, 2)] + dv_ * ih2


def fused_correction(x, pres_old, vel, mx, mp, pfac, ih2, *,
                     grad_signs=None, interpret=None):
    """x, pres_old: [L, Ny, Nx]; vel: [L, 2, Ny, Nx]; mx/mp/pfac: [L]
    (means and -0.5*dt*h per batch member). grad_signs: optional
    static (sx_lo, sx_hi, sy_lo, sy_hi) per-face pressure-row signs
    (bc.BCTable.pressure_signs); None = all-Neumann, bit-identical to
    the PR-9 kernel. Returns (pres, vel)."""
    L, ny, nx = x.shape
    by = _BY_F32
    n = ny // by
    if interpret is None:
        interpret = not _on_accel()
    gs = ((1.0, 1.0, 1.0, 1.0) if grad_signs is None
          else tuple(float(s) for s in grad_signs))
    scal = jnp.stack([mx, mp, pfac], axis=-1).astype(jnp.float32)
    kern = functools.partial(_correct_kernel, by, n, ny, nx, ih2, gs)
    f32 = jnp.float32
    return pl.pallas_call(
        kern,
        grid=(L, n),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.ANY),
                  pl.BlockSpec(memory_space=pltpu.ANY),
                  pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=[
            pl.BlockSpec((1, by, nx), lambda l, i: (l, i, 0)),
            pl.BlockSpec((1, 2, by, nx), lambda l, i: (l, 0, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((L, ny, nx), x.dtype),
            jax.ShapeDtypeStruct((L, 2, ny, nx), vel.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((4, by, nx), f32), pltpu.SemaphoreType.DMA((4,)),
            pltpu.VMEM((4, by, nx), f32), pltpu.SemaphoreType.DMA((4,)),
            pltpu.VMEM((2, 2, by, nx), f32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(scal, x, pres_old, vel)


# ---------------------------------------------------------------------------
# memory-tiered FAS strip smoothers (ISSUE 19): the whole n-sweep
# damped-Jacobi chain of one MG smooth as ONE time-skewed strip
# pipeline — the XLA fori_loop form reads e and r from HBM once per
# sweep (2n+1 field passes for n sweeps); here sweep k of strip j runs
# as soon as sweep k-1 has produced strips j-1..j+1, so the chain is
# one HBM read of (e, r) and one write of the result regardless of n.
# ---------------------------------------------------------------------------

# sweep-chain depth cap: each extra sweep costs one [4, by, nx]
# intermediate VMEM ring plus one r-ring slot; 6 keeps the 8192-wide
# f32 worst case under the 16M scoped-vmem budget. The nu1/nu2/nu_img
# chains are 1-3 sweeps; the 24-sweep coarsest chain (tiny grids, XLA
# does fine) falls back on purpose.
_JACOBI_MAX_SWEEPS = 6


def jacobi_strip_supported(ny: int, nx: int, dtype, n: int) -> bool:
    """Gate for ``fused_jacobi_sweeps`` at one MG level: f32/bf16
    storage, sublane-aligned strip heights, lane-aligned rows on a
    compiled TPU, and a bounded sweep depth (see _JACOBI_MAX_SWEEPS).
    A False here is a silent fall-back to the identical-result XLA
    sweep chain — an optimization gate, not a capability refusal."""
    if not HAVE_PALLAS:
        return False
    dt = jnp.dtype(dtype)
    if dt not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
        return False
    by = _BY_BF16 if dt == jnp.bfloat16 else _BY_F32
    if ny < by or ny % by:
        return False
    if _on_accel() and nx % 128:
        return False
    return 1 <= n <= _JACOBI_MAX_SWEEPS


def _jacobi_strips_kernel(by, nstr, ny, nx, nsw, omega, gs, from_zero,
                          store_dtype, r_ref, *rest):
    """Time-skewed n-sweep Jacobi chain over row strips of one member.

    Grid (L, nstr + nsw - 1): at step i, sweep level k (k = 1..nsw)
    computes strip j = i - (k - 1) — level k-1's strip j+1 is produced
    earlier in the SAME program (the Python level loop emits them in
    order), so every neighbor a sweep needs is resident by the time it
    runs. Input rings follow the megakernel's exactly-once DMA
    discipline; intermediate sweeps live in plain 4-slot VMEM rings
    (compute writes, no DMA); the final sweep writes the out block,
    whose index map revisits block j = max(i - nsw + 1, 0) so Pallas
    flushes it exactly once, after the level-nsw write. All arithmetic
    is f32 (accumulate tier); strips store at ``store_dtype`` — for
    bf16 legs that is the one rounding per sweep the XLA bf16 chain
    also pays, for f32 the chain is term-for-term the XLA expression
    (the ~1-ulp parity contract, tests/test_strip_smoother.py)."""
    if from_zero:
        e_ref = None
        out_ref = rest[0]
        sc = rest[1:]
    else:
        e_ref, out_ref = rest[0], rest[1]
        sc = rest[2:]
        ering, esems = sc[0], sc[1]
        sc = sc[2:]
    rslots = nsw + 2
    rring, rsems = sc[0], sc[1]
    lvls = sc[2:]                     # nsw-1 intermediate sweep rings

    l = pl.program_id(0)
    i = pl.program_id(1)
    f32 = jnp.float32

    def rdma(k):
        slot = _rem(k, rslots)
        return pltpu.make_async_copy(
            r_ref.at[l, pl.ds(k * by, by), :],
            rring.at[slot], rsems.at[slot])

    # r strip j is first consumed by sweep 1 at step j and last by
    # sweep nsw at step j + nsw - 1; nsw+2 slots keep the window plus
    # a one-step prefetch live
    @pl.when(i == 0)
    def _():
        rdma(0).start()

    @pl.when(i + 1 < nstr)
    def _():
        rdma(i + 1).start()

    @pl.when(i < nstr)
    def _():
        rdma(i).wait()

    if not from_zero:
        def edma(k):
            slot = _rem(k, 4)
            return pltpu.make_async_copy(
                e_ref.at[l, pl.ds(k * by, by), :],
                ering.at[slot], esems.at[slot])

        @pl.when(i == 0)
        def _():
            edma(0).start()
            if nstr > 1:
                edma(1).start()

        @pl.when(i + 2 < nstr)
        def _():
            edma(i + 2).start()

        @pl.when(i == 0)
        def _():
            edma(0).wait()
            if nstr > 1:
                edma(1).wait()

        @pl.when((i > 0) & (i + 1 < nstr))
        def _():
            edma(i + 1).wait()

    sx_lo, sx_hi, sy_lo, sy_hi = gs
    zero = jnp.zeros((), f32)

    def corr_inv(j):
        """The level's signed wall-diagonal row, from GLOBAL indices —
        the exact values (and groupings) of stencil._edge_ones /
        MultigridPreconditioner._inv_diag (2-D iota: Mosaic has no
        1-D iota)."""
        col = jax.lax.broadcasted_iota(jnp.int32, (by, nx), 1)
        row = jax.lax.broadcasted_iota(jnp.int32, (by, nx), 0) + j * by
        ex = jnp.where(col == 0, jnp.asarray(sx_lo, f32),
                       jnp.where(col == nx - 1, jnp.asarray(sx_hi, f32),
                                 zero))
        ey = jnp.where(row == 0, jnp.asarray(sy_lo, f32),
                       jnp.where(row == ny - 1, jnp.asarray(sy_hi, f32),
                                 zero))
        corr = (ey + ex) - 4.0
        return corr, 1.0 / corr

    def sweep(cur, top, bot, rv, corr, inv_d):
        """One damped-Jacobi update of one strip: zero-ghost 5-point
        Laplacian (term order of stencil.laplacian5_neumann/_bc) then
        the _smooth fori-body grouping e + omega*(r - lap)*inv_d."""
        ecol = jnp.concatenate([top, cur, bot], axis=0)   # [by+2, nx]
        z = jnp.zeros((by + 2, 1), f32)
        ew = jnp.concatenate([z, ecol, z], axis=1)        # [by+2, nx+2]
        lap = (ew[1:-1, 2:] + ew[1:-1, :-2] + ew[2:, 1:-1]
               + ew[:-2, 1:-1]) + cur * corr
        return cur + omega * (rv - lap) * inv_d

    for k in range(1, nsw + 1):
        j = i - (k - 1)

        @pl.when((j >= 0) & (j < nstr))
        def _(k=k, j=j):
            rv = rring[_rem(j, rslots)].astype(f32)
            corr, inv_d = corr_inv(j)
            if k == 1 and from_zero:
                # first sweep from e=0: e = omega r / d (the _smooth
                # from_zero shortcut, same grouping)
                new = omega * rv * inv_d
            else:
                if k == 1:
                    ring = ering
                else:
                    ring = lvls[k - 2]

                def src(m, rows):
                    # untaken wall branches may read an uninitialized
                    # ring slot — jnp.where only selects, never
                    # computes on the discarded operand
                    return ring[_rem(m, 4)][rows, :].astype(f32)

                cur = src(j, slice(None))
                top = jnp.where(j > 0, src(j + 3, slice(by - 1, by)),
                                zero)
                bot = jnp.where(j + 1 < nstr, src(j + 1, slice(0, 1)),
                                zero)
                new = sweep(cur, top, bot, rv, corr, inv_d)
            if k == nsw:
                out_ref[0] = new.astype(store_dtype)
            else:
                dst = lvls[k - 1]
                slot = _rem(j, 4)
                for s in range(4):
                    # static-index stores (dynamic leading-index READS
                    # are established idiom above; writes stay static)
                    @pl.when(slot == s)
                    def _(s=s):
                        dst[s] = new.astype(store_dtype)


def fused_jacobi_sweeps(e, r, omega, n, *, edge_signs=None,
                        from_zero=False, interpret=None):
    """n damped-Jacobi sweeps of the undivided 5-point zero-ghost
    Laplacian in ONE strip pipeline: one HBM read of (e, r), one write
    of the smoothed e — the fused form of the
    MultigridPreconditioner._smooth sweep chain (which pays ~2n+1
    field passes through XLA's fori_loop). Leading-dim agnostic like
    the megakernel: [..., Ny, Nx] operands flatten to [L, Ny, Nx], so
    one kernel serves the solo grid (L=1), fleet member batches (L=B)
    and forest window stacks. ``edge_signs`` = the BC table's
    (sx_lo, sx_hi, sy_lo, sy_hi) pressure-ghost signs; None =
    all-Neumann. ``from_zero``: first sweep is the e = omega r / d
    shortcut and ``e`` is ignored (may be None). Storage dtype follows
    ``r`` (f32 or bf16); accumulation is always f32."""
    lead = r.shape[:-2]
    L = _flatten_lead(lead)
    ny, nx = r.shape[-2:]
    store = jnp.dtype(r.dtype)
    by = _BY_BF16 if store == jnp.bfloat16 else _BY_F32
    nstr = ny // by
    nsw = int(n)
    if interpret is None:
        interpret = not _on_accel()
    gs = ((1.0, 1.0, 1.0, 1.0) if edge_signs is None
          else tuple(float(s) for s in edge_signs))
    ops = [r.reshape((L, ny, nx))]
    in_specs = [pl.BlockSpec(memory_space=pltpu.ANY)]
    scratch = []
    if not from_zero:
        ops.append(e.astype(store).reshape((L, ny, nx)))
        in_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))
        scratch += [pltpu.VMEM((4, by, nx), store),
                    pltpu.SemaphoreType.DMA((4,))]
    rslots = nsw + 2
    scratch += [pltpu.VMEM((rslots, by, nx), store),
                pltpu.SemaphoreType.DMA((rslots,))]
    for _ in range(nsw - 1):
        scratch.append(pltpu.VMEM((4, by, nx), store))
    kern = functools.partial(_jacobi_strips_kernel, by, nstr, ny, nx,
                             nsw, float(omega), gs, from_zero, store)
    out = pl.pallas_call(
        kern,
        grid=(L, nstr + nsw - 1),
        in_specs=in_specs,
        # block j revisited (unwritten) by the skew's fill steps, then
        # written by sweep nsw at step j + nsw - 1 and flushed on the
        # next index change — exactly once per strip
        out_specs=pl.BlockSpec(
            (1, by, nx),
            lambda l, i: (l, jnp.maximum(i - (nsw - 1), 0), 0)),
        out_shape=jax.ShapeDtypeStruct((L, ny, nx), store),
        scratch_shapes=scratch,
        interpret=interpret,
    )(*ops)
    return out.reshape(r.shape)


# ---------------------------------------------------------------------------
# sharded single-sweep halo strip (ISSUE 19): the strip-tier twin of
# shard_halo.overlap_jacobi_sweeps' per-sweep body. The shard_map
# wrapper ppermutes the 1-wide edge columns BEFORE dispatching (the
# PR-16 pattern), each sweep then runs as one strip pipeline over the
# local slab with the received columns riding a lane-padded ``aux``
# operand — the chain cannot time-skew across sweeps (each sweep needs
# fresh neighbor columns), so the sharded win is per-sweep fusion:
# one read of (e, r) and one write per sweep instead of the GSPMD
# chain's separate stencil/AXPY passes.
# ---------------------------------------------------------------------------

def _jacobi_halo_kernel(by, nstr, ny, nxl, omega, info_ref, r_ref,
                        e_ref, aux_ref, out_ref, ering, esems, rring,
                        rsems, aring, asems):
    """One damped-Jacobi sweep over the local x slab [ny, nxl]; aux
    [ny, 2*_GX] carries the received left edge column in [:, 0:1] and
    right in [:, 1:2] (zeros at mesh walls = the zero ghost). info
    (SMEM i32 [1, 2]) = (is_lo, is_hi) — the only per-shard values, so
    all shards share one executable; the wall-diagonal x indicators
    are masked by them exactly like the shard_map body's
    device-index masks."""
    i = pl.program_id(0)
    f32 = jnp.float32

    def edma(k):
        slot = _rem(k, 4)
        return pltpu.make_async_copy(
            e_ref.at[pl.ds(k * by, by), :], ering.at[slot],
            esems.at[slot])

    def rdma(k):
        slot = _rem(k, 2)
        return pltpu.make_async_copy(
            r_ref.at[pl.ds(k * by, by), :], rring.at[slot],
            rsems.at[slot])

    def adma(k):
        slot = _rem(k, 2)
        return pltpu.make_async_copy(
            aux_ref.at[pl.ds(k * by, by), :], aring.at[slot],
            asems.at[slot])

    @pl.when(i == 0)
    def _():
        edma(0).start()
        rdma(0).start()
        adma(0).start()
        if nstr > 1:
            edma(1).start()

    @pl.when(i + 2 < nstr)
    def _():
        edma(i + 2).start()

    @pl.when(i + 1 < nstr)
    def _():
        rdma(i + 1).start()
        adma(i + 1).start()

    @pl.when(i == 0)
    def _():
        edma(0).wait()
        if nstr > 1:
            edma(1).wait()

    @pl.when((i > 0) & (i + 1 < nstr))
    def _():
        edma(i + 1).wait()

    rdma(i).wait()
    adma(i).wait()

    cur = ering[_rem(i, 4)].astype(f32)                  # [by, nxl]
    prev_t = ering[_rem(i + 3, 4)][by - 1:by, :].astype(f32)
    next_h = ering[_rem(i + 1, 4)][0:1, :].astype(f32)
    zero = jnp.zeros((), f32)
    top = jnp.where(i > 0, prev_t, zero)
    bot = jnp.where(i + 1 < nstr, next_h, zero)
    a = aring[_rem(i, 2)].astype(f32)
    gl, gr = a[:, 0:1], a[:, 1:2]
    xp = jnp.concatenate([cur[:, 1:], gr], axis=1)
    xm = jnp.concatenate([gl, cur[:, :-1]], axis=1)
    ecol = jnp.concatenate([top, cur, bot], axis=0)      # [by+2, nxl]
    yp = ecol[2:, :]
    ym = ecol[:-2, :]
    is_lo = info_ref[0, 0]
    is_hi = info_ref[0, 1]
    col = jax.lax.broadcasted_iota(jnp.int32, (by, nxl), 1)
    row = jax.lax.broadcasted_iota(jnp.int32, (by, nxl), 0) + i * by
    one = jnp.ones((), f32)
    ix = jnp.where((col == 0) & (is_lo > 0), one,
                   jnp.where((col == nxl - 1) & (is_hi > 0), one, zero))
    iy = jnp.where(row == 0, one,
                   jnp.where(row == ny - 1, one, zero))
    corr = (iy + ix) - 4.0
    inv_d = 1.0 / corr
    rv = rring[_rem(i, 2)].astype(f32)
    lap = xp + xm + yp + ym + cur * corr
    out_ref[...] = (cur + omega * (rv - lap) * inv_d).astype(
        out_ref.dtype)


def fused_jacobi_halo_sweep(e, r, aux, info, omega, *, interpret=None):
    """One sharded-slab Jacobi sweep (shard_map body helper): e, r
    [ny, nxl] local slabs; aux [ny, 2*_GX] received edge columns;
    info [1, 2] i32 (is_lo, is_hi). Neumann-only (the overlapped
    sharded smoother is free-slip-specific, see
    MultigridPreconditioner.__init__)."""
    ny, nxl = e.shape
    store = jnp.dtype(e.dtype)
    by = _BY_BF16 if store == jnp.bfloat16 else _BY_F32
    nstr = ny // by
    if interpret is None:
        interpret = not _on_accel()
    kern = functools.partial(_jacobi_halo_kernel, by, nstr, ny, nxl,
                             float(omega))
    return pl.pallas_call(
        kern,
        grid=(nstr,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.ANY),
                  pl.BlockSpec(memory_space=pltpu.ANY),
                  pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec((by, nxl), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((ny, nxl), store),
        scratch_shapes=[
            pltpu.VMEM((4, by, nxl), store),
            pltpu.SemaphoreType.DMA((4,)),
            pltpu.VMEM((2, by, nxl), store),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.VMEM((2, by, 2 * _GX), aux.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(info, r, e, aux)


# ---------------------------------------------------------------------------
# fused forest block-Jacobi update (ISSUE 19): one sweep of the
# composite smoother is e + P_inv (r - A e); the composite A-apply
# stays XLA (it walks the level maps), but the smoother's OWN traffic
# — residual subtract, the [N, bs^2] x [bs^2, bs^2] P_inv GEMM and the
# update add, three separate XLA passes over the block stack — fuses
# to one read of (e, r, Ae) and one write. Honest scope note: the
# forest form is one-fused-pass-PER-SWEEP; only the uniform chain
# above gets n-sweeps-one-pass.
# ---------------------------------------------------------------------------

def block_update_supported(dtype) -> bool:
    """f32 block stacks only (Mosaic has no f64; the f64 forest
    validation path stays on the XLA composition)."""
    return HAVE_PALLAS and jnp.dtype(dtype) == jnp.float32


def _block_jacobi_kernel(p_ref, e_ref, r_ref, lap_ref, out_ref):
    d = r_ref[...] - lap_ref[...]                   # [cb, bs*bs]
    z = jax.lax.dot_general(
        d, p_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)         # d @ p_inv.T
    out_ref[...] = e_ref[...] + z


def fused_block_jacobi_update(e, r, lap, p_inv, *, interpret=None):
    """e + P_inv (r - lap) over a [N, bs, bs] block stack in one fused
    pass (the apply_block_precond_blocks composition, term-for-term:
    (r - lap).reshape(N, bs^2) @ p_inv.T + e). The [N, bs, bs] ->
    [N, bs^2] reshapes happen OUTSIDE the kernel (XLA bitcasts), so
    the kernel body is pure 2-D MXU work riding the standard chunked
    BlockSpec pipeline."""
    n, bs, _ = e.shape
    m = bs * bs
    cb = _pick(n, (64, 32, 16, 8, 4, 2, 1))
    if interpret is None:
        interpret = not _on_accel()
    out = pl.pallas_call(
        _block_jacobi_kernel,
        grid=(n // cb,),
        in_specs=[
            pl.BlockSpec((m, m), lambda i: (0, 0)),     # replicated
            pl.BlockSpec((cb, m), lambda i: (i, 0)),
            pl.BlockSpec((cb, m), lambda i: (i, 0)),
            pl.BlockSpec((cb, m), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((cb, m), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, m), e.dtype),
        interpret=interpret,
    )(p_inv, e.reshape(n, m), r.reshape(n, m), lap.reshape(n, m))
    return out.reshape(n, bs, bs)
