"""Surface force/power diagnostics: the reference's KernelComputeForces
(`/root/reference/main.cpp:5573-5746`) + ComputeSurfaceNormals
(`3774-3830`) as one gather kernel.

Surface cells are detected from the combined chi/sdf gradients (the
delta-function weight D); each surface cell probes up to 4 cells along
its outward normal to find fluid (chi < 0.01), evaluates one-sided
5th-order velocity derivatives there, Taylor-corrects them back to the
surface cell, and accumulates traction (viscous nu/h * grad u . n_chi +
pressure * n_chi), torque, thrust/drag split along the body velocity,
lift, and output/deformation power — the reference's 19-component
per-shape reduction (main.cpp:7188-7284).

`surface_forces_block` is the single-tile core over ghost-padded labs,
with the reference's probe/stencil lab-edge gates; the AMR path vmaps it
over forest blocks with G=4 (the reference's own lab extent, including
its stencil-order degradation at lab edges — see `surface_forces_blocks`)
and the uniform wrapper `surface_forces` calls it as one big tile with
G=10 ghosts, so derivative order degrades only near the *domain*
boundary — a documented improvement over the reference's per-8-cell-block
artifacts. Surface membership for overlapping bodies is cell-granular
(own-sdf band) instead of the reference's block-granular choice — the
second documented deviation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_EPS = 2.220446049250313e-16

# 5th-order one-sided first-derivative coefficients (main.cpp:5579-5584)
_C = (-137.0 / 60.0, 5.0, -5.0, 10.0 / 3.0, -5.0 / 4.0, 1.0 / 5.0)

FORCE_KEYS = (
    "perimeter", "circulation", "forcex", "forcey", "forcex_P", "forcey_P",
    "forcex_V", "forcey_V", "torque", "torque_P", "torque_V",
    "drag", "thrust", "lift", "Pout", "PoutBnd", "defPower", "defPowerBnd",
    "PoutNew",
)


def surface_forces_block(velp, pres, chip, sdfp, udef, own_sdf, xc, yc,
                         com, uvw, nu, h, G):
    """Force reduction over ONE ghost-padded tile.

    velp: [2, L, L] velocity lab; chip/sdfp: [L, L] combined chi/sdf
    labs; pres/own_sdf: [ny, nx] interiors; udef: [2, ny, nx] the
    shape's own deformation velocity; xc/yc: [ny, nx] cell centers;
    h scalar (this tile's spacing). Returns the 18 partial sums plus
    PoutNew assembled by the caller after summing.
    """
    ny, nx = pres.shape
    iy, ix = jnp.meshgrid(jnp.arange(ny), jnp.arange(nx), indexing="ij")

    def at_s(lab, yy, xx):
        return lab[yy + G, xx + G]

    def at_v(yy, xx):
        return velp[:, yy + G, xx + G]

    # --- surface detection (ComputeSurfaceNormals, main.cpp:3786-3810) ---
    grad_hx = at_s(chip, iy, ix + 1) - at_s(chip, iy, ix - 1)
    grad_hy = at_s(chip, iy + 1, ix) - at_s(chip, iy - 1, ix)
    i2h = 0.5 / h
    grad_ux = i2h * (at_s(sdfp, iy, ix + 1) - at_s(sdfp, iy, ix - 1))
    grad_uy = i2h * (at_s(sdfp, iy + 1, ix) - at_s(sdfp, iy - 1, ix))
    grad_usq = grad_ux * grad_ux + grad_uy * grad_uy + _EPS
    d_w = (0.5 * h) * (grad_hx * grad_ux + grad_hy * grad_uy) / grad_usq
    norm_x = -d_w * grad_ux
    norm_y = -d_w * grad_uy
    mask = ((grad_hx * grad_hx + grad_hy * grad_hy) >= 1e-12) \
        & (jnp.abs(d_w) > _EPS) & (own_sdf > -4.0 * h)

    nmag = jnp.sqrt(norm_x * norm_x + norm_y * norm_y) + _EPS
    dx_u = norm_x / nmag
    dy_u = norm_y / nmag

    # --- probe walk along the normal to fluid (main.cpp:5619-5632):
    # a step is taken only while its +-1 neighborhood stays inside the
    # lab (the reference's inrange gate) ---
    px_i = ix
    py_i = iy
    done = jnp.zeros_like(mask)
    for k in range(5):
        cx = ix + jnp.rint(k * dx_u).astype(jnp.int32)
        cy = iy + jnp.rint(k * dy_u).astype(jnp.int32)
        inb = (cx - 1 >= -G) & (cx + 1 <= nx + G - 1) \
            & (cy - 1 >= -G) & (cy + 1 <= ny + G - 1)
        take = inb & ~done
        px_i = jnp.where(take, cx, px_i)
        py_i = jnp.where(take, cy, py_i)
        done = done | (take & (at_s(chip, cy, cx) < 0.01))

    sx = jnp.where(norm_x > 0, 1, -1)
    sy = jnp.where(norm_y > 0, 1, -1)

    def deriv_1d(axis):
        """One-sided first derivative at the probe, 5th/2nd/1st order by
        distance to the lab edge (main.cpp:5640-5696), per component."""
        if axis == 0:
            off = lambda k: at_v(py_i, px_i + k * sx)  # noqa: E731
            pos, s_, n_ = px_i, sx, nx
        else:
            off = lambda k: at_v(py_i + k * sy, px_i)  # noqa: E731
            pos, s_, n_ = py_i, sy, ny
        in5 = (pos + 5 * s_ >= -G) & (pos + 5 * s_ <= n_ + G - 1)
        in2 = (pos + 2 * s_ >= -G) & (pos + 2 * s_ <= n_ + G - 1)
        d5 = sum(c * off(k) for k, c in enumerate(_C))
        d2 = -1.5 * off(0) + 2.0 * off(1) - 0.5 * off(2)
        d1 = off(1) - off(0)
        return s_ * jnp.where(in5, d5, jnp.where(in2, d2, d1))

    dveldx = deriv_1d(0)
    dveldy = deriv_1d(1)
    dveldx2 = at_v(py_i, px_i - 1) - 2.0 * at_v(py_i, px_i) \
        + at_v(py_i, px_i + 1)
    dveldy2 = at_v(py_i - 1, px_i) - 2.0 * at_v(py_i, px_i) \
        + at_v(py_i + 1, px_i)

    def d2nd(kx, ky):
        return (-1.5 * at_v(py_i, px_i + kx * sx)
                + 2.0 * at_v(py_i + sy, px_i + kx * sx)
                - 0.5 * at_v(py_i + 2 * sy, px_i + kx * sx))
    dveldxdy = (sx * sy) * (-0.5 * d2nd(2, 0) + 2.0 * d2nd(1, 0)
                            - 1.5 * d2nd(0, 0))

    tx = (ix - px_i)
    ty = (iy - py_i)
    du_dx = dveldx[0] + dveldx2[0] * tx + dveldxdy[0] * ty
    dv_dx = dveldx[1] + dveldx2[1] * tx + dveldxdy[1] * ty
    du_dy = dveldy[0] + dveldy2[0] * ty + dveldxdy[0] * tx
    dv_dy = dveldy[1] + dveldy2[1] * ty + dveldxdy[1] * tx

    # --- traction and reductions (main.cpp:5700-5745) ---
    nuoh = nu / h
    fxv = nuoh * (du_dx * norm_x + du_dy * norm_y)
    fyv = nuoh * (dv_dx * norm_x + dv_dy * norm_y)
    fxp = -pres * norm_x
    fyp = -pres * norm_y
    fxt = fxv + fxp
    fyt = fyv + fyp

    u_here = at_v(iy, ix)[0]
    v_here = at_v(iy, ix)[1]
    vel_norm = jnp.sqrt(uvw[0] ** 2 + uvw[1] ** 2)
    unit_x = jnp.where(vel_norm > 0, uvw[0] / (vel_norm + _EPS), 0.0)
    unit_y = jnp.where(vel_norm > 0, uvw[1] / (vel_norm + _EPS), 0.0)

    rx = xc - com[0]
    ry = yc - com[1]

    force_par = fxt * unit_x + fyt * unit_y
    force_perp = fxt * unit_y - fyt * unit_x
    pow_out = fxt * u_here + fyt * v_here
    pow_def = fxt * udef[0] + fyt * udef[1]

    def red(q):
        return jnp.sum(jnp.where(mask, q, 0.0))

    return {
        "perimeter": red(nmag - _EPS),
        "circulation": red(norm_x * v_here - norm_y * u_here),
        "forcex": red(fxt),
        "forcey": red(fyt),
        "forcex_P": red(fxp),
        "forcey_P": red(fyp),
        "forcex_V": red(fxv),
        "forcey_V": red(fyv),
        "torque": red(rx * fyt - ry * fxt),
        "torque_P": red(rx * fyp - ry * fxp),
        "torque_V": red(rx * fyv - ry * fxv),
        "thrust": red(0.5 * (force_par + jnp.abs(force_par))),
        "drag": -red(0.5 * (force_par - jnp.abs(force_par))),
        "lift": red(force_perp),
        "Pout": red(pow_out),
        "PoutBnd": red(jnp.minimum(0.0, pow_out)),
        "defPower": red(pow_def),
        "defPowerBnd": red(jnp.minimum(0.0, pow_def)),
    }


def _finish(sums, uvw):
    out = dict(sums)
    out["PoutNew"] = out["forcex"] * uvw[0] + out["forcey"] * uvw[1]
    return out


def surface_forces_blocks(velp, pres, chip, sdfp, udef, own_sdf, xc, yc,
                          com, uvw, nu, h, G=4):
    """AMR path: vmap the core over [N] forest blocks (velp [N, 2, L, L],
    labs [N, L, L], interiors [N, ...], h [N]) and sum the partials."""
    core = functools.partial(surface_forces_block, G=G)
    per_block = jax.vmap(
        core, in_axes=(0, 0, 0, 0, 0, 0, 0, 0, None, None, None, 0),
    )(velp, pres, chip, sdfp, udef, own_sdf, xc, yc, com, uvw, nu, h)
    sums = {k: jnp.sum(v) for k, v in per_block.items()}
    return _finish(sums, uvw)


def surface_forces(vel, pres, chi, sdf, udef, own_sdf, com, uvw, nu, h):
    """Uniform-grid wrapper: one big tile with G=10 ghosts (edge-pad
    scalars, free-slip mirror velocity — VectorLab, main.cpp:3127).
    Fields are full-grid: vel/udef [2, Ny, Nx], rest [Ny, Nx]."""
    ny, nx = chi.shape
    G = 10  # covers probe walk (<=4) + 5-cell stencils away from walls
    chip = jnp.pad(chi, G, mode="edge")
    sdfp = jnp.pad(sdf, G, mode="edge")
    velp = jnp.pad(vel, ((0, 0), (G, G), (G, G)), mode="edge")
    sgnx = jnp.ones(nx + 2 * G, vel.dtype).at[:G].set(-1).at[nx + G:].set(-1)
    sgny = jnp.ones(ny + 2 * G, vel.dtype).at[:G].set(-1).at[ny + G:].set(-1)
    velp = jnp.stack([velp[0] * sgnx[None, :], velp[1] * sgny[:, None]])

    iy, ix = jnp.meshgrid(jnp.arange(ny), jnp.arange(nx), indexing="ij")
    xc = (ix + 0.5) * h
    yc = (iy + 0.5) * h
    sums = surface_forces_block(velp, pres, chip, sdfp, udef, own_sdf,
                                xc, yc, com, uvw, nu, h, G)
    return _finish(sums, uvw)
