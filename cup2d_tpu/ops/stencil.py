"""Stencil operator library — the physics kernels, written once over
ghost-padded arrays and shared by the uniform-grid and AMR block paths.

TPU-native re-design of the reference's per-cell OpenMP loops
(`/root/reference/main.cpp:5441-5572` KernelAdvectDiffuse,
`main.cpp:3343-3366` KernelVorticity, `main.cpp:6105-6287` pressure RHS,
`main.cpp:6021-6104` pressure correction): every kernel here is a pure
function over whole arrays — shifts instead of indexed reads — so XLA fuses
each operator into a handful of elementwise/reduce HLOs over all cells (or
all blocks, when vmapped by the AMR path) at once.

Array convention: fields are padded with `g` ghost cells on each side of the
last two axes, i.e. shape `[..., Ny + 2g, Nx + 2g]`; kernels return interior
arrays `[..., Ny, Nx]`. Axis -2 is y, axis -1 is x. Velocity labs carry a
leading component axis of size 2 (u, v). "Undivided" differences (no 1/h)
are used where the reference uses them, so scalings match exactly.

This library is also the fused Pallas tier's semantic reference: the
megakernel (ops/pallas_kernels.py) reuses these op bodies over VMEM
strips and, since ISSUE 16, synthesizes every bc.py ghost kind in-VMEM
from the same affine edge/inner-line combinations the XLA chain paints
via pad_vector_bc — the pad -> advect_diffuse_rhs -> heun_substage
composition below is what the kernel equivalence tests pin against.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def dt_from_umax(umax, h, nu, cfl):
    """CFL/diffusive timestep (main.cpp:6579-6595):
    min(0.25 h^2/(nu + 0.25 h umax), cfl h/(umax + 1e-8)). The ONE
    definition shared by the uniform and forest paths, on device and
    from host-pulled umax — cached next-dt and fallback recomputation
    must agree bit-for-bit or a checkpoint restart forks the
    trajectory."""
    dt_diff = 0.25 * h * h / (nu + 0.25 * h * umax)
    return jnp.minimum(dt_diff, cfl * h / (umax + 1e-8))


def interior(lab: jnp.ndarray, g: int) -> jnp.ndarray:
    """Strip g ghost layers from the last two axes."""
    if g == 0:
        return lab
    return lab[..., g:-g, g:-g]


def shift(lab: jnp.ndarray, g: int, dy: int, dx: int) -> jnp.ndarray:
    """Interior view displaced by (dy, dx); |dy|,|dx| <= g."""
    ny = lab.shape[-2] - 2 * g
    nx = lab.shape[-1] - 2 * g
    return lab[..., g + dy : g + dy + ny, g + dx : g + dx + nx]


# ---------------------------------------------------------------------------
# WENO5 (reference main.cpp:162-208) — vectorized; the `pow(b+e, 2)`
# smoothness weighting and e=1e-6 are kept bit-for-bit.
# ---------------------------------------------------------------------------

_WENO_EPS = 1e-6
# guard for the fast-weights denominator: legitimate denominators stay
# >= ~1e-21 (analysis in _weno5_weights); only total ratio-underflow
# (b_max/b_min beyond ~1e19, i.e. an already-blown-up field) trips it
_WENO_TINY = 1e-35


def _weno5_weights(b1, b2, b3, g1, g2, g3):
    # max-normalized single-divide form (2 divides instead of the
    # textbook 4): divide every smoothness indicator by the largest,
    # so the ratios r_i live in (0, 1] and the cross products
    # n_i = g_i * prod_{j != i} r_j^2 CANNOT overflow — which was the
    # measured objection (f32 blow-up at b ~ 2e9) that kept round 2 on
    # the ratio form. The weights are scale-invariant in b, so this is
    # the same algebra, just evaluated at a safe scale. Advection is
    # VPU-divide-bound at 8192^2 (10x above the HBM roofline,
    # BASELINE.md r3 trace), so trading 2 divides for ~8 multiplies is
    # the single biggest lever on the step.
    #
    # Degenerate tail: if b_max/b_min exceeds ~1e19 every cross
    # product underflows to 0 (TPU flushes denormals); the 0/0 is
    # caught by the _WENO_TINY select, which falls back to the optimal
    # (central) weights — finite, convex, and irrelevant in practice
    # because a field that rough has long since collapsed dt.
    bmax = jnp.maximum(jnp.maximum(b1, b2), b3) + _WENO_EPS
    if bmax.dtype == jnp.float32:
        # the weights are EXACTLY scale-invariant in the normalizer
        # (any common factor in the r_i cancels from n_i/den), so the
        # 1/b_max divide needs no accuracy at all: a bit-trick
        # approximate reciprocal (~2% error, one int subtract) replaces
        # a VPU divide per reconstruction. f64 (CPU validation) keeps
        # the exact divide — performance is irrelevant there and the
        # magic constant is format-specific.
        m = jax.lax.bitcast_convert_type(
            jnp.int32(0x7EF311C3)
            - jax.lax.bitcast_convert_type(bmax, jnp.int32),
            jnp.float32)
    else:
        m = 1.0 / bmax
    r1 = (b1 + _WENO_EPS) * m
    r2 = (b2 + _WENO_EPS) * m
    r3 = (b3 + _WENO_EPS) * m
    s1, s2, s3 = r1 * r1, r2 * r2, r3 * r3
    n1 = g1 * (s2 * s3)
    n2 = g2 * (s1 * s3)
    n3 = g3 * (s1 * s2)
    den = (n1 + n3) + n2
    ok = den > _WENO_TINY
    aux = 1.0 / jnp.where(ok, den, 1.0)
    w1 = jnp.where(ok, n1 * aux, g1)
    w2 = jnp.where(ok, n2 * aux, g2)
    w3 = jnp.where(ok, n3 * aux, g3)
    return w1, w2, w3


def _weno5_weights_ref(b1, b2, b3, g1, g2, g3):
    # the textbook ratio form (bit-matches the reference's
    # main.cpp:162-208 evaluation order) — kept for the bit-comparison
    # tests that pin the fast form against it
    w1 = g1 / (b1 + _WENO_EPS) ** 2
    w2 = g2 / (b2 + _WENO_EPS) ** 2
    w3 = g3 / (b3 + _WENO_EPS) ** 2
    aux = 1.0 / ((w1 + w3) + w2)
    return w1 * aux, w2 * aux, w3 * aux


def _smoothness(um2, um1, u, up1, up2):
    b1 = 13.0 / 12.0 * ((um2 + u) - 2 * um1) ** 2 + 0.25 * ((um2 + 3 * u) - 4 * um1) ** 2
    b2 = 13.0 / 12.0 * ((um1 + up1) - 2 * u) ** 2 + 0.25 * (um1 - up1) ** 2
    b3 = 13.0 / 12.0 * ((u + up2) - 2 * up1) ** 2 + 0.25 * ((3 * u + up2) - 4 * up1) ** 2
    return b1, b2, b3


def weno5_plus(um2, um1, u, up1, up2):
    """Upwind-biased flux reconstruction, wind > 0 (main.cpp:162-180)."""
    b1, b2, b3 = _smoothness(um2, um1, u, up1, up2)
    w1, w2, w3 = _weno5_weights(b1, b2, b3, 0.1, 0.6, 0.3)
    f1 = (11.0 / 6.0) * u + ((1.0 / 3.0) * um2 - (7.0 / 6.0) * um1)
    f2 = (5.0 / 6.0) * u + ((-1.0 / 6.0) * um1 + (1.0 / 3.0) * up1)
    f3 = (1.0 / 3.0) * u + ((5.0 / 6.0) * up1 - (1.0 / 6.0) * up2)
    return (w1 * f1 + w3 * f3) + w2 * f2


def weno5_minus(um2, um1, u, up1, up2):
    """Upwind-biased flux reconstruction, wind < 0 (main.cpp:181-201)."""
    b1, b2, b3 = _smoothness(um2, um1, u, up1, up2)
    w1, w2, w3 = _weno5_weights(b1, b2, b3, 0.3, 0.6, 0.1)
    f1 = (1.0 / 3.0) * u + ((-1.0 / 6.0) * um2 + (5.0 / 6.0) * um1)
    f2 = (5.0 / 6.0) * u + ((1.0 / 3.0) * um1 - (1.0 / 6.0) * up1)
    f3 = (11.0 / 6.0) * u + ((-7.0 / 6.0) * up1 + (1.0 / 3.0) * up2)
    return (w1 * f1 + w3 * f3) + w2 * f2


def weno_derivative(wind, um3, um2, um1, u, up1, up2, up3):
    """Undivided upwind WENO5 derivative (main.cpp:202-208): flux difference
    of the reconstruction chosen by the local wind sign.

    Exploits the exact mirror identity
    ``weno5_minus(a,b,c,d,e) == weno5_plus(e,d,c,b,a)`` (the smoothness
    indicators, ideal weights and candidate stencils all pair up under
    argument reversal, commutative adds only): selecting the five
    STENCIL ARGUMENTS by wind sign up front needs 10 one-cycle selects
    and TWO reconstructions, where the textbook both-branches-then-
    select form needs FOUR. Bit-identical to the latter (asserted in
    tests/test_fused_bc.py::test_weno_mirror_identity_bit_exact);
    advection is the VPU-bound hot spot of the whole step, so halving
    its reconstruction count is worth the obfuscation."""
    pos = wind > 0

    def sel(a, b):
        return jnp.where(pos, a, b)

    # wind>0: weno5_plus at i      | wind<0: weno5_minus at i (mirrored)
    t1 = weno5_plus(sel(um2, up3), sel(um1, up2), sel(u, up1),
                    sel(up1, u), sel(up2, um1))
    # wind>0: weno5_plus at i-1    | wind<0: weno5_minus at i-1 (mirrored)
    t2 = weno5_plus(sel(um3, up2), sel(um2, up1), sel(um1, u),
                    sel(u, um1), sel(up1, um2))
    return t1 - t2


# ---------------------------------------------------------------------------
# Advection–diffusion RHS (KernelAdvectDiffuse, main.cpp:5441-5503)
# ---------------------------------------------------------------------------

def advect_diffuse_rhs(vlab: jnp.ndarray, g: int, h, nu, dt):
    """RHS in the reference's block scaling: h^2 * du/dt * dt, i.e.
    ``afac*(u·∇)u + dfac*lap(u)`` with afac = -dt*h, dfac = nu*dt and
    *undivided* differences — exactly what the reference writes into tmpV;
    the integrator divides by h^2 (main.cpp:6619-6626).

    vlab: [..., 2, Ny+2g, Nx+2g] velocity with ghosts, g >= 3.
    Returns [..., 2, Ny, Nx].
    """
    return advect_diffuse_core(vlab, g, -dt * h, nu * dt)


def advect_diffuse_core(vlab: jnp.ndarray, g: int, afac, dfac):
    """Same, with the scale factors precomputed — shared verbatim by the
    XLA path above and the Pallas kernel (ops/pallas_kernels.py), so the
    two can never drift numerically.

    Deliberately the per-cell form: evaluating each reconstruction once
    on a one-cell-extended range and differencing by shift halves the
    arithmetic on paper but measured 26% SLOWER at 8192^2 — the
    odd-width (n+1) intermediates misalign XLA's (8, 128) lane tiling
    and the relayouts cost more than the saved flops."""
    assert g >= 3
    u = shift(vlab, g, 0, 0)
    wind_u = u[..., 0:1, :, :]  # u component drives x-derivatives
    wind_v = u[..., 1:2, :, :]  # v component drives y-derivatives

    dx = weno_derivative(
        wind_u,
        shift(vlab, g, 0, -3), shift(vlab, g, 0, -2), shift(vlab, g, 0, -1),
        u,
        shift(vlab, g, 0, 1), shift(vlab, g, 0, 2), shift(vlab, g, 0, 3),
    )
    dy = weno_derivative(
        wind_v,
        shift(vlab, g, -3, 0), shift(vlab, g, -2, 0), shift(vlab, g, -1, 0),
        u,
        shift(vlab, g, 1, 0), shift(vlab, g, 2, 0), shift(vlab, g, 3, 0),
    )
    lap = (
        shift(vlab, g, 0, 1) + shift(vlab, g, 0, -1)
        + shift(vlab, g, 1, 0) + shift(vlab, g, -1, 0)
        - 4.0 * u
    )
    return afac * (wind_u * dx + wind_v * dy) + dfac * lap


def heun_substage(vold, cfac, rhs, ih2):
    """One Heun stage update ``vold + cfac * rhs * ih2`` (rhs in the
    reference's undivided h^2-scaled form, ih2 = 1/h^2). Trivial on
    purpose: the expression lives HERE so the XLA drivers (uniform,
    fleet, amr) and the fused Pallas megakernel all evaluate the same
    association order — the f32 equivalence goldens pin it."""
    return vold + cfac * rhs * ih2


# ---------------------------------------------------------------------------
# Fused-BC forms of the LINEAR operators (uniform path).
#
# jnp.pad(mode="edge") lowers to concatenates of edge strips that XLA
# materializes (measured ~19 ms/step at 8192^2, the "halo-pad" slice of
# the round-3 trace). For a linear stencil the physical BC is
# equivalently a zero-ghost shift plus a rank-1 edge correction — the
# Neumann ghost contributes the edge cell once per adjacent wall, the
# free-slip ghost contributes +/- the edge cell — and zero padding is a
# plain `pad` HLO that fuses into the consumer. These forms take the
# UNPADDED field and produce bit-close (summation-order differs only in
# wall cells) results to laplacian5(pad_scalar(p, 1)) etc.
# ---------------------------------------------------------------------------

def _edge_ones(n, dtype, lo=1.0, hi=1.0):
    # iota + compares, NOT .at[].set on zeros: the latter bakes an HLO
    # constant that is DMA-staged from HBM on every use inside loop
    # bodies (~4.7 ms/step of f32[8192] copy-starts in the round-4
    # trace); an iota is generated in-register for free
    i = jnp.arange(n)
    z = jnp.zeros((), dtype)
    return jnp.where(i == 0, jnp.asarray(lo, dtype),
                     jnp.where(i == n - 1, jnp.asarray(hi, dtype), z))


def _zshift(p: jnp.ndarray, dy: int, dx: int,
            spmd_safe: bool = False) -> jnp.ndarray:
    """Shift with zero ghosts on an unpadded array (|dy|,|dx| <= 1).

    Default form: pad(0)+slice — XLA folds the resulting negative-pad
    into the consumer fusion (fastest single-device form, measured
    3.8 vs 5.8 ms for the 8192^2 Laplacian against the edge-pad
    original). This image's GSPMD partitioner MISCOMPILES that
    negative-pad pattern when the sliced axis is sharded (compositions
    return garbage at small shard widths — caught by the sharded-
    equality test); ``spmd_safe=True`` switches to slice-then-pad,
    which the partitioner handles exactly (to 1 ulp) at a ~2x cost the
    sharded paths accept."""
    ny, nx = p.shape[-2], p.shape[-1]
    if spmd_safe:
        ys = slice(max(dy, 0), ny + min(dy, 0))
        xs = slice(max(dx, 0), nx + min(dx, 0))
        q = p[..., ys, xs]
        pad = [(0, 0)] * (p.ndim - 2) + [(max(-dy, 0), max(dy, 0)),
                                         (max(-dx, 0), max(dx, 0))]
        return jnp.pad(q, pad)
    pad = [(0, 0)] * (p.ndim - 2) + [(max(-dy, 0), max(dy, 0)),
                                     (max(-dx, 0), max(dx, 0))]
    zp = jnp.pad(p, pad)
    oy, ox = max(dy, 0), max(dx, 0)
    return zp[..., oy:oy + ny, ox:ox + nx]


def laplacian5_neumann(p: jnp.ndarray, spmd_safe: bool = False) -> jnp.ndarray:
    """Undivided 5-point Laplacian with zero-Neumann walls, UNPADDED
    input [..., Ny, Nx] — fused-BC equivalent of
    ``laplacian5(pad_scalar(p, 1), 1)``."""
    ny, nx = p.shape[-2], p.shape[-1]
    ex = _edge_ones(nx, p.dtype)
    ey = _edge_ones(ny, p.dtype)
    zs = lambda dy, dx: _zshift(p, dy, dx, spmd_safe)
    return (
        zs(0, 1) + zs(0, -1) + zs(1, 0) + zs(-1, 0)
        + p * ((ey[:, None] + ex[None, :]) - 4.0)
    )


def divergence_freeslip(v: jnp.ndarray, spmd_safe: bool = False) -> jnp.ndarray:
    """Undivided central divergence with free-slip mirror walls,
    UNPADDED input [..., 2, Ny, Nx] — fused-BC equivalent of
    ``divergence(pad_vector(v, 1), 1)``. The mirrored normal component
    (ghost = -edge) adds +u at the low wall and -u at the high wall."""
    u = v[..., 0, :, :]
    w = v[..., 1, :, :]
    ny, nx = u.shape[-2], u.shape[-1]
    gx = _edge_ones(nx, v.dtype, lo=1.0, hi=-1.0)
    gy = _edge_ones(ny, v.dtype, lo=1.0, hi=-1.0)
    return (
        _zshift(u, 0, 1, spmd_safe) - _zshift(u, 0, -1, spmd_safe)
        + u * gx[None, :]
        + _zshift(w, 1, 0, spmd_safe) - _zshift(w, -1, 0, spmd_safe)
        + w * gy[:, None]
    )


def divergence_rhs_fused(v, udef, chi, h, dt, spmd_safe: bool = False):
    """Fused-BC pressure RHS: (h/2dt)[div(u*) - chi div(u_def)], all
    inputs unpadded — replaces divergence_rhs(pad_vector(v,1), ...)."""
    fac = 0.5 * h / dt
    return (fac * divergence_freeslip(v, spmd_safe)
            - (fac * chi) * divergence_freeslip(udef, spmd_safe))


def pressure_gradient_update_fused(p: jnp.ndarray, h, dt,
                                   spmd_safe: bool = False) -> jnp.ndarray:
    """Fused-BC equivalent of
    ``pressure_gradient_update(pad_scalar(p, 1), 1, h, dt)``: undivided
    central gradient with Neumann ghosts (ghost = edge ⇒ the one-sided
    difference p[1]-p[0] at the low wall, p[n-1]-p[n-2] at the high)."""
    ny, nx = p.shape[-2], p.shape[-1]
    gx = _edge_ones(nx, p.dtype, lo=-1.0, hi=1.0)
    gy = _edge_ones(ny, p.dtype, lo=-1.0, hi=1.0)
    pfac = -0.5 * dt * h
    zs = lambda dy, dx: _zshift(p, dy, dx, spmd_safe)
    dpx = (zs(0, 1) - zs(0, -1)) + p * gx[None, :]
    dpy = (zs(1, 0) - zs(-1, 0)) + p * gy[:, None]
    return pfac * jnp.stack([dpx, dpy], axis=-3)


# ---------------------------------------------------------------------------
# Per-face generalizations of the fused-BC forms (ISSUE 12, bc.py).
# Same zero-ghost-shift + rank-1 edge-correction construction; only the
# edge coefficients become per-face parameters. The legacy functions
# above are UNTOUCHED and remain the free-slip/Neumann fast path — the
# default BCTable dispatches to them verbatim (bit-identity contract).
# Signs/coefficients are derived once per table by bc.pressure_signs /
# bc.divergence_coeffs; they are Python floats, so each table traces
# its own executable (tables are static per driver).
#
# Periodic directions (ISSUE 20): the per-axis flags ``px`` / ``py``
# (bc.periodic_axes) switch the shifts ALONG that axis to wrap (roll)
# shifts, and the corresponding edge signs/coefficients come in as 0
# (bc.pressure_signs / bc.divergence_coeffs) — no edge correction, the
# wrapped interior cell IS the neighbor. Every shift here is
# axis-aligned, so the wrap/zero choice is per shift, not per array.
# ---------------------------------------------------------------------------

def _shift_bc(p: jnp.ndarray, dy: int, dx: int, px: bool, py: bool,
              spmd_safe: bool = False) -> jnp.ndarray:
    """Axis-aligned unit shift honoring periodic axes: wrap (roll)
    along a periodic axis, zero-ghost (_zshift) otherwise. roll lowers
    to two slices + a concatenate — GSPMD shards it correctly (it is
    the same pattern as the spmd_safe slice-then-pad form), so no
    sharded variant is needed."""
    if (dx != 0 and px) or (dy != 0 and py):
        return jnp.roll(p, shift=(-dy, -dx), axis=(-2, -1))
    return _zshift(p, dy, dx, spmd_safe)


def laplacian5_bc(p: jnp.ndarray, sx_lo: float, sx_hi: float,
                  sy_lo: float, sy_hi: float,
                  spmd_safe: bool = False,
                  px: bool = False, py: bool = False) -> jnp.ndarray:
    """Undivided 5-point Laplacian with per-face pressure-ghost signs
    (+1 Neumann ghost = edge, -1 Dirichlet ghost = -edge, 0 periodic —
    with the matching wrap shift via ``px``/``py``). All-(+1)
    reproduces ``laplacian5_neumann``. The wall diagonal becomes
    -4 + sum(adjacent face signs) in [-6, -2] — never 0, so the
    Jacobi smoother diagonal stays invertible at every level
    (periodic rows keep the full interior -4 diagonal)."""
    ny, nx = p.shape[-2], p.shape[-1]
    ex = _edge_ones(nx, p.dtype, lo=sx_lo, hi=sx_hi)
    ey = _edge_ones(ny, p.dtype, lo=sy_lo, hi=sy_hi)
    zs = lambda dy, dx: _shift_bc(p, dy, dx, px, py, spmd_safe)
    return (
        zs(0, 1) + zs(0, -1) + zs(1, 0) + zs(-1, 0)
        + p * ((ey[:, None] + ex[None, :]) - 4.0)
    )


def divergence_bc(v: jnp.ndarray, cx_lo: float, cx_hi: float,
                  cy_lo: float, cy_hi: float,
                  spmd_safe: bool = False,
                  px: bool = False, py: bool = False) -> jnp.ndarray:
    """Undivided central divergence with per-face edge coefficients on
    the wall-NORMAL component (bc.divergence_coeffs): mirror and
    2*uw-edge ghosts keep the free-slip (+1 lo, -1 hi) pattern,
    extrapolated outflow ghosts (ghost = edge) flip it, periodic wraps
    (coefficient 0, roll shift). Prescribed nonzero wall-normal
    velocities additionally contribute the state-independent
    bc.divergence_affine_bc constant — added by the caller, NOT here,
    so this stays linear in ``v`` (the fused RHS applies it once, not
    per div() call)."""
    u = v[..., 0, :, :]
    w = v[..., 1, :, :]
    ny, nx = u.shape[-2], u.shape[-1]
    gx = _edge_ones(nx, v.dtype, lo=cx_lo, hi=cx_hi)
    gy = _edge_ones(ny, v.dtype, lo=cy_lo, hi=cy_hi)
    su = lambda dy, dx: _shift_bc(u, dy, dx, px, py, spmd_safe)
    sw = lambda dy, dx: _shift_bc(w, dy, dx, px, py, spmd_safe)
    return (
        su(0, 1) - su(0, -1) + u * gx[None, :]
        + sw(1, 0) - sw(-1, 0) + w * gy[:, None]
    )


def pressure_gradient_update_bc(p: jnp.ndarray, h, dt,
                                sx_lo: float, sx_hi: float,
                                sy_lo: float, sy_hi: float,
                                spmd_safe: bool = False,
                                px: bool = False,
                                py: bool = False) -> jnp.ndarray:
    """Per-face-sign generalization of
    ``pressure_gradient_update_fused``: the undivided central gradient's
    edge coefficient is -s at the low wall and +s at the high wall
    (Neumann s=+1 reproduces the legacy (-1, +1) one-sided form;
    Dirichlet s=-1 differences against the reflected ghost -edge;
    periodic s=0 differences against the wrapped neighbor)."""
    ny, nx = p.shape[-2], p.shape[-1]
    gx = _edge_ones(nx, p.dtype, lo=-sx_lo, hi=sx_hi)
    gy = _edge_ones(ny, p.dtype, lo=-sy_lo, hi=sy_hi)
    pfac = -0.5 * dt * h
    zs = lambda dy, dx: _shift_bc(p, dy, dx, px, py, spmd_safe)
    dpx = (zs(0, 1) - zs(0, -1)) + p * gx[None, :]
    dpy = (zs(1, 0) - zs(-1, 0)) + p * gy[:, None]
    return pfac * jnp.stack([dpx, dpy], axis=-3)


# ---------------------------------------------------------------------------
# Vorticity (KernelVorticity, main.cpp:3343-3366)
# ---------------------------------------------------------------------------

def vorticity(vlab: jnp.ndarray, g: int, h):
    """omega = dv/dx - du/dy, central differences. vlab: [..., 2, Ny+2g, Nx+2g]."""
    assert g >= 1
    i2h = 0.5 / h
    du_dy = shift(vlab, g, 1, 0)[..., 0, :, :] - shift(vlab, g, -1, 0)[..., 0, :, :]
    dv_dx = shift(vlab, g, 0, 1)[..., 1, :, :] - shift(vlab, g, 0, -1)[..., 1, :, :]
    return i2h * (dv_dx - du_dy)


# ---------------------------------------------------------------------------
# Pressure RHS (pressure_rhs, main.cpp:6105-6139): block-scaled divergence
#   tmp = (h / 2 dt) * [ div(u*) - chi * div(u_def) ]   (undivided central)
# ---------------------------------------------------------------------------

def divergence(vlab: jnp.ndarray, g: int):
    """Undivided central divergence of a vector lab
    [..., 2, Ny+2g, Nx+2g] -> [..., Ny, Nx]."""
    assert g >= 1
    return (
        shift(vlab, g, 0, 1)[..., 0, :, :] - shift(vlab, g, 0, -1)[..., 0, :, :]
        + shift(vlab, g, 1, 0)[..., 1, :, :] - shift(vlab, g, -1, 0)[..., 1, :, :]
    )


def divergence_rhs(vlab: jnp.ndarray, ulab: jnp.ndarray, chi: jnp.ndarray,
                   g: int, h, dt):
    """vlab: velocity lab [..., 2, Ny+2g, Nx+2g]; ulab: u_def lab (same
    shape); chi: interior [..., Ny, Nx]. Returns h^2-scaled Poisson RHS."""
    assert g >= 1
    fac = 0.5 * h / dt
    return fac * divergence(vlab, g) - fac * chi * divergence(ulab, g)


# ---------------------------------------------------------------------------
# 5-point undivided Laplacian (pressure_rhs1 main.cpp:6209-6230 subtracts it;
# the Poisson operator itself uses the same stencil)
# ---------------------------------------------------------------------------

def laplacian5(plab: jnp.ndarray, g: int):
    """Undivided 5-point Laplacian of a scalar lab [..., Ny+2g, Nx+2g]."""
    assert g >= 1
    return (
        shift(plab, g, 0, 1) + shift(plab, g, 0, -1)
        + shift(plab, g, 1, 0) + shift(plab, g, -1, 0)
        - 4.0 * shift(plab, g, 0, 0)
    )


# ---------------------------------------------------------------------------
# Pressure correction (pressureCorrectionKernel, main.cpp:6021-6043):
#   dU = -(dt h / 2) * grad p  (undivided central), applied as u += dU / h^2
# ---------------------------------------------------------------------------

def pressure_gradient_update(plab: jnp.ndarray, g: int, h, dt):
    """Returns the h^2-scaled velocity increment [..., 2, Ny, Nx] from a
    pressure lab [..., Ny+2g, Nx+2g]."""
    assert g >= 1
    pfac = -0.5 * dt * h
    dpx = shift(plab, g, 0, 1) - shift(plab, g, 0, -1)
    dpy = shift(plab, g, 1, 0) - shift(plab, g, -1, 0)
    return pfac * jnp.stack([dpx, dpy], axis=-3)
