"""Stencil operator library — the physics kernels, written once over
ghost-padded arrays and shared by the uniform-grid and AMR block paths.

TPU-native re-design of the reference's per-cell OpenMP loops
(`/root/reference/main.cpp:5441-5572` KernelAdvectDiffuse,
`main.cpp:3343-3366` KernelVorticity, `main.cpp:6105-6287` pressure RHS,
`main.cpp:6021-6104` pressure correction): every kernel here is a pure
function over whole arrays — shifts instead of indexed reads — so XLA fuses
each operator into a handful of elementwise/reduce HLOs over all cells (or
all blocks, when vmapped by the AMR path) at once.

Array convention: fields are padded with `g` ghost cells on each side of the
last two axes, i.e. shape `[..., Ny + 2g, Nx + 2g]`; kernels return interior
arrays `[..., Ny, Nx]`. Axis -2 is y, axis -1 is x. Velocity labs carry a
leading component axis of size 2 (u, v). "Undivided" differences (no 1/h)
are used where the reference uses them, so scalings match exactly.
"""

from __future__ import annotations

import jax.numpy as jnp


def dt_from_umax(umax, h, nu, cfl):
    """CFL/diffusive timestep (main.cpp:6579-6595):
    min(0.25 h^2/(nu + 0.25 h umax), cfl h/(umax + 1e-8)). The ONE
    definition shared by the uniform and forest paths, on device and
    from host-pulled umax — cached next-dt and fallback recomputation
    must agree bit-for-bit or a checkpoint restart forks the
    trajectory."""
    dt_diff = 0.25 * h * h / (nu + 0.25 * h * umax)
    return jnp.minimum(dt_diff, cfl * h / (umax + 1e-8))


def interior(lab: jnp.ndarray, g: int) -> jnp.ndarray:
    """Strip g ghost layers from the last two axes."""
    if g == 0:
        return lab
    return lab[..., g:-g, g:-g]


def shift(lab: jnp.ndarray, g: int, dy: int, dx: int) -> jnp.ndarray:
    """Interior view displaced by (dy, dx); |dy|,|dx| <= g."""
    ny = lab.shape[-2] - 2 * g
    nx = lab.shape[-1] - 2 * g
    return lab[..., g + dy : g + dy + ny, g + dx : g + dx + nx]


# ---------------------------------------------------------------------------
# WENO5 (reference main.cpp:162-208) — vectorized; the `pow(b+e, 2)`
# smoothness weighting and e=1e-6 are kept bit-for-bit.
# ---------------------------------------------------------------------------

_WENO_EPS = 1e-6


def _weno5_weights(b1, b2, b3, g1, g2, g3):
    # deliberately the textbook ratio form. The single-divide variant
    # (n_i = g_i * prod_{j!=i} (b_j+e)^2, one normalization divide) is
    # 17% faster on the STANDALONE advection op but does not move the
    # fused full step at all (XLA hides the divides behind HBM traffic
    # there), and its quartic products overflow f32 at b ~ 2e9 versus
    # ~1e19 here — a transiently unstable run would NaN instead of
    # producing large-but-finite values the CFL/penalization machinery
    # can recover from. Measurements in BASELINE.md.
    w1 = g1 / (b1 + _WENO_EPS) ** 2
    w2 = g2 / (b2 + _WENO_EPS) ** 2
    w3 = g3 / (b3 + _WENO_EPS) ** 2
    aux = 1.0 / ((w1 + w3) + w2)
    return w1 * aux, w2 * aux, w3 * aux


def _smoothness(um2, um1, u, up1, up2):
    b1 = 13.0 / 12.0 * ((um2 + u) - 2 * um1) ** 2 + 0.25 * ((um2 + 3 * u) - 4 * um1) ** 2
    b2 = 13.0 / 12.0 * ((um1 + up1) - 2 * u) ** 2 + 0.25 * (um1 - up1) ** 2
    b3 = 13.0 / 12.0 * ((u + up2) - 2 * up1) ** 2 + 0.25 * ((3 * u + up2) - 4 * up1) ** 2
    return b1, b2, b3


def weno5_plus(um2, um1, u, up1, up2):
    """Upwind-biased flux reconstruction, wind > 0 (main.cpp:162-180)."""
    b1, b2, b3 = _smoothness(um2, um1, u, up1, up2)
    w1, w2, w3 = _weno5_weights(b1, b2, b3, 0.1, 0.6, 0.3)
    f1 = (11.0 / 6.0) * u + ((1.0 / 3.0) * um2 - (7.0 / 6.0) * um1)
    f2 = (5.0 / 6.0) * u + ((-1.0 / 6.0) * um1 + (1.0 / 3.0) * up1)
    f3 = (1.0 / 3.0) * u + ((5.0 / 6.0) * up1 - (1.0 / 6.0) * up2)
    return (w1 * f1 + w3 * f3) + w2 * f2


def weno5_minus(um2, um1, u, up1, up2):
    """Upwind-biased flux reconstruction, wind < 0 (main.cpp:181-201)."""
    b1, b2, b3 = _smoothness(um2, um1, u, up1, up2)
    w1, w2, w3 = _weno5_weights(b1, b2, b3, 0.3, 0.6, 0.1)
    f1 = (1.0 / 3.0) * u + ((-1.0 / 6.0) * um2 + (5.0 / 6.0) * um1)
    f2 = (5.0 / 6.0) * u + ((1.0 / 3.0) * um1 - (1.0 / 6.0) * up1)
    f3 = (11.0 / 6.0) * u + ((-7.0 / 6.0) * up1 + (1.0 / 3.0) * up2)
    return (w1 * f1 + w3 * f3) + w2 * f2


def weno_derivative(wind, um3, um2, um1, u, up1, up2, up3):
    """Undivided upwind WENO5 derivative (main.cpp:202-208): flux difference
    of the reconstruction chosen by the local wind sign."""
    dplus = weno5_plus(um2, um1, u, up1, up2) - weno5_plus(um3, um2, um1, u, up1)
    dminus = weno5_minus(um1, u, up1, up2, up3) - weno5_minus(um2, um1, u, up1, up2)
    return jnp.where(wind > 0, dplus, dminus)


# ---------------------------------------------------------------------------
# Advection–diffusion RHS (KernelAdvectDiffuse, main.cpp:5441-5503)
# ---------------------------------------------------------------------------

def advect_diffuse_rhs(vlab: jnp.ndarray, g: int, h, nu, dt):
    """RHS in the reference's block scaling: h^2 * du/dt * dt, i.e.
    ``afac*(u·∇)u + dfac*lap(u)`` with afac = -dt*h, dfac = nu*dt and
    *undivided* differences — exactly what the reference writes into tmpV;
    the integrator divides by h^2 (main.cpp:6619-6626).

    vlab: [..., 2, Ny+2g, Nx+2g] velocity with ghosts, g >= 3.
    Returns [..., 2, Ny, Nx].
    """
    return advect_diffuse_core(vlab, g, -dt * h, nu * dt)


def advect_diffuse_core(vlab: jnp.ndarray, g: int, afac, dfac):
    """Same, with the scale factors precomputed — shared verbatim by the
    XLA path above and the Pallas kernel (ops/pallas_kernels.py), so the
    two can never drift numerically.

    Deliberately the per-cell form: evaluating each reconstruction once
    on a one-cell-extended range and differencing by shift halves the
    arithmetic on paper but measured 26% SLOWER at 8192^2 — the
    odd-width (n+1) intermediates misalign XLA's (8, 128) lane tiling
    and the relayouts cost more than the saved flops."""
    assert g >= 3
    u = shift(vlab, g, 0, 0)
    wind_u = u[..., 0:1, :, :]  # u component drives x-derivatives
    wind_v = u[..., 1:2, :, :]  # v component drives y-derivatives

    dx = weno_derivative(
        wind_u,
        shift(vlab, g, 0, -3), shift(vlab, g, 0, -2), shift(vlab, g, 0, -1),
        u,
        shift(vlab, g, 0, 1), shift(vlab, g, 0, 2), shift(vlab, g, 0, 3),
    )
    dy = weno_derivative(
        wind_v,
        shift(vlab, g, -3, 0), shift(vlab, g, -2, 0), shift(vlab, g, -1, 0),
        u,
        shift(vlab, g, 1, 0), shift(vlab, g, 2, 0), shift(vlab, g, 3, 0),
    )
    lap = (
        shift(vlab, g, 0, 1) + shift(vlab, g, 0, -1)
        + shift(vlab, g, 1, 0) + shift(vlab, g, -1, 0)
        - 4.0 * u
    )
    return afac * (wind_u * dx + wind_v * dy) + dfac * lap


# ---------------------------------------------------------------------------
# Vorticity (KernelVorticity, main.cpp:3343-3366)
# ---------------------------------------------------------------------------

def vorticity(vlab: jnp.ndarray, g: int, h):
    """omega = dv/dx - du/dy, central differences. vlab: [..., 2, Ny+2g, Nx+2g]."""
    assert g >= 1
    i2h = 0.5 / h
    du_dy = shift(vlab, g, 1, 0)[..., 0, :, :] - shift(vlab, g, -1, 0)[..., 0, :, :]
    dv_dx = shift(vlab, g, 0, 1)[..., 1, :, :] - shift(vlab, g, 0, -1)[..., 1, :, :]
    return i2h * (dv_dx - du_dy)


# ---------------------------------------------------------------------------
# Pressure RHS (pressure_rhs, main.cpp:6105-6139): block-scaled divergence
#   tmp = (h / 2 dt) * [ div(u*) - chi * div(u_def) ]   (undivided central)
# ---------------------------------------------------------------------------

def divergence(vlab: jnp.ndarray, g: int):
    """Undivided central divergence of a vector lab
    [..., 2, Ny+2g, Nx+2g] -> [..., Ny, Nx]."""
    assert g >= 1
    return (
        shift(vlab, g, 0, 1)[..., 0, :, :] - shift(vlab, g, 0, -1)[..., 0, :, :]
        + shift(vlab, g, 1, 0)[..., 1, :, :] - shift(vlab, g, -1, 0)[..., 1, :, :]
    )


def divergence_rhs(vlab: jnp.ndarray, ulab: jnp.ndarray, chi: jnp.ndarray,
                   g: int, h, dt):
    """vlab: velocity lab [..., 2, Ny+2g, Nx+2g]; ulab: u_def lab (same
    shape); chi: interior [..., Ny, Nx]. Returns h^2-scaled Poisson RHS."""
    assert g >= 1
    fac = 0.5 * h / dt
    return fac * divergence(vlab, g) - fac * chi * divergence(ulab, g)


# ---------------------------------------------------------------------------
# 5-point undivided Laplacian (pressure_rhs1 main.cpp:6209-6230 subtracts it;
# the Poisson operator itself uses the same stencil)
# ---------------------------------------------------------------------------

def laplacian5(plab: jnp.ndarray, g: int):
    """Undivided 5-point Laplacian of a scalar lab [..., Ny+2g, Nx+2g]."""
    assert g >= 1
    return (
        shift(plab, g, 0, 1) + shift(plab, g, 0, -1)
        + shift(plab, g, 1, 0) + shift(plab, g, -1, 0)
        - 4.0 * shift(plab, g, 0, 0)
    )


# ---------------------------------------------------------------------------
# Pressure correction (pressureCorrectionKernel, main.cpp:6021-6043):
#   dU = -(dt h / 2) * grad p  (undivided central), applied as u += dU / h^2
# ---------------------------------------------------------------------------

def pressure_gradient_update(plab: jnp.ndarray, g: int, h, dt):
    """Returns the h^2-scaled velocity increment [..., 2, Ny, Nx] from a
    pressure lab [..., Ny+2g, Nx+2g]."""
    assert g >= 1
    pfac = -0.5 * dt * h
    dpx = shift(plab, g, 0, 1) - shift(plab, g, 0, -1)
    dpy = shift(plab, g, 1, 0) - shift(plab, g, -1, 0)
    return pfac * jnp.stack([dpx, dpy], axis=-3)
