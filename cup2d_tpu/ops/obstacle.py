"""Device-side obstacle kernels: SDF rasterization, chi, udef, integrals.

TPU-native re-design of the reference's scatter-style rasterization
(`/root/reference/main.cpp:4271-4463` PutFishOnBlocks, `3911-3969`
PutChiOnGrid, `4488-4630` integrals + udef de-meaning): instead of walking
midline segments and scattering into 6x6 cell neighborhoods (a
race-managed, branch-heavy pattern), every cell of a static-size window
around the body *gathers* its distance to the whole surface polygon and
its deformation velocity from the nearest midline node — a dense
[cells x edges] computation with argmin/min reductions, which is exactly
the shape the VPU wants, and trivially vmappable over shapes/blocks.

The surface polygon is the same curve the reference rasterizes (the two
skin offset curves, upper head->tail + lower tail->head); the sign is the
polygon crossing parity (positive inside, like the reference's dist
field), replacing the per-segment ellipse disambiguation
(main.cpp:4352-4381) with an exact point-in-polygon test.

All kernels run inside jit; window origins are traced scalars so the
moving body never retriggers compilation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .stencil import shift

_EPS = 2.220446049250313e-16  # reference EPS (f64 machine eps, main.cpp:27)


def polygon_sdf(px, py, poly):
    """Signed distance of points (px, py) [...,] to closed polygon
    ``poly`` [E, 2]; positive inside (the reference's sign convention for
    its dist field). Points and polygon should share a local origin for
    f32 accuracy (caller subtracts the window center)."""
    ax, ay = poly[:, 0], poly[:, 1]
    bx, by = jnp.roll(poly[:, 0], -1), jnp.roll(poly[:, 1], -1)
    ex, ey = bx - ax, by - ay
    elen2 = ex * ex + ey * ey

    pax = px[..., None] - ax
    pay = py[..., None] - ay
    t = jnp.clip((pax * ex + pay * ey) / (elen2 + _EPS), 0.0, 1.0)
    dx = pax - t * ex
    dy = pay - t * ey
    d2 = jnp.min(dx * dx + dy * dy, axis=-1)

    # crossing-parity inside test (+x ray)
    cond = (ay > py[..., None]) != (by > py[..., None])
    xint = ax + (py[..., None] - ay) * ex / jnp.where(ey == 0, 1.0, ey)
    crossings = jnp.sum(cond & (px[..., None] < xint), axis=-1)
    inside = (crossings % 2) == 1
    d = jnp.sqrt(d2)
    return jnp.where(inside, d, -d)


def pack_polygon_segments(poly: "np.ndarray") -> "np.ndarray":
    """Host-side precompute of polygon_sdf's per-segment quantities into
    ONE [E, 6] table (ax, ay, ex, ey, 1/(|e|^2+eps), 1/ey-safe).

    Motivation is per-op overhead, not flops: the op-level profiler
    trace of the canonical megastep showed ~40% of device time spent
    staging dozens of tiny [E, 2]-derived buffers (roll/sub/div chains
    and per-field gathers) through scratch memory — each costs a fixed
    DMA latency regardless of size. One packed operand turns the chain
    into slices of a single staged table. numpy in, numpy out (f64);
    the caller casts to the field dtype."""
    import numpy as np
    ax, ay = poly[:, 0], poly[:, 1]
    bx = np.roll(poly[:, 0], -1)
    by = np.roll(poly[:, 1], -1)
    ex, ey = bx - ax, by - ay
    elen2 = ex * ex + ey * ey
    inv_l2 = 1.0 / (elen2 + _EPS)
    inv_ey = 1.0 / np.where(ey == 0, 1.0, ey)
    return np.stack([ax, ay, ex, ey, inv_l2, inv_ey], axis=1)


def polygon_sdf_seg(px, py, seg):
    """polygon_sdf on a host-packed segment table (pack_polygon_
    segments). Same geometry/sign semantics; divisions are replaced by
    the packed reciprocals."""
    ax, ay = seg[:, 0], seg[:, 1]
    ex, ey = seg[:, 2], seg[:, 3]
    inv_l2, inv_ey = seg[:, 4], seg[:, 5]
    pax = px[..., None] - ax
    pay = py[..., None] - ay
    t = jnp.clip((pax * ex + pay * ey) * inv_l2, 0.0, 1.0)
    dx = pax - t * ex
    dy = pay - t * ey
    d2 = jnp.min(dx * dx + dy * dy, axis=-1)
    cond = (ay > py[..., None]) != ((ay + ey) > py[..., None])
    xint = ax + (py[..., None] - ay) * ex * inv_ey
    crossings = jnp.sum(cond & (px[..., None] < xint), axis=-1)
    inside = (crossings % 2) == 1
    d = jnp.sqrt(d2)
    return jnp.where(inside, d, -d)


def pack_midline(mid_r, mid_v, mid_nor, mid_vnor, width) -> "np.ndarray":
    """Host-side packing of the per-node midline fields into ONE
    [Nm, 9] table (rx, ry, vx, vy, nx, ny, vnx, vny, width) so the
    nearest-node lookup is a single gather instead of eight (same
    per-op overhead rationale as pack_polygon_segments)."""
    import numpy as np
    return np.concatenate([
        np.asarray(mid_r), np.asarray(mid_v), np.asarray(mid_nor),
        np.asarray(mid_vnor), np.asarray(width)[:, None]], axis=1)


def midline_udef_packed(px, py, mid):
    """midline_udef on a host-packed [Nm, 9] node table: one argmin +
    one gather."""
    dx = px[..., None] - mid[:, 0]
    dy = py[..., None] - mid[:, 1]
    i = jnp.argmin(dx * dx + dy * dy, axis=-1)
    m = mid[i]                                   # [..., 9], one gather
    w = jnp.clip((px - m[..., 0]) * m[..., 4]
                 + (py - m[..., 1]) * m[..., 5],
                 -m[..., 8], m[..., 8])
    ux = m[..., 2] + w * m[..., 6]
    uy = m[..., 3] + w * m[..., 7]
    return jnp.stack([ux, uy], axis=0)


def midline_udef(px, py, mid_r, mid_v, mid_nor, mid_vnor, width):
    """Deformation velocity at points (px, py): nearest midline node i*,
    normal offset w = clamp(<p - r_i*, n_i*>, +-width_i*), udef = v_i* +
    w * vn_i* — the gather form of the reference's surface/interior udef
    splats (main.cpp:4326-4330 surface, 4403-4437 interior, both of which
    assign v + offset*vNor with bilinear spreading)."""
    dx = px[..., None] - mid_r[:, 0]
    dy = py[..., None] - mid_r[:, 1]
    i = jnp.argmin(dx * dx + dy * dy, axis=-1)
    rx = mid_r[i, 0]
    ry = mid_r[i, 1]
    nx = mid_nor[i, 0]
    ny = mid_nor[i, 1]
    w = jnp.clip((px - rx) * nx + (py - ry) * ny, -width[i], width[i])
    ux = mid_v[i, 0] + w * mid_vnor[i, 0]
    uy = mid_v[i, 1] + w * mid_vnor[i, 1]
    return jnp.stack([ux, uy], axis=0)


def chi_from_sdf(sdf_lab, dist_own, h):
    """The reference's PutChiOnGrid formula (main.cpp:3938-3958): cells
    deeper than +-h get a sharp 0/1; band cells get the regularized
    gradient ratio chi = <grad max(0,d), grad d> / |grad d|^2 computed on
    the COMBINED sdf field (overlapping bodies interact through it).

    sdf_lab: [Ny+2, Nx+2] combined sdf with 1 ghost (Neumann); dist_own:
    [Ny, Nx] this shape's own sdf; returns chi [Ny, Nx].
    """
    sp_x = shift(sdf_lab, 1, 0, 1)
    sm_x = shift(sdf_lab, 1, 0, -1)
    sp_y = shift(sdf_lab, 1, 1, 0)
    sm_y = shift(sdf_lab, 1, -1, 0)
    grad_ix = jnp.maximum(sp_x, 0.0) - jnp.maximum(sm_x, 0.0)
    grad_iy = jnp.maximum(sp_y, 0.0) - jnp.maximum(sm_y, 0.0)
    grad_ux = sp_x - sm_x
    grad_uy = sp_y - sm_y
    grad_usq = grad_ux * grad_ux + grad_uy * grad_uy + _EPS
    ratio = (grad_ix * grad_ux + grad_iy * grad_uy) / grad_usq
    return jnp.where(
        dist_own > h, 1.0, jnp.where(dist_own < -h, 0.0, ratio)
    )


def window_coords(ox, oy, wx, wy, h, dtype):
    """Cell-center coordinates of a wx x wy window whose lower-left cell
    index is (ox, oy): x[j, i], y[j, i] each [wy, wx]."""
    x = (ox + jnp.arange(wx)[None, :] + 0.5).astype(dtype) * h
    y = (oy + jnp.arange(wy)[:, None] + 0.5).astype(dtype) * h
    return jnp.broadcast_to(x, (wy, wx)), jnp.broadcast_to(y, (wy, wx))


def scatter_window_max(field, win, oy, ox):
    """field[oy:oy+wy, ox:ox+wx] = max(field_slice, win) (the reference's
    per-block max-combining of dist/chi across shapes)."""
    wy, wx = win.shape[-2:]
    cur = jax.lax.dynamic_slice(field, (oy, ox), (wy, wx))
    return jax.lax.dynamic_update_slice(field, jnp.maximum(cur, win), (oy, ox))


def scatter_window_set(field, win, oy, ox):
    """Per-component scatter of a [..., wy, wx] window into [..., Ny, Nx]."""
    zero = jnp.zeros_like(oy)
    idx = (zero,) * (field.ndim - 2) + (oy, ox)
    return jax.lax.dynamic_update_slice(field, win, idx)


def shape_integrals(chi, udef, xrel, yrel, hsq):
    """The 7 penalization-frame integrals (main.cpp:4489-4533):
    returns (x, y, m, j, u, v, a) where u, v, a are already normalized
    by m, m, j respectively. xrel/yrel are cell centers minus the CoM."""
    w = chi * hsq
    m = jnp.sum(w)
    x = jnp.sum(w * xrel)
    y = jnp.sum(w * yrel)
    j = jnp.sum(w * (xrel * xrel + yrel * yrel))
    u = jnp.sum(w * udef[0])
    v = jnp.sum(w * udef[1])
    a = jnp.sum(w * (xrel * udef[1] - yrel * udef[0]))
    # a body thinner than a cell can have zero chi mass — return zero
    # mean motion instead of NaN-ing every downstream field
    u = jnp.where(m > 0, u / (m + _EPS), 0.0)
    v = jnp.where(m > 0, v / (m + _EPS), 0.0)
    a = jnp.where(j > 0, a / (j + _EPS), 0.0)
    return x, y, m, j, u, v, a


def penalization_integrals(vel, chi, udef, xrel, yrel, lamdt, hsq):
    """The 7 sums of the rigid-momentum system (main.cpp:6647-6692):
    F = h^2 * Xlamdt/(1+Xlamdt) with Xlamdt = lambda*dt where chi >= 0.5.
    Returns (PM, PJ, PX, PY, UM, VM, AM)."""
    xlamdt = jnp.where(chi >= 0.5, lamdt, 0.0)
    f = hsq * xlamdt / (1.0 + xlamdt)
    udx = vel[0] - udef[0]
    udy = vel[1] - udef[1]
    pm = jnp.sum(f)
    pj = jnp.sum(f * (xrel * xrel + yrel * yrel))
    px = jnp.sum(f * xrel)
    py = jnp.sum(f * yrel)
    um = jnp.sum(f * udx)
    vm = jnp.sum(f * udy)
    am = jnp.sum(f * (xrel * udy - yrel * udx))
    return pm, pj, px, py, um, vm, am


def solve_rigid_momentum(pm, pj, px, py, um, vm, am):
    """Solve the 3x3 system [[PM,0,-PY],[0,PM,PX],[-PY,PX,PJ]] (u,v,w) =
    (UM,VM,AM) (main.cpp:6691-6703, GSL LU there). Normalized by PM for
    f32 conditioning and solved in closed form — the normalized matrix is
    [[1,0,a],[0,1,b],[a,b,c]], whose Schur complement is the scalar
    c - a^2 - b^2 (an explicit LU here would lower to XLA's
    LuDecomposition expander: f32-only on TPU and absurdly heavyweight
    for a 3x3)."""
    s = 1.0 / (pm + _EPS)
    a = -py * s
    b = px * s
    # tiny ridge keeps the omega row regular when the body has no
    # penalized cells (PM = PJ = 0, an under-resolved body)
    c = pj * s + 1e-30
    r0, r1, r2 = um * s, vm * s, am * s
    w = (r2 - a * r0 - b * r1) / (c - a * a - b * b + _EPS)
    sol = jnp.stack([r0 - a * w, r1 - b * w, w])
    return jnp.where(pm > 0, sol, jnp.zeros_like(sol))
