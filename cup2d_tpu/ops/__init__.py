from .stencil import (  # noqa: F401
    weno5_plus,
    weno5_minus,
    weno_derivative,
    advect_diffuse_rhs,
    vorticity,
    divergence_rhs,
    pressure_gradient_update,
    laplacian5,
)
