"""Shape-shape collision model: chi-overlap detection + impulse response.

Reproduces the reference's collision pipeline
(`/root/reference/main.cpp:6705-6943` detection/overlap integrals,
`209-235` compute_j, `236-291` the e=1 impulse solve) as pure jnp on the
per-shape chi/sdf/udef fields, so it runs inside the same jitted flow
step as the momentum solve — no host round-trip between them (the
reference interleaves the same three stages between two MPI reductions).

The reference keeps the z-components around as dead 3-D baggage; the
math here is specialized to 2-D (z momenta/vectors are identically zero
in the reference too), with the same merged-per-shape accumulation over
opponents and the same gating thresholds.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .stencil import shift

_EPS = 1e-21  # reference impulse denominator guard (main.cpp:282)


def _overlap_sums(w, sdf_i, udef_i, uvw_i, com_i, x, y):
    """The 7 chi-weighted overlap sums for one shape given its per-cell
    weight field ``w`` (main.cpp:6733-6815): mass, position, momentum
    (rigid + deformation), and the own-SDF gradient (contact normal).
    Sums are unweighted by h^2, exactly like the reference (its
    iM < 2.0 gate counts cells)."""
    ur_x = -uvw_i[2] * (y - com_i[1])
    ur_y = uvw_i[2] * (x - com_i[0])
    # central SDF gradient (undivided); the reference falls back to
    # one-sided at block edges only because its blocks lack ghosts.
    # Pad the last two axes only, so [N, BS, BS] forest layouts (leading
    # block axis) work as well as [Ny, Nx] uniform grids.
    pad = [(0, 0)] * (sdf_i.ndim - 2) + [(1, 1), (1, 1)]
    lab = jnp.pad(sdf_i, pad, mode="edge")
    gx = 0.5 * (shift(lab, 1, 0, 1) - shift(lab, 1, 0, -1))
    gy = 0.5 * (shift(lab, 1, 1, 0) - shift(lab, 1, -1, 0))
    return jnp.stack([
        jnp.sum(w),
        jnp.sum(w * x),
        jnp.sum(w * y),
        jnp.sum(w * (uvw_i[0] + ur_x + udef_i[0])),
        jnp.sum(w * (uvw_i[1] + ur_y + udef_i[1])),
        jnp.sum(w * gx),
        jnp.sum(w * gy),
    ])


def overlap_integrals(chi_i, chi_j, sdf_i, udef_i, uvw_i, com_i, x, y):
    """Shape i's overlap sums against one opponent j: cells where both
    chi > 0, weighted by chi_i."""
    w = jnp.where((chi_i > 0.0) & (chi_j > 0.0), chi_i, 0.0)
    return _overlap_sums(w, sdf_i, udef_i, uvw_i, com_i, x, y)


def merged_overlap_integrals(chi_s, sdf_s, udef_s, uvw, com, x, y):
    """All shapes' opponent-merged overlap sums in ONE field pass.

    The reference accumulates per-opponent integrals into a single
    per-shape struct (main.cpp:6733-6815); summing overlap_integrals
    over opponents j is identical to weighting shape i's cells by
    chi_i * (number of opponents with chi_j > 0 at that cell), so the
    merged sums cost O(S*N) field work instead of the O(S^2*N) pair
    unroll (VERDICT r1 Weak #9 — 'no story for many bodies').
    chi_s/sdf_s: [S, ...spatial]; udef_s: [S, 2, ...]; uvw: [S, 3];
    com: [S, 2]. Returns [S, 7]."""
    pos = (chi_s > 0.0)
    cnt = jnp.sum(pos, axis=0)

    def one(chi_i, sdf_i, udef_i, uvw_i, com_i):
        others = (cnt - (chi_i > 0.0)).astype(chi_i.dtype)
        w = jnp.where(chi_i > 0.0, chi_i, 0.0) * others
        return _overlap_sums(w, sdf_i, udef_i, uvw_i, com_i, x, y)

    return jax.vmap(one)(chi_s, sdf_s, udef_s, uvw, com)


def pairwise_collision_update(colls, uvw, mass, inertia, com, lengths):
    """Sequential e=1 impulse updates over every (i < j) pair in fixed
    pair order — the reference's loop order (main.cpp:6863-6943), where
    earlier impulses feed later pairs through uvw. A lax.fori_loop over
    a precomputed pair list keeps the compiled size O(1) in the pair
    count (a Python unroll is fine for 2 fish, not for 100 disks)."""
    S = int(colls.shape[0])
    ii, jj = np.triu_indices(S, 1)
    if len(ii) == 0:
        return uvw
    pi = jnp.asarray(ii, jnp.int32)
    pj = jnp.asarray(jj, jnp.int32)

    def body(k, uvw_):
        i, j = pi[k], pj[k]
        new_i, new_j, _hit = collision_response(
            colls[i], colls[j], uvw_[i], uvw_[j], mass[i], mass[j],
            inertia[i], inertia[j], com[i], com[j], lengths[i])
        return uvw_.at[i].set(new_i).at[j].set(new_j)

    return jax.lax.fori_loop(0, len(ii), body, uvw)


def collision_response(coll_i, coll_j, uvw_i, uvw_j, m1, m2, j1, j2,
                       com_i, com_j, length_i):
    """Impulse response for the (i, j) pair (main.cpp:6862-6943 +
    collision(), 236-291, e = 1 elastic). Returns (new_uvw_i, new_uvw_j,
    hit) where hit gates the update (insufficient overlap, separated
    centroids, or receding contact leave the inputs unchanged)."""
    iM, iPx, iPy, iMx, iMy, ivx, ivy = coll_i
    jM, jPx, jPy, jMx, jMy, jvx, jvy = coll_j

    enough = (iM >= 2.0) & (jM >= 2.0)
    sep = (jnp.abs(iPx / jnp.maximum(iM, _EPS)
                   - jPx / jnp.maximum(jM, _EPS)) > length_i) | (
        jnp.abs(iPy / jnp.maximum(iM, _EPS)
                - jPy / jnp.maximum(jM, _EPS)) > length_i)

    norm_i = jnp.sqrt(ivx * ivx + ivy * ivy) + _EPS
    norm_j = jnp.sqrt(jvx * jvx + jvy * jvy) + _EPS
    mx = ivx / norm_i - jvx / norm_j
    my = ivy / norm_i - jvy / norm_j
    inorm = 1.0 / (jnp.sqrt(mx * mx + my * my) + _EPS)
    nx_ = mx * inorm
    ny_ = my * inorm

    iMs = jnp.maximum(iM, _EPS)
    jMs = jnp.maximum(jM, _EPS)
    vc1 = jnp.stack([iMx / iMs, iMy / iMs])
    vc2 = jnp.stack([jMx / jMs, jMy / jMs])
    proj_vel = (vc2[0] - vc1[0]) * nx_ + (vc2[1] - vc1[1]) * ny_

    cx = 0.5 * (iPx / iMs + jPx / jMs)
    cy = 0.5 * (iPy / iMs + jPy / jMs)

    # compute_j specialized to 2-D: J = (r x N)_z / I  (main.cpp:209-235
    # inverts a diagonal [1,1,I] matrix)
    r1x, r1y = cx - com_i[0], cy - com_i[1]
    r2x, r2y = cx - com_j[0], cy - com_j[1]
    jz1 = (r1x * ny_ - r1y * nx_) / jnp.maximum(j1, _EPS)
    jz2 = -(r2x * ny_ - r2y * nx_) / jnp.maximum(j2, _EPS)

    u1, v1, o1 = uvw_i[0], uvw_i[1], uvw_i[2]
    u2, v2, o2 = uvw_j[0], uvw_j[1], uvw_j[2]

    # u*DEF = contact-cloud velocity minus rigid velocity at the contact
    u1d_x = vc1[0] - u1 + o1 * r1y
    u1d_y = vc1[1] - v1 - o1 * r1x
    u2d_x = vc2[0] - u2 + o2 * r2y
    u2d_y = vc2[1] - v2 - o2 * r2x

    e = 1.0
    nom = (e * ((vc1[0] - vc2[0]) * nx_ + (vc1[1] - vc2[1]) * ny_)
           + ((u1 - u2 + u1d_x - u2d_x) * nx_
              + (v1 - v2 + u1d_y - u2d_y) * ny_)
           + ((-o1 * r1y) * nx_ + (o1 * r1x) * ny_)
           - ((-o2 * r2y) * nx_ + (o2 * r2x) * ny_))
    denom = (-(1.0 / m1 + 1.0 / m2)
             + ((-jz1 * r1y) * (-nx_) + (jz1 * r1x) * (-ny_))
             - ((-jz2 * r2y) * (-nx_) + (jz2 * r2x) * (-ny_)))
    impulse = nom / (denom + _EPS)

    hit = enough & ~sep & (proj_vel > 0)
    new_i = jnp.stack([
        u1 + nx_ / m1 * impulse,
        v1 + ny_ / m1 * impulse,
        o1 + jz1 * impulse,
    ])
    new_j = jnp.stack([
        u2 - nx_ / m2 * impulse,
        v2 - ny_ / m2 * impulse,
        o2 + jz2 * impulse,
    ])
    return (
        jnp.where(hit, new_i, uvw_i),
        jnp.where(hit, new_j, uvw_j),
        hit,
    )
