"""Profiling and observability (SURVEY.md §5: the reference has NO
timers, counters, or traces — a stderr step counter only).

Three tools:
- ``PhaseTimers``: per-phase wall-clock accumulation. Instrumented
  code must synchronize inside each phase (the sims block on the
  phase's device outputs whenever timers are enabled) — without that,
  async dispatch attributes device time to whoever synchronizes next.
  Enable on a sim with ``sim.timers = PhaseTimers()``; `report()`
  gives totals, means, and counts per phase.
- ``throughput(sim)``: the north-star cells*steps/s metric from a sim's
  counters (works for uniform and forest sims).
- ``trace(logdir)``: context manager around `jax.profiler` for a full
  TensorBoard-readable device trace.
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager

import jax


class PhaseTimers:
    """Accumulates wall time per named phase across steps."""

    def __init__(self):
        self.acc = defaultdict(float)
        self.count = defaultdict(int)

    @contextmanager
    def phase(self, name: str):
        """Time a host-side block. The caller is responsible for device
        fencing (pass the phase's outputs through `fence`)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.acc[name] += time.perf_counter() - t0
            self.count[name] += 1

    def report(self) -> dict:
        return {
            name: {
                "total_s": self.acc[name],
                "mean_ms": 1e3 * self.acc[name] / max(1, self.count[name]),
                "count": self.count[name],
            }
            for name in sorted(self.acc)
        }

    def summary(self) -> str:
        rows = [f"{k:>16s}: {v['total_s']:8.3f}s total "
                f"{v['mean_ms']:8.2f}ms/call x{v['count']}"
                for k, v in self.report().items()]
        return "\n".join(rows)


def throughput(sim) -> dict:
    """cells*steps/s so far, from the sim's own counters. For forest
    sims the live cell count is used (the adapted count varies; this is
    the instantaneous grid, matching how the reference would report)."""
    if hasattr(sim, "forest"):
        cells = len(sim.forest.blocks) * sim.forest.bs ** 2
    else:
        cells = sim.grid.nx * sim.grid.ny
    wall = getattr(sim, "timers", None)
    # top-level phases are non-nested by construction (adapt() refreshes
    # tables BEFORE opening its phase); "a/b"-named sub-phases break the
    # parent down and are excluded from the wall total
    total = (sum(v for k, v in wall.acc.items() if "/" not in k)
             if wall else float("nan"))
    return {
        "cells": cells,
        "steps": sim.step_count,
        "sim_time": sim.time,
        "wall_s": total,
        "cells_steps_per_sec": (
            cells * sim.step_count / total if wall and total > 0
            else float("nan")),
    }


class _NullTimers:
    """No-op stand-in so instrumented code needs no branches."""

    @contextmanager
    def phase(self, name):
        yield

    def fence(self, name, *arrays):
        return arrays


NULL_TIMERS = _NullTimers()


@contextmanager
def trace(logdir: str):
    """TensorBoard device trace of the enclosed block."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
