"""Telemetry & profiling (SURVEY.md §5: the reference has NO timers,
counters, metrics or traces — a stderr step counter only).

The run-telemetry subsystem (PR 3), layered so every piece rides work
the step ALREADY does — the contract throughout is **zero extra device
syncs**: the per-step scalars arrive in the step's one existing batched
diag pull (`sim.py`/`amr.py`), and everything here is host-side
bookkeeping on top of it (asserted by ``tests/test_telemetry.py``: a
metrics-on run is bit-identical to metrics-off with equal
``device_get`` counts).

- :class:`MetricsRecorder`: one structured record per step — solver
  health (Poisson iters / true residual / converged / stalled),
  timestep state (dt, umax, next dt), fused on-device physics
  invariants (kinetic energy, max |∇·u|), AMR shape (per-level block
  histogram, refine/coarsen counts), comm volume (real/padded halo
  bytes of the shard exchange plan), host counters (jit recompiles,
  ``device_get`` pulls, HBM high-water mark) and per-phase wall times —
  streamed as JSONL through the PR-2 ``resilience.EventLog`` machinery
  (process-0 writer on pods). The key set is frozen
  (:data:`METRICS_KEYS`, schema-stability golden test).
- :class:`HostCounters`: process-wide host-side counters — jit
  recompiles via the ``jax.monitoring`` backend-compile event,
  device→host pulls by wrapping ``jax.device_get``, HBM peak bytes via
  ``jax.local_devices()[0].memory_stats()`` (None on backends without
  an allocator report, e.g. CPU).
- :class:`TraceWindow`: windowed device tracing —
  ``CUP2D_TRACE=start:stop[:logdir]`` wraps exactly steps
  ``[start, stop)`` of a production run in ``jax.profiler`` so a
  TensorBoard trace costs only its window, not the whole run.
- :class:`PhaseTimers`: per-phase wall-clock accumulation. Instrumented
  code must synchronize inside each phase — pass the phase's device
  outputs through :meth:`PhaseTimers.fence` (without that, async
  dispatch attributes device time to whoever synchronizes next).
  Enable on a sim with ``sim.timers = PhaseTimers()``; ``report()``
  gives totals, means, and counts per phase.
- ``throughput(sim)``: the north-star cells*steps/s metric from a sim's
  counters (works for uniform and forest sims).
- ``trace(logdir)``: context manager around `jax.profiler` for a full
  TensorBoard-readable device trace (whole-block form; production runs
  want :class:`TraceWindow`).
"""

from __future__ import annotations

import json
import os
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Optional

import numpy as np

import jax


class PhaseTimers:
    """Accumulates wall time per named phase across steps."""

    def __init__(self):
        self.acc = defaultdict(float)
        self.count = defaultdict(int)

    @contextmanager
    def phase(self, name: str):
        """Time a host-side block. The caller is responsible for device
        fencing (pass the phase's outputs through `fence`)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.acc[name] += time.perf_counter() - t0
            self.count[name] += 1

    def fence(self, name: str, *arrays):
        """Block until ``arrays`` (arrays or pytrees of arrays) are
        ready, so the enclosing ``phase(name)`` block charges their
        device time to the right phase instead of to whoever
        synchronizes next. Call INSIDE the phase block; returns the
        arrays unchanged so it can wrap a phase's outputs in place.
        Non-jax leaves (numpy tables) pass through untouched."""
        for a in arrays:
            if a is not None:
                jax.block_until_ready(a)
        return arrays

    def report(self) -> dict:
        return {
            name: {
                "total_s": self.acc[name],
                "mean_ms": 1e3 * self.acc[name] / max(1, self.count[name]),
                "count": self.count[name],
            }
            for name in sorted(self.acc)
        }

    def summary(self) -> str:
        rows = [f"{k:>16s}: {v['total_s']:8.3f}s total "
                f"{v['mean_ms']:8.2f}ms/call x{v['count']}"
                for k, v in self.report().items()]
        return "\n".join(rows)


def throughput(sim) -> dict:
    """cells*steps/s so far, from the sim's own counters. For forest
    sims the live cell count is used (the adapted count varies; this is
    the instantaneous grid, matching how the reference would report)."""
    if hasattr(sim, "forest"):
        cells = len(sim.forest.blocks) * sim.forest.bs ** 2
    else:
        # a fleet steps B member grids per dispatch (fleet.FleetSim)
        cells = sim.grid.nx * sim.grid.ny * getattr(sim, "members", 1)
    wall = getattr(sim, "timers", None)
    # top-level phases are non-nested by construction (adapt() refreshes
    # tables BEFORE opening its phase); "a/b"-named sub-phases break the
    # parent down and are excluded from the wall total
    total = (sum(v for k, v in wall.acc.items() if "/" not in k)
             if wall else float("nan"))
    return {
        "cells": cells,
        "steps": sim.step_count,
        "sim_time": sim.time,
        "wall_s": total,
        "cells_steps_per_sec": (
            cells * sim.step_count / total if wall and total > 0
            else float("nan")),
    }


class _NullTimers:
    """No-op stand-in so instrumented code needs no branches."""

    @contextmanager
    def phase(self, name):
        yield

    def fence(self, name, *arrays):
        return arrays


NULL_TIMERS = _NullTimers()


@contextmanager
def trace(logdir: str):
    """TensorBoard device trace of the enclosed block."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


# ---------------------------------------------------------------------------
# windowed device tracing (CUP2D_TRACE=start:stop[:logdir])
# ---------------------------------------------------------------------------

class TraceWindow:
    """Windowed `jax.profiler` tracing driven by the step counter: the
    trace wraps exactly steps ``[start, stop)``, so a production run
    captures a TensorBoard trace of a few warmed steps without paying
    profiler overhead (or trace-file volume) for the whole run.

    The driver calls :meth:`maybe_start` BEFORE attempting a step and
    :meth:`maybe_stop` AFTER it completes (with the post-step counter);
    ``>=`` comparisons keep a restarted run from arming a window its
    step range already passed. :meth:`close` stops a still-open trace
    at loop exit (a window past ``tend`` must not leave the profiler
    running)."""

    def __init__(self, start: int, stop: int, logdir: str = "trace"):
        if not (0 <= int(start) < int(stop)):
            raise ValueError(
                f"trace window needs 0 <= start < stop, got "
                f"{start}:{stop}")
        self.start = int(start)
        self.stop = int(stop)
        self.logdir = logdir
        self.active = False
        self.done = False

    @classmethod
    def from_env(cls) -> Optional["TraceWindow"]:
        """Latch CUP2D_TRACE once (the sanctioned read site — see
        tests/test_env_latch.py). A typo'd spec raises instead of
        silently arming nothing (the CUP2D_FAULTS principle: a trace
        window that never fires measures nothing)."""
        spec = os.environ.get("CUP2D_TRACE", "")
        if not spec:
            return None
        parts = spec.split(":", 2)
        if len(parts) < 2:
            raise ValueError(
                f"CUP2D_TRACE={spec!r}: expected start:stop[:logdir]")
        try:
            start, stop = int(parts[0]), int(parts[1])
        except ValueError:
            raise ValueError(
                f"CUP2D_TRACE={spec!r}: start/stop must be integers")
        logdir = parts[2] if len(parts) == 3 and parts[2] else "trace"
        return cls(start, stop, logdir)

    def maybe_start(self, step_count: int) -> None:
        """Arm the trace before stepping ``step_count`` if the window
        opens here."""
        if self.active or self.done or step_count < self.start \
                or step_count >= self.stop:
            return
        jax.profiler.start_trace(self.logdir)
        self.active = True
        from .resilience import record_event
        record_event(event="trace_start", step=step_count,
                     logdir=self.logdir)

    def maybe_stop(self, step_count: int) -> None:
        """Close the trace once the post-step counter reaches the
        window end."""
        if self.active and step_count >= self.stop:
            self._stop(step_count)

    def close(self) -> None:
        if self.active:
            self._stop(None)

    def _stop(self, step_count) -> None:
        jax.profiler.stop_trace()
        self.active = False
        self.done = True
        from .resilience import record_event
        record_event(event="trace_stop", step=step_count,
                     logdir=self.logdir)


# ---------------------------------------------------------------------------
# host-side counters: jit recompiles, device_get pulls, HBM high-water
# ---------------------------------------------------------------------------

# active counter instances; the jax-level hooks dispatch to whatever is
# active (jax.monitoring has no per-listener deregistration, and
# un-wrapping jax.device_get under someone else's later monkeypatch
# would drop their wrapper — the pass-through hooks are inert while no
# counter is active)
_ACTIVE_COUNTERS: list = []
_LISTENER_ON = False

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def _on_compile(event, duration, **kw):
    if event == _COMPILE_EVENT:
        from . import tracing
        if tracing.compiles_suppressed():
            # a flight-recorder memory-ledger re-lower is compiling:
            # ledger-internal, invisible to HostCounters AND to the
            # compile ledger itself (equal-compile-count contract)
            return
        for c in _ACTIVE_COUNTERS:
            c.jit_compiles += 1
        tracing._note_compile(float(duration))


def _install_hooks() -> None:
    global _LISTENER_ON
    if not _LISTENER_ON:
        _LISTENER_ON = True
        jax.monitoring.register_event_duration_secs_listener(_on_compile)
    # marker-checked (not a one-shot flag): a test monkeypatch that
    # saved/restored jax.device_get around an install would otherwise
    # silently unwind the wrapper forever
    if not getattr(jax.device_get, "_cup2d_counting", False):
        orig = jax.device_get

        def _counting_device_get(x):
            for c in _ACTIVE_COUNTERS:
                c.device_gets += 1
            return orig(x)

        _counting_device_get._cup2d_counting = True
        jax.device_get = _counting_device_get


def _note_state_gather() -> None:
    """io._gather_state reports each FULL D2H state gather here: the
    device snapshot ring exists so steady-state supervised steps make
    zero of these (disk checkpoints and post-mortems only), and the CI
    sync guard asserts it through this counter."""
    for c in _ACTIVE_COUNTERS:
        c.state_gathers += 1


def hbm_peak_bytes() -> Optional[int]:
    """HBM high-water mark of the first local device, or None where the
    backend reports no allocator stats (CPU)."""
    try:
        ms = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if not ms:
        return None
    peak = ms.get("peak_bytes_in_use")
    return int(peak) if peak is not None else None


class HostCounters:
    """Host-side observability counters for one run.

    - ``jit_compiles``: XLA backend compiles since :meth:`install`
      (the `jax.monitoring` backend-compile event — a steady-state step
      must trigger ZERO of these; `tests/test_telemetry.py` guards it).
    - ``device_gets``: explicit device→host pulls (`jax.device_get`
      calls — the drivers' batched per-step pull discipline makes this
      exactly one per step on the hot paths, and under the lagged
      StepGuard verdict that one pull is issued AFTER the next step's
      dispatch, off the critical path).
    - ``state_gathers``: full D2H state gathers (io._gather_state) —
      zero in steady state since the device snapshot ring; nonzero only
      at disk checkpoints/post-mortems.
    - HBM high-water via :func:`hbm_peak_bytes` (absolute, not delta:
      the allocator reports a process-lifetime peak).

    ``install``/``uninstall`` only toggle membership in the active set;
    the underlying hooks are process-wide pass-throughs (see
    ``_install_hooks``) and never removed."""

    def __init__(self):
        self.jit_compiles = 0
        self.device_gets = 0
        self.state_gathers = 0

    def install(self) -> "HostCounters":
        _install_hooks()
        if self not in _ACTIVE_COUNTERS:
            _ACTIVE_COUNTERS.append(self)
        return self

    def uninstall(self) -> None:
        if self in _ACTIVE_COUNTERS:
            _ACTIVE_COUNTERS.remove(self)

    def snapshot(self) -> dict:
        return {"jit_compiles": self.jit_compiles,
                "device_gets": self.device_gets,
                "state_gathers": self.state_gathers}


# ---------------------------------------------------------------------------
# the per-step metrics stream
# ---------------------------------------------------------------------------

# THE frozen record key set (schema-stability golden test). Keys are
# always present; fields that do not apply to a path (AMR shape on a
# uniform run, comm volume on a single device, counters when disabled)
# are null — consumers key on names, never on presence.
METRICS_SCHEMA_VERSION = 12
METRICS_KEYS = (
    "schema", "step", "t", "dt", "wall_ms",
    # solver health + timestep state (the step's existing diag pull).
    # On a FLEET record (schema v3) these scalar slots carry the
    # fleet-conservative aggregate — umax/iters/residual/div_linf max,
    # dt/dt_next min, energy sum, converged all, stalled any — and the
    # per-member vectors live in member_health below.
    "umax", "dt_next",
    "poisson_iters", "poisson_residual",
    "poisson_converged", "poisson_stalled",
    # solve-path attribution (schema v4, PR 6): the ACTIVE Poisson
    # path latch as a string (drivers' .poisson_mode — CUP2D_POIS mode
    # + trigger state) and the per-step preconditioner/MG cycle count
    # (rides the one diag pull), so an A/B run is attributable from
    # metrics.jsonl alone. The VALUE vocabulary grew twice without a
    # schema bump (no keys moved): uniform "bicgstab+mg | fas | fas-f",
    # forest "bicgstab+jacobi | bicgstab+twolevel | bicgstab+fft |
    # fas+forest | fas-f+forest" (PR 13 — forest-native FAS as the
    # full solver; there precond_cycles == poisson_iters, one mg_solve
    # cycle per outer iteration, vs the Krylov arms' 2 M-applies/iter).
    # Schema v12 (ISSUE 20) adds the uniform-family DIRECT tokens:
    # "fftd" (doubly-periodic pure-spectral solve) and "fftd+tridiag"
    # (one periodic axis FFT-diagonalized, per-mode Thomas solves on
    # the wall axis) — both report poisson_iters == 1 by contract and
    # precond_cycles == 0 (no hierarchy runs)
    "poisson_mode", "precond_cycles",
    # kernel-tier attribution (schema v6, PR 9): the ACTIVE advection
    # kernel tier latch (drivers' .kernel_tier — xla | pallas-fused |
    # pallas-fused-bf16) and the hot-loop storage-precision contract
    # (.prec_mode — f32|f64|bf16), so a kernel-tier A/B run is
    # attributable from metrics.jsonl alone, like poisson_mode. The
    # VALUE vocabulary grew without a schema bump (ISSUE 16, no keys
    # moved): BC'd fused tiers suffix the per-face token — e.g.
    # "pallas-fused+bc(in(1,0)[parabolic],out,fs,fs)" — captured at
    # dispatch through the _Pending lagged-commit rule like
    # poisson_mode
    "kernel_tier", "prec_mode",
    # smoother-tier attribution (schema v11, ISSUE 19): the pressure
    # hierarchy's sweep-chain implementation latch (drivers'
    # .smoother_tier — xla | strip | strip+bf16, with "+bf16"
    # suffixing whichever base the shape gate left armed), riding the
    # same diag-then-driver pull as kernel_tier, so a memory-tiered
    # FAS A/B run is attributable from metrics.jsonl alone
    "smoother_tier",
    # boundary-condition attribution (schema v8, ISSUE 12): the
    # driver's compact per-face BCTable token string (.bc_table — e.g.
    # "fs,fs,fs,fs" legacy box, "ns,ns,ns,ns(1,0)" lid-driven cavity;
    # schema v12 adds the "pd" face token — "pd,pd,pd,pd" for the
    # doubly-periodic turbulence catalog, "pd,pd,ns,ns" periodic
    # channels) and the case-registry tag (.case — cavity|channel|
    # cylinder|tgv_periodic|shear_layer|turb2d, null outside -case
    # runs), so a record says WHICH physics scenario it measured, like
    # poisson_mode says which solve path
    "bc_table", "case",
    # fused on-device physics invariants (watchdog inputs)
    "energy", "div_linf",
    # AMR shape
    "n_blocks", "blocks_per_level", "refines", "coarsens",
    # comm volume (shard surface-exchange plan, per one vec3 exchange)
    "halo_real_bytes", "halo_padded_bytes",
    # host-side counters (per-step deltas; hbm peak is absolute);
    # state_gathers counts FULL D2H state gathers — zero in guarded
    # steady state since the device snapshot ring (schema v2)
    "jit_compiles", "device_gets", "state_gathers", "hbm_peak_bytes",
    # supervision state (schema v2): device snapshot ring HBM footprint
    # (absolute bytes) + replayed-step delta of the snapshot-cadence
    # recovery path — the D2H win made visible in post --metrics
    "snap_ring_bytes", "replayed_steps",
    # elastic topology (schema v5, PR 7): the current topology epoch
    # (bumped by each survivor re-mesh agreement — 0 for a run that
    # never lost a host), the cumulative re-mesh count, and the wall
    # cost of any re-mesh that landed since the previous record (null
    # on ordinary steps) — a topology loss and its recovery are
    # attributable from metrics.jsonl alone
    "topology_epoch", "remesh_count", "remesh_ms",
    # host-redundant mirror tier (schema v9, PR 17): HBM footprint of
    # the held neighbor-mirror payloads (absolute bytes; null with the
    # tier off), the enqueue-side mirror cost landed since the
    # previous record (delta ms, null when none), and the rung the
    # LAST recovery restored from ("ring"|"mirror"|"disk", null until
    # a recovery happens) — a real-loss resume is attributable from
    # metrics.jsonl alone
    "mirror_bytes", "mirror_ms", "restore_source",
    # fleet batching (schema v3, fleet.py): member count of the fused
    # dispatch, its throughput in member-steps/s (B / wall of the one
    # dispatch — THE dispatch-amortization metric), and per-member
    # solver health folded into the one record as {key: [B values]}
    "fleet_members", "member_steps_per_s", "member_health",
    # fleet serving (schema v7, fleet.FleetServer): slot-pool gauges —
    # live member count, occupancy fraction of the padded pool,
    # cumulative admissions/evictions, and the request-queue depth.
    # Null outside -serve. With per-client streams attached
    # (ClientStreams) the per-member rows move to clients/<id>.jsonl
    # and member_health above is null on serving records.
    "active_members", "occupancy", "admitted", "evicted",
    "queue_depth",
    # flight recorder (schema v10, tracing.FlightRecorder): cumulative
    # span count (absolute — the ring drains to its own spans.jsonl,
    # the record only gauges volume), cumulative attributed compile
    # wall ms, and the summed memory_analysis footprint of every
    # ledgered executable (argument+output+temp+generated-code bytes;
    # null until a capture lands). Null with no recorder attached —
    # all three are host state, same zero-pull discipline as counters
    "span_count", "compile_ms_total", "hbm_exec_bytes",
    # merged PhaseTimers wall times (per-step deltas, ms)
    "phase_ms",
)

_SERVE_KEYS = ("active_members", "occupancy", "admitted", "evicted",
               "queue_depth")

_DIAG_KEYS = ("umax", "dt_next", "poisson_iters", "poisson_residual",
              "poisson_converged", "poisson_stalled", "energy",
              "div_linf", "precond_cycles")

_INT_KEYS = {"poisson_iters", "precond_cycles"}
_BOOL_KEYS = {"poisson_converged", "poisson_stalled", "finite"}


def _jsonable(key: str, v):
    if v is None:
        return None
    if key in _INT_KEYS:
        return int(v)
    if key in _BOOL_KEYS:
        return bool(v)
    return float(v)


# fleet records (schema v3): how each per-member [B] diag vector folds
# into the record's scalar slot — conservative aggregates (the value an
# alerting consumer should key on), with the full vectors preserved in
# member_health
_FLEET_AGG = {
    "umax": np.max, "dt_next": np.min,
    "poisson_iters": np.max, "poisson_residual": np.max,
    "poisson_converged": np.all, "poisson_stalled": np.any,
    "energy": np.sum, "div_linf": np.max,
    "precond_cycles": np.max,
}

# the per-member vectors folded into member_health (diag keys plus the
# health/clock extras the guard's pull carries)
_MEMBER_KEYS = _DIAG_KEYS + ("finite", "dt")


def _member_list(key: str, v):
    return [_jsonable(key, x) for x in np.asarray(v).ravel()]


class MetricsRecorder:
    """Assembles one :data:`METRICS_KEYS` record per step and streams
    it through ``sink`` (a ``resilience.EventLog`` — process-0 JSONL,
    unified with the PR-2 event stream; ``None`` returns records
    without writing, the bench path).

    The record costs no device work: every diag scalar arrives in the
    step's one existing batched pull (on library paths that keep diag
    scalars on device, ONE `device_get` fetches the union — same policy
    as ``resilience.health_verdict``), the AMR histogram is host numpy
    cached per topology version, and counters/timers are host state."""

    def __init__(self, sink=None, counters: Optional[HostCounters] = None,
                 timers: Optional[PhaseTimers] = None, guard=None,
                 server=None, flight=None):
        self.sink = sink
        self.counters = counters
        self.timers = timers
        self.guard = guard          # resilience.StepGuard, opt-in
        self.server = server        # fleet.FleetServer, opt-in (v7)
        self.flight = flight        # tracing.FlightRecorder, opt-in (v10)
        self._last_time: Optional[float] = None
        self._last_counters = counters.snapshot() if counters else None
        self._last_phase: dict = dict(timers.acc) if timers else {}
        self._last_regrid = (0, 0)
        self._last_replayed = 0
        self._last_remesh_ms = 0.0
        self._last_mirror_ms = 0.0
        self._lvl_cache = (None, None, None)   # (version, hist, n)

    def prime(self, sim) -> None:
        """Anchor the dt baseline to the sim's current time (call once
        before the loop; the first record's dt is null otherwise)."""
        self._last_time = float(sim.time)
        if hasattr(sim, "_n_refined"):
            self._last_regrid = (sim._n_refined, sim._n_coarsened)

    # -- assembly ------------------------------------------------------
    def record(self, sim, diag: dict, wall_ms: Optional[float] = None
               ) -> dict:
        """One record from a driver sim (uniform or forest) after a
        completed step; emits into the sink and returns the record."""
        rec = self.record_step(
            step=sim.step_count, t=float(sim.time), diag=diag,
            wall_ms=wall_ms, sim=sim)
        return rec

    def record_step(self, *, step: int, t: float, diag: dict,
                    wall_ms: Optional[float] = None, sim=None,
                    dt: Optional[float] = None) -> dict:
        vals = {k: diag[k] for k in _MEMBER_KEYS if k in diag}
        if any(isinstance(v, jax.Array) for v in vals.values()):
            vals = jax.device_get(vals)   # library-path fallback: 1 pull
        # fleet record (schema v3): [B]-vector diags — fold the
        # per-member detail into member_health, put the conservative
        # aggregate in the scalar slots
        vecs = [np.asarray(v) for v in vals.values() if np.ndim(v) >= 1]
        fleet_b = int(vecs[0].shape[0]) if vecs else 0
        member_health = None
        if fleet_b:
            member_health = {k: _member_list(k, v)
                             for k, v in vals.items()
                             if np.ndim(v) >= 1}
            vals = {k: (_FLEET_AGG[k](np.asarray(v))
                        if np.ndim(v) >= 1 and k in _FLEET_AGG else v)
                    for k, v in vals.items()}
        if dt is not None and np.ndim(dt) >= 1:
            if member_health is not None:
                member_health["dt"] = _member_list("dt", dt)
            dt = float(np.min(dt))    # the pacing (slowest-dt) member
        if dt is None:
            dt = (t - self._last_time) if self._last_time is not None \
                else None
        self._last_time = t
        rec = {
            "schema": METRICS_SCHEMA_VERSION,
            "step": int(step),
            "t": float(t),
            "dt": float(dt) if dt is not None else None,
            "wall_ms": round(wall_ms, 3) if wall_ms is not None else None,
        }
        for k in _DIAG_KEYS:
            rec[k] = _jsonable(k, vals.get(k))
        # the active solve-path latch (schema v4): a host string — from
        # the diag when a producer supplies one (bench), else the
        # driver's .poisson_mode property; never a device value
        pm = diag.get("poisson_mode")
        if pm is None and sim is not None:
            pm = getattr(sim, "poisson_mode", None)
        rec["poisson_mode"] = str(pm) if pm is not None else None
        # kernel-tier attribution (schema v6) and BC/case attribution
        # (schema v8): same diag-then-driver pull as poisson_mode —
        # host strings from constructor latches (.bc_table is the
        # table's token string, .case the case-registry tag)
        for key in ("kernel_tier", "prec_mode", "smoother_tier",
                    "bc_table", "case"):
            kv = diag.get(key)
            if kv is None and sim is not None:
                kv = getattr(sim, key, None)
            rec[key] = str(kv) if kv is not None else None
        rec.update(self._amr_fields(sim))
        rec.update(self._comm_fields(sim))
        rec.update(self._counter_fields())
        rec.update(self._guard_fields())
        rec["fleet_members"] = fleet_b or None
        rec["member_steps_per_s"] = (
            round(fleet_b * 1e3 / wall_ms, 3)
            if fleet_b and wall_ms else None)
        # fleet serving gauges (schema v7): host state on the server;
        # null slots on every non-serving record
        serve = (self.server.telemetry_fields()
                 if self.server is not None else {})
        for k in _SERVE_KEYS:
            rec[k] = serve.get(k)
        if (self.server is not None and self.server.clients is not None
                and member_health is not None):
            # the per-client split (schema v7): per-member rows ride
            # their own JSONL streams keyed by client id — the
            # aggregate record keeps only the conservative folds
            self._emit_client_rows(rec, member_health)
            member_health = None
        rec["member_health"] = member_health
        rec.update(self._flight_fields())
        rec["phase_ms"] = self._phase_fields()
        if self.sink is not None:
            self.sink.emit(event="metrics", **rec)
        return rec

    def _emit_client_rows(self, rec: dict, member_health: dict) -> None:
        """One JSONL row per slot that was OCCUPIED during the recorded
        step (``server.step_clients`` — a member retiring at the end of
        that very step must still get its final row; ``client_of`` is
        already cleared by then): the member's slice of the pulled diag
        vectors plus its own clock (``sim.times[m]`` — the aggregate
        record's ``t`` is only the pool min; the retiree's final clock
        survives until the next cycle's refill). Slots parked for the
        whole step have no client and emit nothing."""
        srv = self.server
        sim = srv.sim
        nm = len(next(iter(member_health.values())))
        for m in range(nm):
            cid = srv.step_clients[m]
            if cid is None:
                continue
            row = {k: v[m] for k, v in member_health.items()}
            srv.clients.emit(cid, {
                "event": "metrics", "client": str(cid), "member": m,
                "step": rec["step"], "t": float(sim.times[m]), **row})

    def _amr_fields(self, sim) -> dict:
        f = getattr(sim, "forest", None)
        if f is None:
            return {"n_blocks": None, "blocks_per_level": None,
                    "refines": None, "coarsens": None}
        if self._lvl_cache[0] != f.version:
            order = getattr(sim, "_order", None)
            if order is None:
                order = f.order()
            lv, cnt = np.unique(f.level[order], return_counts=True)
            hist = {str(int(l)): int(c) for l, c in zip(lv, cnt)}
            self._lvl_cache = (f.version, hist, int(len(order)))
        nr = getattr(sim, "_n_refined", 0)
        nc = getattr(sim, "_n_coarsened", 0)
        ref_d = nr - self._last_regrid[0]
        coa_d = nc - self._last_regrid[1]
        self._last_regrid = (nr, nc)
        return {"n_blocks": self._lvl_cache[2],
                "blocks_per_level": self._lvl_cache[1],
                "refines": ref_d, "coarsens": coa_d}

    def _comm_fields(self, sim) -> dict:
        st = getattr(sim, "_comm_stats", None)
        if not st:
            return {"halo_real_bytes": None, "halo_padded_bytes": None}
        return {"halo_real_bytes": int(st["halo_real_bytes"]),
                "halo_padded_bytes": int(st["halo_padded_bytes"])}

    def _counter_fields(self) -> dict:
        if self.counters is None:
            return {"jit_compiles": None, "device_gets": None,
                    "state_gathers": None, "hbm_peak_bytes": None}
        cur = self.counters.snapshot()
        last = self._last_counters or {k: 0 for k in cur}
        self._last_counters = cur
        return {
            "jit_compiles": cur["jit_compiles"] - last["jit_compiles"],
            "device_gets": cur["device_gets"] - last["device_gets"],
            "state_gathers": (cur["state_gathers"]
                              - last.get("state_gathers", 0)),
            "hbm_peak_bytes": hbm_peak_bytes(),
        }

    def _guard_fields(self) -> dict:
        """Supervision telemetry: the device snapshot ring's HBM bytes
        (absolute — host metadata on the arrays, no sync), the
        replayed-step delta of the snapshot-cadence recovery path, and
        the elastic-topology group (schema v5): epoch / cumulative
        re-mesh count / per-record re-mesh wall cost, the mirror-tier
        group (schema v9): held mirror bytes / per-record mirror
        enqueue cost / last restore rung, all host state on the
        guard."""
        if self.guard is None:
            return {"snap_ring_bytes": None, "replayed_steps": None,
                    "topology_epoch": None, "remesh_count": None,
                    "remesh_ms": None, "mirror_bytes": None,
                    "mirror_ms": None, "restore_source": None}
        cur = int(getattr(self.guard, "replayed_steps", 0))
        delta = cur - self._last_replayed
        self._last_replayed = cur
        ms_total = float(getattr(self.guard, "remesh_ms_total", 0.0))
        ms_delta = ms_total - self._last_remesh_ms
        self._last_remesh_ms = ms_total
        mirroring = getattr(self.guard, "mirror_hosts", None) is not None
        mir_total = float(getattr(self.guard, "mirror_ms_total", 0.0))
        mir_delta = mir_total - self._last_mirror_ms
        self._last_mirror_ms = mir_total
        src = getattr(self.guard, "restore_source", None)
        return {"snap_ring_bytes": int(self.guard.ring_nbytes()),
                "replayed_steps": delta,
                "topology_epoch": int(
                    getattr(self.guard, "topology_epoch", 0)),
                "remesh_count": int(
                    getattr(self.guard, "remesh_count", 0)),
                "remesh_ms": (round(ms_delta, 3)
                              if ms_delta > 0 else None),
                "mirror_bytes": (int(self.guard.mirror_nbytes())
                                 if mirroring else None),
                "mirror_ms": (round(mir_delta, 3)
                              if mir_delta > 0 else None),
                "restore_source": (str(src) if src is not None
                                   else None)}

    def _flight_fields(self) -> dict:
        """Flight-recorder gauges (schema v10): host state on the
        recorder — span volume, attributed compile cost, ledgered
        executable footprint. Null slots with no recorder attached."""
        f = self.flight
        if f is None:
            return {"span_count": None, "compile_ms_total": None,
                    "hbm_exec_bytes": None}
        hbm = f.hbm_exec_bytes()
        return {"span_count": int(f.span_count),
                "compile_ms_total": round(f.compile_ms_total, 3),
                "hbm_exec_bytes": int(hbm) if hbm else None}

    def _phase_fields(self) -> Optional[dict]:
        if self.timers is None:
            return None
        cur = dict(self.timers.acc)
        out = {k: round(1e3 * (v - self._last_phase.get(k, 0.0)), 3)
               for k, v in cur.items()
               if v - self._last_phase.get(k, 0.0) > 0.0}
        self._last_phase = cur
        return out


class ClientStreams:
    """Per-client JSONL telemetry (schema v7): one append-only stream
    per serving client id under ``dirpath``, written by the
    MetricsRecorder's serving split — the per-member rows that used to
    exist only folded inside the aggregate record's ``member_health``.
    A session's telemetry thereby survives slot reuse (the slot index
    is an allocator detail; the client id is the identity) and is
    readable per client by ``post --metrics``.

    ``rotate_mb`` caps each stream file: a stream crossing the cap is
    renamed to ``<name>.jsonl.N`` (N ascending in rotation order) and
    reopened fresh — same scheme as ``EventLog``; ``load_metrics``
    reads the segments back in order. Off (None) by default: rotation
    exists for long serving runs, not 200-step CI drills."""

    def __init__(self, dirpath: str, rotate_mb: Optional[float] = None):
        self.dir = dirpath
        os.makedirs(dirpath, exist_ok=True)
        self._files: dict = {}
        self.rotate_bytes = (int(rotate_mb * 2 ** 20)
                             if rotate_mb else None)
        self._seq: dict = {}

    @staticmethod
    def _fname(cid) -> str:
        # client ids come from the request queue — sanitize into a flat
        # filename (no separators, no dot-prefix surprises)
        s = "".join(c if c.isalnum() or c in "-_." else "_"
                    for c in str(cid))
        return (s or "client").lstrip(".") + ".jsonl"

    def path_of(self, cid) -> str:
        return os.path.join(self.dir, self._fname(cid))

    def emit(self, cid, rec: dict) -> None:
        f = self._files.get(cid)
        if f is None:
            f = open(self.path_of(cid), "a")
            self._files[cid] = f
        f.write(json.dumps(rec, sort_keys=True, default=float) + "\n")
        f.flush()
        if self.rotate_bytes and f.tell() >= self.rotate_bytes:
            path = self.path_of(cid)
            f.close()
            seq = self._seq.get(cid, _next_segment_seq(path))
            os.replace(path, f"{path}.{seq}")
            self._seq[cid] = seq + 1
            self._files[cid] = open(path, "a")

    def close(self, cid=None) -> None:
        """Close one client's stream (retire/evict) or all of them."""
        files = ([self._files.pop(cid)] if cid in self._files
                 else list(self._files.values()) if cid is None else [])
        if cid is None:
            self._files.clear()
        for f in files:
            if not f.closed:
                f.close()


def summarize_client(records: list) -> dict:
    """Aggregate one client stream (clients/<id>.jsonl rows) into the
    per-client summary ``post --metrics`` reports: session extent,
    clock, dt/solver-health stats — the per-member slice analogue of
    :func:`summarize_metrics`."""
    recs = [r for r in records if r.get("event", "metrics") == "metrics"]

    def col(key):
        return [r[key] for r in recs if r.get(key) is not None]

    def stats(xs):
        if not xs:
            return None
        return {"mean": round(float(np.mean(xs)), 6),
                "max": round(float(np.max(xs)), 6)}

    return {
        "steps": len(recs),
        "t_first": recs[0]["t"] if recs else None,
        "t_final": recs[-1]["t"] if recs else None,
        "dt": stats(col("dt")),
        "umax_max": (max(col("umax")) if col("umax") else None),
        "energy_last": (col("energy")[-1] if col("energy") else None),
        "poisson_iters": stats(col("poisson_iters")),
        "poisson_residual_max": (max(col("poisson_residual"))
                                 if col("poisson_residual") else None),
        "div_linf_max": (max(col("div_linf"))
                         if col("div_linf") else None),
        "finite_all": (all(col("finite")) if col("finite") else None),
    }


def _next_segment_seq(path: str) -> int:
    """1 + the highest existing numeric rotation suffix of ``path``."""
    import glob
    top = 0
    for p in glob.glob(path + ".*"):
        suf = p[len(path) + 1:]
        if suf.isdigit():
            top = max(top, int(suf))
    return top + 1


def _segment_paths(path: str) -> list:
    """Rotated segments of ``path`` in write order (``path.1`` oldest),
    then the live file itself."""
    import glob
    segs = []
    for p in glob.glob(path + ".*"):
        suf = p[len(path) + 1:]
        if suf.isdigit():
            segs.append((int(suf), p))
    return [p for _, p in sorted(segs)] + [path]


def load_metrics(path: str) -> list:
    """All JSONL records from ``path`` and its rotated segments, in
    write order (mixed event streams are fine; `summarize_metrics`
    filters for ``event == "metrics"``). Torn lines are skipped — use
    :func:`load_metrics_report` to count them."""
    return load_metrics_report(path)[0]


def load_metrics_report(path: str) -> tuple:
    """(records, truncated_records) from ``path`` plus rotated
    segments. A SIGKILL'd run leaves a torn last line (and an empty
    file is a run killed before its first record) — both are facts
    about the run, not read errors, so unparseable lines are counted
    and reported instead of raised. A missing path still raises
    ``FileNotFoundError`` unless rotated segments exist for it."""
    out: list = []
    torn = 0
    paths = [p for p in _segment_paths(path) if os.path.exists(p)]
    if not paths:
        open(path).close()     # surface the original FileNotFoundError
    for p in paths:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    torn += 1
    return out, torn


def summarize_metrics(records: list) -> dict:
    """Aggregate a metrics stream (list of record dicts — from
    :func:`load_metrics` or directly from a recorder) into the summary
    `python -m cup2d_tpu.post --metrics` prints and `bench.py` embeds."""
    recs = [r for r in records if r.get("event", "metrics") == "metrics"]

    def col(key):
        return [r[key] for r in recs if r.get(key) is not None]

    def stats(xs):
        if not xs:
            return None
        return {"mean": round(float(np.mean(xs)), 6),
                "max": round(float(np.max(xs)), 6)}

    energy = col("energy")
    out = {
        "schema": METRICS_SCHEMA_VERSION,
        "steps": len(recs),
        "t_first": recs[0]["t"] if recs else None,
        "t_final": recs[-1]["t"] if recs else None,
        "dt": stats(col("dt")),
        "wall_ms": stats(col("wall_ms")),
        "poisson_iters": stats(col("poisson_iters")),
        "poisson_residual_max": (max(col("poisson_residual"))
                                 if col("poisson_residual") else None),
        # solve-path attribution (schema v4): the distinct paths the
        # run's steps took (the trigger can flip mid-run) + cycle cost
        "poisson_modes": (sorted({str(m) for m in col("poisson_mode")})
                          or None),
        # smoother-tier attribution (schema v11): distinct sweep-chain
        # implementations the run's solves used, like poisson_modes
        "smoother_tiers": (sorted({str(m)
                                   for m in col("smoother_tier")})
                           or None),
        "precond_cycles": stats(col("precond_cycles")),
        "energy_first": energy[0] if energy else None,
        "energy_last": energy[-1] if energy else None,
        "div_linf_max": (max(col("div_linf"))
                         if col("div_linf") else None),
        "jit_compiles_total": (sum(col("jit_compiles"))
                               if col("jit_compiles") else None),
        "device_gets_per_step": stats(col("device_gets")),
        "hbm_peak_bytes": (max(col("hbm_peak_bytes"))
                           if col("hbm_peak_bytes") else None),
        "n_blocks_last": (col("n_blocks")[-1]
                          if col("n_blocks") else None),
        "refines_total": (sum(col("refines"))
                          if col("refines") else None),
        "coarsens_total": (sum(col("coarsens"))
                           if col("coarsens") else None),
        # supervision (schema v2): zero steady-state state_gathers is
        # the device-ring win; replays say what recovery cost
        "state_gathers_total": (sum(col("state_gathers"))
                                if col("state_gathers") else None),
        "snap_ring_bytes": (max(col("snap_ring_bytes"))
                            if col("snap_ring_bytes") else None),
        "replayed_steps_total": (sum(col("replayed_steps"))
                                 if col("replayed_steps") else None),
        # elastic topology (schema v5): a run that never lost a host
        # reports epoch 0 / 0 re-meshes
        "topology_epoch": (col("topology_epoch")[-1]
                           if col("topology_epoch") else None),
        "remesh_count": (col("remesh_count")[-1]
                         if col("remesh_count") else None),
        # mirror tier (schema v9): held redundancy bytes, total
        # enqueue-side mirror cost, and the rung the last recovery
        # restored from — mirror-attributed real-loss resumes show
        # "mirror" here
        "mirror_bytes": (max(col("mirror_bytes"))
                         if col("mirror_bytes") else None),
        "mirror_ms_total": (round(sum(col("mirror_ms")), 3)
                            if col("mirror_ms") else None),
        "restore_source": (col("restore_source")[-1]
                           if col("restore_source") else None),
        # fleet batching (schema v3): member count + the
        # dispatch-amortization throughput metric
        "fleet_members": (col("fleet_members")[-1]
                          if col("fleet_members") else None),
        "member_steps_per_s": stats(col("member_steps_per_s")),
        # fleet serving (schema v7): occupancy stats of the slot pool +
        # final lifecycle counters (admitted/evicted are cumulative
        # gauges — the last value is the run total)
        "active_members": stats(col("active_members")),
        "occupancy": stats(col("occupancy")),
        "admitted_total": (col("admitted")[-1]
                           if col("admitted") else None),
        "evicted_total": (col("evicted")[-1]
                          if col("evicted") else None),
        "queue_depth": stats(col("queue_depth")),
        # flight recorder (schema v10): cumulative span count, compile
        # blame total and the memory-ledger footprint are gauges — the
        # last value is the run total
        "span_count": (col("span_count")[-1]
                       if col("span_count") else None),
        "compile_ms_total": (col("compile_ms_total")[-1]
                             if col("compile_ms_total") else None),
        "hbm_exec_bytes": (col("hbm_exec_bytes")[-1]
                           if col("hbm_exec_bytes") else None),
    }
    # run-report event rows (emitted once at exit by the CLI): the
    # serving-latency distributions and the compile blame ledger ride
    # the same stream; surface the last of each verbatim
    for ev in ("serving_latency", "compile_ledger"):
        rows = [r for r in records if r.get("event") == ev]
        if rows:
            out[ev] = {k: v for k, v in rows[-1].items()
                       if k != "event"}
    return out
