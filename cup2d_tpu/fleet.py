"""Fleet batching: B independent uniform cases in ONE fused dispatch.

Every entry point before this module stepped exactly one case per
process, so small/medium grids leave the device dispatch-bound (the
BASELINE.md adaptive step is tunnel-latency bound at 0.218 s warm vs
21.5 ms device time). Batching independent cases onto one device is the
classic inference-stack throughput lever, and the codebase is shaped
for it: the step core is pure and trivially batchable (every stencil op
in ops/stencil.py is leading-dim agnostic), dt chains on device, and
the diag pull is already one batched ``device_get``. Multi-case
throughput is the same axis AMReX exploits for multiphysics fleets
(arXiv:2009.12009) and the FFT multi-block solver exploits for
massively parallel runs (arXiv:2106.03583).

:class:`FleetSim` advances B independent obstacle-free ``UniformSim``
cases per dispatch:

- the state is one ``FlowState`` with a leading member axis
  ``[B, ...]``; the whole Heun + penalization-free projection step is
  ONE jitted executable regardless of B;
- each member integrates at ITS OWN dt — no lockstep: dt is a ``[B]``
  device vector chained on device from each member's end-state umax,
  and the per-member clocks live in ``times`` (host, settled through
  the same one batched diag pull the single-case drivers pay);
- the pressure solves of all members run in ONE fused Krylov loop
  (``poisson.bicgstab(member_axis=True)``): per-member convergence
  mask, predicate = any member unconverged, converged members frozen
  via select so the extra sweeps are bit-exact identity for them;
- supervision is per-member (``resilience.FleetStepGuard``): a bad
  member restores ONLY its slice of the device snapshot ring and
  replays solo through :meth:`FleetSim.member_step_once`; healthy
  members never rewind.

Contract with the single-case driver (tests/test_fleet.py):
``FleetSim`` with B = 1 is BIT-IDENTICAL to ``UniformSim`` — same
trajectory, equal ``device_get`` counts. For B > 1 each member's
trajectory matches its solo run to <= 1e-12: the advection, projection
and every reduction (umax/energy/Krylov dots) are bit-exact per member
(measured — per-member reductions over ``[B, Ny, Nx]`` reduce the same
elements in the same order as the solo form), but the multigrid
V-cycle's fused elementwise sweep chains compile with different
FMA-contraction choices for member-batched operands (LLVM
vectorization over the leading axis), deviating ~1 ulp per sweep.
Flexible BiCGSTAB absorbs preconditioner inexactness by construction,
so the per-step trajectory deviation stays at ~1e-16..1e-13, the
short warm-start production solves keep IDENTICAL per-member iteration
counts and solver health, and the per-member clock can differ from the
solo clock by at most an ulp per step (the state deviation perturbing
the umax cell's last bit perturbs dt_next's) — pinned by
``tests/test_fleet.py::test_fleet_members_match_solo_runs`` (production
regime — warm deltap guesses, short solves). Long ROUGH solves (~50+
iterations on O(1) residuals) can compound the rounding into a
different — equally converged — Krylov path, so batched-vs-solo
agreement there is at the solve's own convergence target rather than
1e-12; the frozen-member invariance (the select mask) is exact
regardless and pinned separately.

Sharding composes (``mesh=``): when the per-member grid is small,
WHOLE MEMBERS are placed along the existing ``"x"`` mesh axis
(member-parallel — each member's stencils and reductions stay
shard-local, zero per-step halo collectives); big grids fall back to
the spatial x-split of ``ShardedUniformSim`` (with its spmd_safe
stencil forms), where the member axis rides along replicated.
"""

from __future__ import annotations

import os
import time
from collections import deque
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import tracing
from .config import SimConfig
from .ops.pallas_kernels import fused_advect_heun
from .ops.stencil import (
    advect_diffuse_rhs,
    divergence_freeslip,
    divergence_rhs_fused,
    dt_from_umax,
    heun_substage,
    laplacian5_neumann,
)
from .poisson import bicgstab, fft_diag_solve, mg_solve, project_correct
from .uniform import FlowState, UniformGrid, pad_vector, taylor_green_state


def stack_states(states) -> FlowState:
    """Stack per-member FlowStates into one fleet state [B, ...]."""
    return FlowState(*(jnp.stack(list(leaves))
                       for leaves in zip(*states)))


def taylor_green_fleet(grid, members: int, amp0: float = 1.0,
                       decay: float = 0.8) -> FlowState:
    """A B-member ensemble of Taylor-Green vortices at geometrically
    decaying amplitudes (member m scaled by ``amp0 * decay**m``): the
    canonical obstacle-free validation case, with per-member umax — so
    every member runs at its OWN CFL dt and the no-lockstep contract is
    exercised for real (identical members would hide a lockstep bug)."""
    base = taylor_green_state(grid)
    return stack_states([
        base._replace(vel=base.vel * (amp0 * decay ** m))
        for m in range(members)])


class FleetSim:
    """Host-side driver for a B-member fleet: owns the shared step
    counter and per-member clocks, jits the fused member-batched step.

    Mirrors the ``UniformSim`` driver contract (``step_once`` /
    ``async_diag`` / ``_force_exact`` / ``_next_dt``) so the StepGuard
    machinery drives it unchanged — except the diag scalars are [B]
    vectors and the guard generalizes the verdict per member
    (resilience.FleetStepGuard).

    Placement (``mesh=``): ``placement="member"`` shards the leading
    member axis over the mesh (small grids — every member's compute is
    shard-local), ``"spatial"`` shards the x-axis like
    ``ShardedUniformSim`` (big grids), ``"auto"`` picks member-parallel
    when B divides the mesh and the per-member grid fits
    ``member_cells_cap`` cells, else spatial.
    """

    def __init__(self, cfg: SimConfig, level: Optional[int] = None,
                 members: int = 1, mesh=None, placement: str = "auto",
                 member_cells_cap: int = 1 << 22, shaped: bool = False,
                 bc=None):
        if members < 1:
            raise ValueError(f"need members >= 1, got {members}")
        self.cfg = cfg
        self.members = int(members)
        self.mesh = mesh
        # shaped membership: per-member obstacle chi/us/udef fields ride
        # the member axis as FROZEN solids (the moving-shape update loop
        # stays solo/AMR-side; the ROADMAP keeps the padded-forest half)
        self.shaped = bool(shaped)
        lvl = cfg.level_start if level is None else level
        nx = cfg.bpdx * cfg.bs << lvl
        ny = cfg.bpdy * cfg.bs << lvl
        if mesh is not None:
            ndev = mesh.devices.size
            if placement == "auto":
                placement = ("member"
                             if members % ndev == 0
                             and nx * ny <= member_cells_cap
                             else "spatial")
            if placement == "member" and members % ndev != 0:
                raise ValueError(
                    f"member placement needs members ({members}) "
                    f"divisible by mesh size {ndev}")
            if placement == "spatial" and nx % ndev != 0:
                raise ValueError(
                    f"spatial placement needs Nx={nx} divisible by "
                    f"mesh size {ndev}")
        else:
            placement = "single"
        self.placement = placement
        # spmd_safe only where spatial axes are actually sharded: the
        # member-parallel layout keeps every member's stencil axes
        # whole on one device, so the fast zero-shift form is safe
        # bc: the pool-wide per-face BCTable (bc.py) — every member of
        # a fleet shares ONE table (the slot-pool executable bakes the
        # edge treatment in; FleetServer._admit refuses mismatches)
        self.grid = UniformGrid(cfg, level,
                                spmd_safe=(placement == "spatial"), bc=bc)
        g = self.grid
        self.state = stack_states([g.zero_state()
                                   for _ in range(self.members)])
        self.times = np.zeros(self.members, dtype=np.float64)
        self.time = 0.0           # min over members (the loop condition)
        self.step_count = 0       # shared: one dispatch = one step for all
        # slot-pool mask (FleetServer): host truth + device mirror.
        # ``_active=None`` keeps the historical unmasked trace; once
        # set_active() is called the mask is ALWAYS passed as a [B]
        # device operand so admit/evict churn never changes the jit
        # signature (zero steady-state recompiles)
        self.active_mask = np.ones(self.members, dtype=bool)
        self._active = None
        self.shapes: list = []    # obstacle-free by construction
        self.case: Optional[str] = None  # case-registry tag (cases.py)
        self.timers = None
        self.force_log = None
        self._next_dt = None      # [B] device vector (end-state dt_next)
        self._force_exact = False
        self.async_diag = False
        out_shardings = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            if placement == "member":
                sv = NamedSharding(mesh, P("x", None, None, None))
                ss = NamedSharding(mesh, P("x", None, None))
            else:
                sv = NamedSharding(mesh, P(None, None, None, "x"))
                ss = NamedSharding(mesh, P(None, None, "x"))
            shardings = FlowState(vel=sv, pres=ss, chi=ss, us=sv, udef=sv)
            self.state = FlowState(*(jax.device_put(a, s) for a, s
                                     in zip(self.state, shardings)))
            out_shardings = (shardings, None)
        self._step = tracing.named_jit(
            "fleet.step", jax.jit(
                self._step_impl, donate_argnums=(0,),
                static_argnames=("exact_poisson",),
                **({"out_shardings": out_shardings}
                   if out_shardings is not None else {})),
            variant=("exact_poisson",))
        self._dt = tracing.named_jit("fleet.dt", jax.jit(self._dt_impl))
        # single-member core for the guard's per-member rewind/replay
        # (the cold path): the SAME pure step the solo driver jits, on
        # one member's slice
        self._member_step = tracing.named_jit(
            "fleet.solo_ladder", jax.jit(
                g.step, donate_argnums=(0,),
                static_argnames=("exact_poisson", "obstacle_terms")),
            variant=("exact_poisson",))
        self._member_dt = tracing.named_jit(
            "fleet.solo_dt", jax.jit(g.compute_dt))
        # slot-pool gather/scatter (FleetServer admit/retire churn):
        # ONE fused executable each, slot index as a device int32
        # operand (any slot, same executable) and the fleet state
        # DONATED on install — an admit/retire costs one dispatch, not
        # a per-field op chain plus a full-state copy
        self._extract_member = tracing.named_jit(
            "fleet.extract", jax.jit(
                lambda state, idx: FlowState(*(a[idx] for a in state))))
        self._install_member = tracing.named_jit(
            "fleet.install", jax.jit(
                lambda state, idx, st: FlowState(
                    *(a.at[idx].set(v) for a, v in zip(state, st))),
                donate_argnums=(0,)))
        self._scatter_next_dt = tracing.named_jit(
            "fleet.scatter_dt", jax.jit(
                lambda nd, idx, v: nd.at[idx].set(v),
                donate_argnums=(0,)))
        # the one-dispatch admit: state install + chained-dt scatter
        # fused, dtv <= 0 meaning "compute the fresh CFL dt from the
        # admitted velocity right here" (bit-identical to
        # grid.compute_dt: the max reduce is order-invariant and
        # dt_from_umax elementwise)
        self._admit_impl = tracing.named_jit(
            "fleet.admit", jax.jit(
                lambda state, nd, idx, st, dtv: (
                    FlowState(*(a.at[idx].set(v)
                                for a, v in zip(state, st))),
                    nd.at[idx].set(jnp.where(dtv > 0, dtv,
                                             g.compute_dt(st.vel)))),
                donate_argnums=(0, 1)))
        # per-slot device indices, transferred once: admit/retire churn
        # re-uses them so a slot op is one dispatch with zero fresh h2d
        self._idx = [jnp.asarray(m, jnp.int32)
                     for m in range(self.members)]
        self._dt_sentinel = jnp.zeros((), g.dtype)  # "fresh dt" flag

    # -- fused member-batched step core -------------------------------
    def _dt_impl(self, vel: jnp.ndarray) -> jnp.ndarray:
        """Per-member CFL dt [B] from the fleet velocity [B,2,Ny,Nx]."""
        g = self.grid
        umax = jnp.max(jnp.abs(vel), axis=(-3, -2, -1))
        return dt_from_umax(umax, jnp.asarray(g.h, g.dtype),
                            g.cfg.nu, g.cfg.cfl)

    @property
    def poisson_mode(self) -> str:
        """Active solve-path latch (telemetry schema v4). Fleet reads
        the grid's latch — this module stays env-read-free by design
        (tests/test_env_latch.py walks it)."""
        return self.grid.poisson_mode

    @property
    def kernel_tier(self) -> str:
        """Active advection-kernel tier (telemetry schema v6) — the
        grid's constructor latch, BC-token-suffixed on BC'd fused
        tiers; under spatial placement the fused tier rides the
        halo-mode kernel (shard_halo.fused_advect_heun_sharded) behind
        the same latch (ISSUE 16 retired the construction refusal)."""
        return self.grid.kernel_tier

    @property
    def prec_mode(self) -> str:
        """Hot-loop storage precision (telemetry schema v6)."""
        return self.grid.prec_mode

    @property
    def smoother_tier(self) -> str:
        """Pressure-hierarchy smoother tier (telemetry schema v11) —
        the pool shares the grid's preconditioner latch."""
        return self.grid.smoother_tier

    @property
    def bc_table(self) -> str:
        """Pool-wide per-face BC token string (telemetry schema v8)."""
        return self.grid.bc_table

    def _pressure_solve(self, rhs: jnp.ndarray, exact: bool):
        """Member-batched ``UniformGrid.pressure_solve``: same
        tolerances/refresh/stall policy and the same CUP2D_POIS solve
        path as the solo driver, ONE fused loop with the per-member
        convergence mask. Under ``fas`` the fleet runs member-batched
        MG cycles (the V-cycle is leading-dim agnostic) with the SAME
        converged-member freeze semantics — extra cycles the loop runs
        for the slowest member are bit-exact identity for converged
        ones (poisson.mg_solve member_axis); exact solves keep Krylov
        exactly like the solo path. Under ``fftd`` (ISSUE 20) the B
        member systems batch through ONE set of transforms — the mode
        axis is embarrassingly parallel — and every member reports
        iters == 1, so the converged-member freeze contract is
        trivially inert: there are no extra sweeps a frozen member
        could diverge under (tests/test_fleet.py pins members == solo
        bit-tight)."""
        g = self.grid
        cfg = self.cfg
        if g.solver_mode == "fftd":
            return fft_diag_solve(
                g.laplacian, rhs, g._fft_plan,
                tol=0.0 if exact else cfg.poisson_tol,
                tol_rel=0.0 if exact else cfg.poisson_tol_rel,
                member_axis=True,
            )
        if g.solver_mode == "fas" and not exact:
            return mg_solve(
                g.laplacian, rhs, g.mg,
                tol=cfg.poisson_tol, tol_rel=cfg.poisson_tol_rel,
                max_cycles=cfg.max_poisson_iterations,
                fmg=g.fas_fmg, member_axis=True,
            )
        return bicgstab(
            g.laplacian,
            rhs,
            M=g.mg if cfg.precond else None,
            tol=0.0 if exact else cfg.poisson_tol,
            tol_rel=0.0 if exact else cfg.poisson_tol_rel,
            max_iter=cfg.max_poisson_iterations,
            max_restarts=100 if exact else cfg.max_poisson_restarts,
            sum_dtype=g.sum_dtype,
            refresh_every=10 if exact else 50,
            stall_iters=20 if exact else 120,
            stall_rtol=0.99 if exact else 0.999,
            member_axis=True,
        )

    def _step_impl(self, state: FlowState, dt: jnp.ndarray,
                   active=None, exact_poisson: bool = False):
        """One fused step of every member: Heun advection-diffusion +
        deltap projection. Obstacle-free by default (the
        identically-zero penalization/chi terms are statically dropped,
        like ``UniformGrid.step(obstacle_terms=False)``); under
        ``shaped=True`` the Brinkman penalization and the chi-weighted
        divergence RHS ride the member axis (per-member obstacles).
        ``dt`` is [B].

        ``active`` (None or a [B] bool vector) is the slot-pool mask:
        inactive slots still ride the fused dispatch — the executable
        is shape-stable across arbitrary admit/evict churn — but every
        one of their outputs is select-frozen to the input state, the
        same trick ``poisson.bicgstab``/``mg_solve`` use for converged
        members. ``active=None`` traces the exact historical unmasked
        graph (bit-preserving for the fixed-B drivers); an all-True
        mask is itself bit-identical to unmasked (``where(True, new,
        old)`` selects ``new`` verbatim), so a serving fleet at full
        occupancy pays nothing but the selects."""
        g = self.grid
        h = g.h
        ih2 = 1.0 / (h * h)
        dt_req = dt
        if active is not None:
            # a dead slot's cached dt_next lane can be anything (an
            # evicted member leaves NaN behind): give dead lanes a
            # finite dt so their lane arithmetic stays NaN-free, and
            # select-freeze every output below
            dt = jnp.where(active, dt, jnp.ones_like(dt))
        dt3 = dt[:, None, None]            # broadcast vs [B, Ny, Nx]
        dt4 = dt[:, None, None, None]      # broadcast vs [B, 2, Ny, Nx]

        # -- advection-diffusion, 2-stage Heun (per-member dt) --
        vel = state.vel
        # dispatch on the BARE tier latch: the kernel_tier property
        # suffixes the BC token for telemetry and would never compare
        # equal to the bare strings here
        if g._kernel_tier != "xla":
            bf16 = g._kernel_tier == "pallas-fused-bf16"
            bc = None if g.bc.is_free_slip else g.bc
            if self.placement == "spatial":
                # spatially sharded pool: the halo-mode kernel behind
                # the explicit ppermute exchange — one executable for
                # all shards, still member-batched on the leading axis
                from .parallel.shard_halo import fused_advect_heun_sharded
                vel = fused_advect_heun_sharded(
                    vel, h, g.cfg.nu, dt, self.mesh, bc=bc, bf16=bf16)
            else:
                # fused megakernel tier, member-batched: the kernel is
                # leading-dim agnostic with a per-member (afac, dfac)
                # row, so B members share ONE dispatch per substage
                vel = fused_advect_heun(
                    vel, h, g.cfg.nu, dt, bc=bc, bf16=bf16)
        else:
            vold = vel
            for c in (0.5, 1.0):
                # grid-level BC dispatch (bc.py): the default table is
                # the legacy pad_vector verbatim; per-face tables paint
                # their ghosts member-batched (dt4 broadcasts the
                # per-member outflow extrapolation speed)
                lab = g.pad_vector_field(vel, 3, dt4)
                rhs = advect_diffuse_rhs(lab, 3, h, g.cfg.nu, dt4)
                vel = heun_substage(vold, c, rhs, ih2)

        # -- deltap pressure projection --
        if self.shaped:
            # Brinkman penalization, member-batched (the SAME scalar
            # chain as UniformGrid.step's obstacle_terms=True branch,
            # so a shaped member matches its solo run to the documented
            # FMA bound): chi/us/udef ride the member axis as frozen
            # per-member obstacle fields
            alpha = jnp.where(state.chi > 0.5,
                              1.0 / (1.0 + g.cfg.lam * dt3), 1.0)
            vel = alpha[:, None] * vel + (1.0 - alpha)[:, None] * state.us
            b = g.poisson_rhs(vel, state.chi, state.udef, dt3)
        else:
            b = g.poisson_rhs(vel, None, None, dt3)
        div_linf = jnp.max(jnp.abs(b), axis=(-2, -1)) * (dt / (h * h))
        b = b - g.laplacian(state.pres)
        if active is not None:
            # zero the dead rows of the Poisson RHS: their initial
            # residual is 0 <= max(tol, tol_rel*0), so the
            # member-batched solvers mark them done AT ITERATION ZERO
            # with inert diag (iters=0, residual=0, converged) and the
            # existing converged-member freeze keeps their lanes exact
            # identity through every sweep the live members need
            b = jnp.where(active[:, None, None], b, jnp.zeros_like(b))
        res = self._pressure_solve(b, exact_poisson)
        # bare latch again; under spatial placement the correction
        # kernel's strip DMA cannot be GSPMD-partitioned, so the
        # sharded pool keeps the XLA epilogue (pinned sharded==single)
        corr_tier = ("xla" if self.placement == "spatial"
                     else g._kernel_tier)
        vel, pres = project_correct(
            res.x, state.pres, vel, h, dt,
            spmd_safe=g.spmd_safe, mean_axes=(-2, -1),
            tier=corr_tier,
            remove_mean=g.bc.all_neumann, grad_signs=g._psigns,
            periodic=g._paxes)
        if active is not None:
            # freeze dead slots: state, diag and clock all read the
            # UNSTEPPED values (bit-exact slot preservation under
            # arbitrary co-member churn)
            vel = jnp.where(active[:, None, None, None], vel, state.vel)
            pres = jnp.where(active[:, None, None], pres, state.pres)
            div_linf = jnp.where(active, div_linf,
                                 jnp.zeros_like(div_linf))

        # -- per-member diag (the one batched pull's payload) --
        umax = jnp.max(jnp.abs(vel), axis=(-3, -2, -1))
        vv = vel.astype(g.sum_dtype) if g.sum_dtype is not None else vel
        energy = 0.5 * h * h * jnp.sum(vv * vv, axis=(-3, -2, -1))
        finite = (jnp.all(jnp.isfinite(vel), axis=(-3, -2, -1))
                  & jnp.all(jnp.isfinite(pres), axis=(-2, -1)))
        diag = {
            "poisson_iters": res.iters,
            "poisson_residual": res.residual,
            "poisson_stalled": res.stalled,
            "poisson_converged": res.converged,
            "finite": finite,
            "umax": umax,
            "energy": energy,
            "div_linf": div_linf,
            # per-member preconditioner-cycle counts [B] (schema v4;
            # the ONE shared accounting convention)
            "precond_cycles": g.precond_cycles(res, exact_poisson),
            "dt_next": dt_from_umax(umax, jnp.asarray(h, g.dtype),
                                    g.cfg.nu, g.cfg.cfl),
        }
        if active is not None:
            # the per-member clock increments ride the one pull: a dead
            # slot advances by exactly 0.0 (its host clock freezes with
            # its state); the requested dt — NaN lanes included — never
            # reaches the times accumulator
            diag["dt"] = jnp.where(active, dt_req,
                                   jnp.zeros_like(dt_req))
        return state._replace(vel=vel, pres=pres), diag

    # -- driver contract (StepGuard-compatible) -----------------------
    def step_once(self, dt=None):
        """One fused fleet step. ``dt``: None (chained per-member
        device dt), a scalar (all members), or a [B] vector. One
        batched diag pull per step for the WHOLE fleet — or none under
        ``async_diag`` (the guard's lagged verdict pulls it)."""
        g = self.grid
        if dt is None:
            dt = (self._next_dt if self._next_dt is not None
                  else self._dt(self.state.vel))
        dt_dev = jnp.asarray(dt, g.dtype)
        if dt_dev.ndim == 0:
            dt_dev = jnp.full((self.members,), dt_dev, g.dtype)
        exact = self.step_count < 10 or self._force_exact
        timers = self.timers
        if timers is None:
            from .profiling import NULL_TIMERS
            timers = NULL_TIMERS
        with timers.phase("step"):
            self.state, diag = self._step(self.state, dt_dev,
                                          self._active,
                                          exact_poisson=exact)
            diag = dict(diag)
            if "dt" not in diag:
                # unmasked path: every slot advances by the dispatched
                # dt (the masked trace returns its own zeroed-dead-lane
                # vector from inside the jit)
                diag["dt"] = dt_dev   # rides the one pull
            self._next_dt = diag["dt_next"]
            if self.async_diag:
                # -profile must still attribute device time to the
                # phase (fence = the documented cost of profiling, as
                # on the other drivers); the no-timers path stays
                # fence-free
                timers.fence("step", self.state.vel)
                self.step_count += 1
                return diag
            diag = jax.device_get(diag)   # the natural phase fence
        self.times = self.times + np.asarray(diag["dt"], np.float64)
        self.time = self._fleet_time()
        self.step_count += 1
        return diag

    def _fleet_time(self) -> float:
        """The loop-condition clock: min over LIVE slots — a retired
        slot's frozen clock must not pin the fleet time at its
        retirement point (empty pool: min over all, i.e. unchanged)."""
        act = self.active_mask
        if act.all() or not act.any():
            return float(self.times.min())
        return float(self.times[act].min())

    def set_active(self, mask) -> None:
        """Install the per-slot active mask (FleetServer lifecycle).
        From the first call on, the fused step runs the masked trace
        permanently — including at full occupancy, where the all-True
        selects are bit-identity — so slot churn re-uses ONE compiled
        executable."""
        m = np.asarray(mask, dtype=bool)
        if m.shape != (self.members,):
            raise ValueError(
                f"active mask shape {m.shape} != ({self.members},)")
        if self._active is not None \
                and np.array_equal(m, self.active_mask):
            # the device mirror already holds this pattern — in steady
            # full-pool churn a retire at one cycle's end and the
            # refill at the next cycle's start cancel out, so the mask
            # usually never changes value and the h2d push is skipped
            return
        self.active_mask = m.copy()
        self._active = jnp.asarray(self.active_mask)

    # -- per-member access (guard rewind + server admit/retire) -------
    # The slot index is passed as a DEVICE int32 operand, not a Python
    # int: a baked int index would compile one gather/scatter
    # executable per distinct slot, and the serving loop's admit/evict
    # churn touches arbitrary slots — with the index as an operand, ONE
    # executable covers the whole pool (the zero-recompile contract).
    def member_state(self, m: int) -> FlowState:
        """Member ``m``'s slice as a solo FlowState (fresh arrays)."""
        return self._extract_member(self.state, self._idx[m])

    def set_member_state(self, m: int, st: FlowState) -> None:
        """Install a solo FlowState into member ``m``'s slice; every
        other member's values pass through bit-unchanged (one donated
        fused scatter — the old state buffers are reused in place)."""
        self.state = self._install_member(self.state, self._idx[m], st)

    def set_member_next_dt(self, m: int, dt_next) -> None:
        if self._next_dt is None:
            # materialize the cache so a pre-first-step admission's dt
            # lands in it: the other lanes get exactly the dt step_once
            # would have computed from the current velocities
            self._next_dt = self._dt(self.state.vel)
        self._next_dt = self._scatter_next_dt(
            jnp.asarray(self._next_dt), self._idx[m],
            jnp.asarray(dt_next, self.grid.dtype))

    def admit_member(self, m: int, st: FlowState,
                     next_dt=None) -> None:
        """The serving hot path: install ``st`` into slot ``m`` AND
        scatter its chained dt in ONE donated dispatch.
        ``next_dt=None`` computes the fresh CFL dt from the admitted
        velocity inside the same executable (bit-identical to
        ``grid.compute_dt`` on the solo slice)."""
        if self._next_dt is None:
            self._next_dt = self._dt(self.state.vel)
        dtv = (self._dt_sentinel if next_dt is None
               else jnp.asarray(next_dt, self.grid.dtype))
        self.state, self._next_dt = self._admit_impl(
            self.state, jnp.asarray(self._next_dt), self._idx[m],
            st, dtv)

    def member_step_once(self, m: int, dt=None, exact: bool = False):
        """Advance ONLY member ``m`` one step through the solo
        single-member executable (the guard's replay/retry path —
        recovery is the cold path; the fused dispatch is the hot one).
        Leaves the shared step counter, the fleet dt cache and the
        clocks untouched: the caller (FleetStepGuard) owns those.
        Returns the solo diag dict (device scalars)."""
        st = self.member_state(m)
        if dt is None:
            dt = float(self._member_dt(st.vel))
        st, diag = self._member_step(
            st, jnp.asarray(dt, self.grid.dtype),
            exact_poisson=bool(exact),
            obstacle_terms=bool(self.shaped))
        self.set_member_state(m, st)
        diag = dict(diag)
        diag["dt"] = float(dt)
        return diag

    def seed_taylor_green(self, amp0: float = 1.0,
                          decay: float = 0.8) -> None:
        """Seed the amplitude-laddered Taylor-Green ensemble (the CLI
        fleet mode's t=0 state: obstacle-free zero state would make a
        trivial run; the ladder gives every member its own umax/dt)."""
        st = taylor_green_fleet(self.grid, self.members, amp0, decay)
        if self.mesh is not None:
            st = FlowState(*(jax.device_put(np.asarray(a), b.sharding)
                             for a, b in zip(st, self.state)))
        self.state = st


# ---------------------------------------------------------------------------
# continuous-batching slot-pool serving
# ---------------------------------------------------------------------------

@dataclass
class FleetRequest:
    """One client session waiting for a fleet slot.

    Exactly one of ``state`` / ``checkpoint`` provides the admission
    state: ``state`` is a solo :class:`FlowState` at clock ``t0``;
    ``checkpoint`` is a per-member session directory written by
    ``io.save_member_checkpoint`` — admission from it resumes the
    session bit-exact (state, clock and the chained per-member dt all
    round-trip losslessly). The member is retired once its clock
    reaches ``t_end``; ``next_dt`` (optional) overrides the first
    step's dt (otherwise the checkpoint's chained dt, else a fresh CFL
    dt from the admitted velocity).

    ``bc`` (optional) declares the session's expected per-face
    :class:`~cup2d_tpu.bc.BCTable`: the pool's slot executables bake
    ONE table's edge treatment in, so admission refuses a mismatch
    loudly instead of stepping the session under the wrong ghosts.
    None means "whatever the pool runs" (back-compat)."""
    client_id: str
    state: Optional[FlowState] = None
    checkpoint: Optional[str] = None
    t0: float = 0.0
    t_end: float = float("inf")
    next_dt: Optional[float] = None
    bc: Optional[object] = None


class FleetServer:
    """Continuous-batching serving loop over a ``FleetSim`` slot pool.

    The inference-stack pattern on a flow fleet: a FIXED-B padded pool
    whose step executable never changes shape, with a per-slot active
    mask (``FleetSim.set_active``). Finished members retire (their
    session checkpoint lands in ``session_dir``), aborted members are
    EVICTED by the guard's per-member ladder (``on_member_abort`` —
    the slot is freed instead of the fleet dying), and free slots
    refill from the request queue — all without recompiling: the mask
    is a device operand, slot installs/slices run through
    device-int32-indexed executables, and dead lanes are select-frozen
    inside the fused step. A live member's trajectory is bit-identical
    regardless of co-member churn (its lane's arithmetic is
    elementwise-independent and dead/alive co-lanes only change values
    OTHER lanes never read).

    Lifecycle events (``member_admit`` / ``member_retire`` /
    ``member_evict``) go to ``event_log``; the serving gauges ride the
    schema-v7 metrics record (``telemetry_fields``); per-client JSONL
    streams split out of ``member_health`` when ``clients_dir`` is set
    (profiling.ClientStreams — the MetricsRecorder writes them).
    """

    def __init__(self, sim: FleetSim, *, guard=None,
                 session_dir: Optional[str] = None,
                 event_log=None, clients_dir: Optional[str] = None,
                 clients_rotate_mb=None, latency=None):
        self.sim = sim
        self.guard = guard
        if guard is not None:
            # wire the eviction rung: an exhausted per-member ladder
            # frees the slot (member_aborted event) instead of raising
            guard.on_member_abort = self._on_member_abort
        self.session_dir = session_dir
        self.event_log = event_log
        # tracing.ServingLatency (or None): queue-wait / admit-to-
        # first-step / per-step histograms, host clocks only
        self.latency = latency
        self.queue: deque = deque()
        self.active = np.zeros(sim.members, dtype=bool)
        self.t_end = np.full(sim.members, np.inf)
        self.client: list = [None] * sim.members
        self.admitted = 0
        self.retired = 0
        self.evicted = 0
        self.step_clients: list = [None] * sim.members
        self.clients = None
        if clients_dir is not None:
            from .profiling import ClientStreams
            self.clients = ClientStreams(clients_dir,
                                         rotate_mb=clients_rotate_mb)
        # one cached zero template: EVICTION re-zeroes the slot through
        # the same one-executable scatter admission uses (an aborted
        # member's NaN state must not leak into the masked step's
        # member_health diag rows). Plain retirement skips the zero —
        # the parked contents are the retiree's final state, finite,
        # mask-frozen, and fully overwritten by the next admit — so a
        # retire costs ZERO dispatches.
        self._zero = sim.grid.zero_state()
        # device-mask sync is coalesced: slot changes mark the mask
        # dirty and step() pushes it ONCE per cycle before dispatch
        self._mask_dirty = False
        sim.set_active(self.active)

    # -- client API ---------------------------------------------------
    def submit(self, req: FleetRequest) -> None:
        """Enqueue a session; it is admitted at the next free slot."""
        if self.latency is not None:
            self.latency.on_submit(req.client_id)
        self.queue.append(req)

    def client_of(self, m: int):
        """The client id occupying slot ``m`` (None when free)."""
        return self.client[m]

    @property
    def occupancy(self) -> float:
        return float(self.active.sum()) / self.sim.members

    def telemetry_fields(self) -> dict:
        """The schema-v7 serving gauges (host-side, no device work)."""
        return {
            "active_members": int(self.active.sum()),
            "occupancy": round(self.occupancy, 6),
            "admitted": int(self.admitted),
            "evicted": int(self.evicted),
            "queue_depth": len(self.queue),
        }

    def close(self) -> None:
        if self.clients is not None:
            self.clients.close()

    # -- slot lifecycle -----------------------------------------------
    def _emit(self, **fields) -> None:
        if self.event_log is not None:
            self.event_log.emit(**fields)

    def _fill_slots(self) -> int:
        n = 0
        for m in range(self.sim.members):
            if not self.queue:
                break
            if not self.active[m]:
                self._admit(m, self.queue.popleft())
                n += 1
        return n

    def _admit(self, slot: int, req: FleetRequest) -> None:
        with tracing.span("admit", member=slot,
                          client=str(req.client_id)):
            self._admit_inner(slot, req)
        if self.latency is not None:
            self.latency.on_admit(req.client_id)

    def _admit_inner(self, slot: int, req: FleetRequest) -> None:
        sim = self.sim
        if req.bc is not None and req.bc != sim.grid.bc:
            # slot-pool executables are BC-table-specific (the edge
            # treatment is baked into the fused step): stepping this
            # session would silently run it under the wrong ghosts
            raise ValueError(
                f"request {req.client_id!r}: session BCTable "
                f"({req.bc.token}) does not match the pool's "
                f"({sim.grid.bc.token}); submit it to a pool built "
                "with that table")
        meta: dict = {}
        if req.checkpoint is not None:
            from .io import load_member_checkpoint
            st, meta = load_member_checkpoint(req.checkpoint, sim.grid)
        else:
            st = req.state
        if st is None:
            raise ValueError(
                f"request {req.client_id!r}: neither state nor "
                "checkpoint provided")
        t0 = float(meta.get("time", req.t0))
        sim.times[slot] = t0
        nd = req.next_dt if req.next_dt is not None \
            else meta.get("next_dt")
        sim.admit_member(slot, st, nd)
        self.active[slot] = True
        self._mask_dirty = True
        self.client[slot] = req.client_id
        self.t_end[slot] = float(req.t_end)
        self.admitted += 1
        if self.guard is not None:
            # the slot's watchdog history belongs to the RETIRED
            # occupant — a fresh session starts with a fresh clone
            self.guard.reset_member_watchdog(slot)
        self._emit(event="member_admit", member=slot,
                   client=req.client_id, t0=t0, t_end=float(req.t_end))

    def _free_slot(self, slot: int, zero: bool = False) -> None:
        if zero:   # eviction only — see the _zero comment in __init__
            self.sim.set_member_state(slot, self._zero)
        self.active[slot] = False
        self._mask_dirty = True
        self.client[slot] = None
        self.t_end[slot] = np.inf

    def _retire(self, slot: int) -> None:
        cid = self.client[slot]
        with tracing.span("retire", member=slot, client=str(cid)):
            ckpt = None
            if self.session_dir is not None:
                from .io import save_member_checkpoint
                ckpt = os.path.join(self.session_dir, str(cid))
                save_member_checkpoint(ckpt, self.sim, slot)
            t_done = float(self.sim.times[slot])
            self._free_slot(slot)
            self.retired += 1
            if self.clients is not None:
                self.clients.close(cid)
            self._emit(event="member_retire", member=slot, client=cid,
                       t=t_done, checkpoint=ckpt)

    def _on_member_abort(self, m: int, reason: str, step: int) -> None:
        """The guard's eviction hook (per-member ladder exhausted):
        free the slot and count the eviction. The guard re-anchors its
        snapshot ring right after this returns, so the fresh anchor
        already holds the zeroed dead slot and the healthy members'
        live states — their trajectories and clocks pass through
        bit-unchanged."""
        cid = self.client[m]
        with tracing.span("evict", member=m, client=str(cid),
                          reason=reason):
            self._free_slot(m, zero=True)
            self.evicted += 1
            # sync NOW, not lazily: the guard is mid-step and its
            # replay of the surviving members runs against the device
            # mask
            self.sim.set_active(self.active)
            self._mask_dirty = False
            if self.clients is not None:
                self.clients.close(cid)
            self._emit(event="member_evict", member=m, client=cid,
                       reason=reason, step=step)

    # -- the serving loop ---------------------------------------------
    def step(self) -> Optional[dict]:
        """One serving cycle: refill free slots from the queue, advance
        the whole pool one fused step, retire members whose clocks
        crossed their horizon. Returns the step record (None when the
        pool is empty and the queue has nothing to admit)."""
        if self._fill_slots() and self.guard is not None:
            # fresh anchor AFTER admissions: a later rewind must
            # restore the admitted state, never pre-admit slot contents
            self.guard.reanchor()
        if not self.active.any():
            return None
        if self._mask_dirty:
            # ONE device-mask push per cycle, however many slots the
            # admissions/retirements above flipped
            self.sim.set_active(self.active)
            self._mask_dirty = False
        lat = self.latency
        t0 = time.perf_counter() if lat is not None else 0.0
        rec = (self.guard.step() if self.guard is not None
               else self.sim.step_once())
        # who occupied each slot DURING this fused step: the recorder
        # runs after step() returns, by which time a retiring member's
        # slot is already cleared — its final step's telemetry row
        # must still reach its client stream (times[] keeps the
        # retiree's final clock until the next cycle's refill)
        self.step_clients = list(self.client)
        if lat is not None:
            lat.on_step(self.step_clients, time.perf_counter() - t0)
        done = np.flatnonzero(self.active
                              & (self.sim.times >= self.t_end))
        for m in done:
            self._retire(int(m))   # mask push deferred to next cycle
        return rec

    def park_all(self) -> int:
        """Retire every live member NOW (the CLI's preemption path):
        each session's checkpoint lands in ``session_dir``, resumable
        bit-exact via admit-from-checkpoint; the queue is left to the
        caller (requests hold no device state). Returns the number of
        sessions parked."""
        live = np.flatnonzero(self.active)
        for m in live:
            self._retire(int(m))
        if live.size:
            self.sim.set_active(self.active)
        return int(live.size)

    def drain(self, *, max_steps: Optional[int] = None) -> int:
        """Serve until the queue is empty and every slot has retired
        (or ``max_steps`` serving cycles elapsed). Returns the number
        of fused steps taken."""
        n = 0
        while self.queue or self.active.any():
            if max_steps is not None and n >= max_steps:
                break
            if self.step() is None:
                break
            n += 1
        return n
