"""Fleet batching: B independent uniform cases in ONE fused dispatch.

Every entry point before this module stepped exactly one case per
process, so small/medium grids leave the device dispatch-bound (the
BASELINE.md adaptive step is tunnel-latency bound at 0.218 s warm vs
21.5 ms device time). Batching independent cases onto one device is the
classic inference-stack throughput lever, and the codebase is shaped
for it: the step core is pure and trivially batchable (every stencil op
in ops/stencil.py is leading-dim agnostic), dt chains on device, and
the diag pull is already one batched ``device_get``. Multi-case
throughput is the same axis AMReX exploits for multiphysics fleets
(arXiv:2009.12009) and the FFT multi-block solver exploits for
massively parallel runs (arXiv:2106.03583).

:class:`FleetSim` advances B independent obstacle-free ``UniformSim``
cases per dispatch:

- the state is one ``FlowState`` with a leading member axis
  ``[B, ...]``; the whole Heun + penalization-free projection step is
  ONE jitted executable regardless of B;
- each member integrates at ITS OWN dt — no lockstep: dt is a ``[B]``
  device vector chained on device from each member's end-state umax,
  and the per-member clocks live in ``times`` (host, settled through
  the same one batched diag pull the single-case drivers pay);
- the pressure solves of all members run in ONE fused Krylov loop
  (``poisson.bicgstab(member_axis=True)``): per-member convergence
  mask, predicate = any member unconverged, converged members frozen
  via select so the extra sweeps are bit-exact identity for them;
- supervision is per-member (``resilience.FleetStepGuard``): a bad
  member restores ONLY its slice of the device snapshot ring and
  replays solo through :meth:`FleetSim.member_step_once`; healthy
  members never rewind.

Contract with the single-case driver (tests/test_fleet.py):
``FleetSim`` with B = 1 is BIT-IDENTICAL to ``UniformSim`` — same
trajectory, equal ``device_get`` counts. For B > 1 each member's
trajectory matches its solo run to <= 1e-12: the advection, projection
and every reduction (umax/energy/Krylov dots) are bit-exact per member
(measured — per-member reductions over ``[B, Ny, Nx]`` reduce the same
elements in the same order as the solo form), but the multigrid
V-cycle's fused elementwise sweep chains compile with different
FMA-contraction choices for member-batched operands (LLVM
vectorization over the leading axis), deviating ~1 ulp per sweep.
Flexible BiCGSTAB absorbs preconditioner inexactness by construction,
so the per-step trajectory deviation stays at ~1e-16..1e-13, the
short warm-start production solves keep IDENTICAL per-member iteration
counts and solver health, and the per-member clock can differ from the
solo clock by at most an ulp per step (the state deviation perturbing
the umax cell's last bit perturbs dt_next's) — pinned by
``tests/test_fleet.py::test_fleet_members_match_solo_runs`` (production
regime — warm deltap guesses, short solves). Long ROUGH solves (~50+
iterations on O(1) residuals) can compound the rounding into a
different — equally converged — Krylov path, so batched-vs-solo
agreement there is at the solve's own convergence target rather than
1e-12; the frozen-member invariance (the select mask) is exact
regardless and pinned separately.

Sharding composes (``mesh=``): when the per-member grid is small,
WHOLE MEMBERS are placed along the existing ``"x"`` mesh axis
(member-parallel — each member's stencils and reductions stay
shard-local, zero per-step halo collectives); big grids fall back to
the spatial x-split of ``ShardedUniformSim`` (with its spmd_safe
stencil forms), where the member axis rides along replicated.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .config import SimConfig
from .ops.pallas_kernels import fused_advect_heun
from .ops.stencil import (
    advect_diffuse_rhs,
    divergence_freeslip,
    dt_from_umax,
    heun_substage,
    laplacian5_neumann,
)
from .poisson import bicgstab, mg_solve, project_correct
from .uniform import FlowState, UniformGrid, pad_vector, taylor_green_state


def stack_states(states) -> FlowState:
    """Stack per-member FlowStates into one fleet state [B, ...]."""
    return FlowState(*(jnp.stack(list(leaves))
                       for leaves in zip(*states)))


def taylor_green_fleet(grid, members: int, amp0: float = 1.0,
                       decay: float = 0.8) -> FlowState:
    """A B-member ensemble of Taylor-Green vortices at geometrically
    decaying amplitudes (member m scaled by ``amp0 * decay**m``): the
    canonical obstacle-free validation case, with per-member umax — so
    every member runs at its OWN CFL dt and the no-lockstep contract is
    exercised for real (identical members would hide a lockstep bug)."""
    base = taylor_green_state(grid)
    return stack_states([
        base._replace(vel=base.vel * (amp0 * decay ** m))
        for m in range(members)])


class FleetSim:
    """Host-side driver for a B-member fleet: owns the shared step
    counter and per-member clocks, jits the fused member-batched step.

    Mirrors the ``UniformSim`` driver contract (``step_once`` /
    ``async_diag`` / ``_force_exact`` / ``_next_dt``) so the StepGuard
    machinery drives it unchanged — except the diag scalars are [B]
    vectors and the guard generalizes the verdict per member
    (resilience.FleetStepGuard).

    Placement (``mesh=``): ``placement="member"`` shards the leading
    member axis over the mesh (small grids — every member's compute is
    shard-local), ``"spatial"`` shards the x-axis like
    ``ShardedUniformSim`` (big grids), ``"auto"`` picks member-parallel
    when B divides the mesh and the per-member grid fits
    ``member_cells_cap`` cells, else spatial.
    """

    def __init__(self, cfg: SimConfig, level: Optional[int] = None,
                 members: int = 1, mesh=None, placement: str = "auto",
                 member_cells_cap: int = 1 << 22):
        if members < 1:
            raise ValueError(f"need members >= 1, got {members}")
        self.cfg = cfg
        self.members = int(members)
        self.mesh = mesh
        lvl = cfg.level_start if level is None else level
        nx = cfg.bpdx * cfg.bs << lvl
        ny = cfg.bpdy * cfg.bs << lvl
        if mesh is not None:
            ndev = mesh.devices.size
            if placement == "auto":
                placement = ("member"
                             if members % ndev == 0
                             and nx * ny <= member_cells_cap
                             else "spatial")
            if placement == "member" and members % ndev != 0:
                raise ValueError(
                    f"member placement needs members ({members}) "
                    f"divisible by mesh size {ndev}")
            if placement == "spatial" and nx % ndev != 0:
                raise ValueError(
                    f"spatial placement needs Nx={nx} divisible by "
                    f"mesh size {ndev}")
        else:
            placement = "single"
        self.placement = placement
        # spmd_safe only where spatial axes are actually sharded: the
        # member-parallel layout keeps every member's stencil axes
        # whole on one device, so the fast zero-shift form is safe
        self.grid = UniformGrid(cfg, level, spmd_safe=(placement == "spatial"))
        g = self.grid
        self.state = stack_states([g.zero_state()
                                   for _ in range(self.members)])
        self.times = np.zeros(self.members, dtype=np.float64)
        self.time = 0.0           # min over members (the loop condition)
        self.step_count = 0       # shared: one dispatch = one step for all
        self.shapes: list = []    # obstacle-free by construction
        self.timers = None
        self.force_log = None
        self._next_dt = None      # [B] device vector (end-state dt_next)
        self._force_exact = False
        self.async_diag = False
        out_shardings = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            if placement == "member":
                sv = NamedSharding(mesh, P("x", None, None, None))
                ss = NamedSharding(mesh, P("x", None, None))
            else:
                sv = NamedSharding(mesh, P(None, None, None, "x"))
                ss = NamedSharding(mesh, P(None, None, "x"))
            shardings = FlowState(vel=sv, pres=ss, chi=ss, us=sv, udef=sv)
            self.state = FlowState(*(jax.device_put(a, s) for a, s
                                     in zip(self.state, shardings)))
            out_shardings = (shardings, None)
        self._step = jax.jit(
            self._step_impl, donate_argnums=(0,),
            static_argnames=("exact_poisson",),
            **({"out_shardings": out_shardings}
               if out_shardings is not None else {}))
        self._dt = jax.jit(self._dt_impl)
        # single-member core for the guard's per-member rewind/replay
        # (the cold path): the SAME pure step the solo driver jits, on
        # one member's slice
        self._member_step = jax.jit(
            g.step, donate_argnums=(0,),
            static_argnames=("exact_poisson", "obstacle_terms"))
        self._member_dt = jax.jit(g.compute_dt)

    # -- fused member-batched step core -------------------------------
    def _dt_impl(self, vel: jnp.ndarray) -> jnp.ndarray:
        """Per-member CFL dt [B] from the fleet velocity [B,2,Ny,Nx]."""
        g = self.grid
        umax = jnp.max(jnp.abs(vel), axis=(-3, -2, -1))
        return dt_from_umax(umax, jnp.asarray(g.h, g.dtype),
                            g.cfg.nu, g.cfg.cfl)

    @property
    def poisson_mode(self) -> str:
        """Active solve-path latch (telemetry schema v4). Fleet reads
        the grid's latch — this module stays env-read-free by design
        (tests/test_env_latch.py walks it)."""
        return self.grid.poisson_mode

    @property
    def kernel_tier(self) -> str:
        """Active advection-kernel tier (telemetry schema v6) — the
        grid's constructor latch; under spatial placement the grid
        refuses the fused tier at construction (spmd_safe), so a
        FleetSim that exists is always tier-consistent."""
        return self.grid.kernel_tier

    @property
    def prec_mode(self) -> str:
        """Hot-loop storage precision (telemetry schema v6)."""
        return self.grid.prec_mode

    def _pressure_solve(self, rhs: jnp.ndarray, exact: bool):
        """Member-batched ``UniformGrid.pressure_solve``: same
        tolerances/refresh/stall policy and the same CUP2D_POIS solve
        path as the solo driver, ONE fused loop with the per-member
        convergence mask. Under ``fas`` the fleet runs member-batched
        MG cycles (the V-cycle is leading-dim agnostic) with the SAME
        converged-member freeze semantics — extra cycles the loop runs
        for the slowest member are bit-exact identity for converged
        ones (poisson.mg_solve member_axis); exact solves keep Krylov
        exactly like the solo path."""
        g = self.grid
        cfg = self.cfg
        if g.solver_mode == "fas" and not exact:
            return mg_solve(
                g.laplacian, rhs, g.mg,
                tol=cfg.poisson_tol, tol_rel=cfg.poisson_tol_rel,
                max_cycles=cfg.max_poisson_iterations,
                fmg=g.fas_fmg, member_axis=True,
            )
        return bicgstab(
            g.laplacian,
            rhs,
            M=g.mg if cfg.precond else None,
            tol=0.0 if exact else cfg.poisson_tol,
            tol_rel=0.0 if exact else cfg.poisson_tol_rel,
            max_iter=cfg.max_poisson_iterations,
            max_restarts=100 if exact else cfg.max_poisson_restarts,
            sum_dtype=g.sum_dtype,
            refresh_every=10 if exact else 50,
            stall_iters=20 if exact else 120,
            stall_rtol=0.99 if exact else 0.999,
            member_axis=True,
        )

    def _step_impl(self, state: FlowState, dt: jnp.ndarray,
                   exact_poisson: bool = False):
        """One fused step of every member: Heun advection-diffusion +
        deltap projection (obstacle-free — the identically-zero
        penalization/chi terms are statically dropped, like
        ``UniformGrid.step(obstacle_terms=False)``). ``dt`` is [B]."""
        g = self.grid
        h = g.h
        ih2 = 1.0 / (h * h)
        dt3 = dt[:, None, None]            # broadcast vs [B, Ny, Nx]
        dt4 = dt[:, None, None, None]      # broadcast vs [B, 2, Ny, Nx]

        # -- advection-diffusion, 2-stage Heun (per-member dt) --
        vel = state.vel
        if g.kernel_tier != "xla":
            # fused megakernel tier, member-batched: the kernel is
            # leading-dim agnostic with a per-member (afac, dfac) row,
            # so B members share ONE dispatch per substage
            vel = fused_advect_heun(
                vel, h, g.cfg.nu, dt,
                bf16=g.kernel_tier == "pallas-fused-bf16")
        else:
            vold = vel
            for c in (0.5, 1.0):
                lab = pad_vector(vel, 3)
                rhs = advect_diffuse_rhs(lab, 3, h, g.cfg.nu, dt4)
                vel = heun_substage(vold, c, rhs, ih2)

        # -- deltap pressure projection (chi == 0) --
        b = (0.5 * h / dt3) * divergence_freeslip(vel, g.spmd_safe)
        div_linf = jnp.max(jnp.abs(b), axis=(-2, -1)) * (dt / (h * h))
        b = b - laplacian5_neumann(state.pres, g.spmd_safe)
        res = self._pressure_solve(b, exact_poisson)
        vel, pres = project_correct(
            res.x, state.pres, vel, h, dt,
            spmd_safe=g.spmd_safe, mean_axes=(-2, -1),
            tier=g.kernel_tier)

        # -- per-member diag (the one batched pull's payload) --
        umax = jnp.max(jnp.abs(vel), axis=(-3, -2, -1))
        vv = vel.astype(g.sum_dtype) if g.sum_dtype is not None else vel
        energy = 0.5 * h * h * jnp.sum(vv * vv, axis=(-3, -2, -1))
        finite = (jnp.all(jnp.isfinite(vel), axis=(-3, -2, -1))
                  & jnp.all(jnp.isfinite(pres), axis=(-2, -1)))
        diag = {
            "poisson_iters": res.iters,
            "poisson_residual": res.residual,
            "poisson_stalled": res.stalled,
            "poisson_converged": res.converged,
            "finite": finite,
            "umax": umax,
            "energy": energy,
            "div_linf": div_linf,
            # per-member preconditioner-cycle counts [B] (schema v4;
            # the ONE shared accounting convention)
            "precond_cycles": g.precond_cycles(res, exact_poisson),
            "dt_next": dt_from_umax(umax, jnp.asarray(h, g.dtype),
                                    g.cfg.nu, g.cfg.cfl),
        }
        return state._replace(vel=vel, pres=pres), diag

    # -- driver contract (StepGuard-compatible) -----------------------
    def step_once(self, dt=None):
        """One fused fleet step. ``dt``: None (chained per-member
        device dt), a scalar (all members), or a [B] vector. One
        batched diag pull per step for the WHOLE fleet — or none under
        ``async_diag`` (the guard's lagged verdict pulls it)."""
        g = self.grid
        if dt is None:
            dt = (self._next_dt if self._next_dt is not None
                  else self._dt(self.state.vel))
        dt_dev = jnp.asarray(dt, g.dtype)
        if dt_dev.ndim == 0:
            dt_dev = jnp.full((self.members,), dt_dev, g.dtype)
        exact = self.step_count < 10 or self._force_exact
        timers = self.timers
        if timers is None:
            from .profiling import NULL_TIMERS
            timers = NULL_TIMERS
        with timers.phase("step"):
            self.state, diag = self._step(self.state, dt_dev,
                                          exact_poisson=exact)
            diag = dict(diag)
            diag["dt"] = dt_dev   # rides the one pull (per-member clocks)
            self._next_dt = diag["dt_next"]
            if self.async_diag:
                # -profile must still attribute device time to the
                # phase (fence = the documented cost of profiling, as
                # on the other drivers); the no-timers path stays
                # fence-free
                timers.fence("step", self.state.vel)
                self.step_count += 1
                return diag
            diag = jax.device_get(diag)   # the natural phase fence
        self.times = self.times + np.asarray(diag["dt"], np.float64)
        self.time = float(self.times.min())
        self.step_count += 1
        return diag

    # -- per-member access (the guard's slice rewind path) ------------
    def member_state(self, m: int) -> FlowState:
        """Member ``m``'s slice as a solo FlowState (fresh arrays)."""
        return FlowState(*(a[m] for a in self.state))

    def set_member_state(self, m: int, st: FlowState) -> None:
        """Install a solo FlowState into member ``m``'s slice; every
        other member's values pass through bit-unchanged."""
        self.state = FlowState(*(a.at[m].set(v)
                                 for a, v in zip(self.state, st)))

    def set_member_next_dt(self, m: int, dt_next) -> None:
        if self._next_dt is not None:
            self._next_dt = jnp.asarray(self._next_dt).at[m].set(
                jnp.asarray(dt_next, self.grid.dtype))

    def member_step_once(self, m: int, dt=None, exact: bool = False):
        """Advance ONLY member ``m`` one step through the solo
        single-member executable (the guard's replay/retry path —
        recovery is the cold path; the fused dispatch is the hot one).
        Leaves the shared step counter, the fleet dt cache and the
        clocks untouched: the caller (FleetStepGuard) owns those.
        Returns the solo diag dict (device scalars)."""
        st = self.member_state(m)
        if dt is None:
            dt = float(self._member_dt(st.vel))
        st, diag = self._member_step(
            st, jnp.asarray(dt, self.grid.dtype),
            exact_poisson=bool(exact), obstacle_terms=False)
        self.set_member_state(m, st)
        diag = dict(diag)
        diag["dt"] = float(dt)
        return diag

    def seed_taylor_green(self, amp0: float = 1.0,
                          decay: float = 0.8) -> None:
        """Seed the amplitude-laddered Taylor-Green ensemble (the CLI
        fleet mode's t=0 state: obstacle-free zero state would make a
        trivial run; the ladder gives every member its own umax/dt)."""
        st = taylor_green_fleet(self.grid, self.members, amp0, decay)
        if self.mesh is not None:
            st = FlowState(*(jax.device_put(np.asarray(a), b.sharding)
                             for a, b in zip(st, self.state)))
        self.state = st
