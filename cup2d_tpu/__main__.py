"""CLI driver: ``python -m cup2d_tpu <reference flags>``.

Runs the same case the reference's ``main()`` runs with the same flag
names (`/root/reference/main.cpp:6306-6341`, `run.sh:1-22`): e.g.

    python -m cup2d_tpu -bpdx 2 -bpdy 1 -levelMax 8 -levelStart 5 \
        -Rtol 2 -Ctol 1 -extent 4 -CFL 0.5 -tend 10 -lambda 1e7 \
        -nu 0.00004 -poissonTol 1e-3 -poissonTolRel 0.01 \
        -maxPoissonRestarts 0 -maxPoissonIterations 1000 -AdaptSteps 20 \
        -tdump 0.5 -shapes 'angle=0 L=0.2 xpos=1.8 ypos=0.8
                            angle=180 L=0.2 xpos=1.6 ypos=0.8'

By default this executes the adaptive (AMR) path, exactly like the
reference. Extra flags beyond the reference: ``-level N`` (force a
single-resolution uniform run at level N), ``-dtype``, ``-output DIR``,
``-checkpointEvery N``, ``-restart DIR``, ``-maxSteps N``, ``-profile``
(per-phase timer report + cells*steps/s at exit), and ``-fleet B``
(fleet batching, fleet.py: advance B independent obstacle-free uniform
cases in ONE fused dispatch — per-member device dt/clocks, one batched
diag pull for the whole fleet, per-member supervision via
FleetStepGuard, per-member telemetry in schema v3. The t=0 state is an
amplitude-laddered Taylor-Green ensemble so every member runs at its
own CFL dt; dumps write one reference-format triplet per member,
``vel.NNNNNNNN.mK``. Obstacle-free only: ``-shapes`` with ``-fleet``
is an error).

``-serve N`` (with ``-fleet B``, PR 11) switches the fleet to
CONTINUOUS-BATCHING SERVING (fleet.FleetServer): N staggered-horizon
sessions flow through the fixed-B slot pool — each admitted into a
free slot, stepped under the per-slot active mask, retired at its own
``t_end`` with a bit-exact session checkpoint in
``<output>/sessions/<client>/`` (resumable via admit-from-checkpoint),
and the freed slot refilled from the queue, all with ZERO steady-state
recompiles (the mask and slot indices are device operands). A member
whose recovery ladder exhausts is EVICTED (slot freed, ``member_evict``
event) instead of aborting the fleet. Telemetry gains the schema-v7
serving gauges (active_members/occupancy/admitted/evicted/queue_depth)
plus one per-client JSONL stream each under ``<output>/clients/``;
SIGTERM parks every live session as its checkpoint and exits 0.

MULTI-DEVICE & ELASTIC (parallel/, PR 7): ``-mesh N|all`` runs the
sharded drivers (ShardedUniformSim / ShardedAMRSim) over an N-device
(or every-device) 1-D mesh; multi-process bring-up takes
``-coordinator HOST:PORT -meshHosts P -processId I`` (TPU pods
autodetect all three) with the coordinator connect budget on
``-connectAttempts`` / ``-connectBackoff`` (argv-latched at the
init_distributed call — never a scattered env read). ``-elastic`` arms
the TopologyGuard: a bounded-timeout heartbeat piggybacked on the
step-boundary SIGTERM collective (``-heartbeatTimeout S``, a host lost
after ``-heartbeatMissK K`` missed beats), survivors agreeing on the
same shrunk device set from the same evidence. On a SIMULATED topology
(``-simHosts H`` groups the virtual devices of a single-process run
into H hosts; losses injected by CUP2D_FAULTS host_exit@N /
host_hang@N, with shard_loss@N zeroing the lost host's shard bytes for
an honest real-loss drill) recovery is fully in place: re-mesh the
survivors, resume from the device snapshot ring, continue — no
relaunch. With >= 2 hosts the elastic guard also arms the
HOST-REDUNDANT MIRROR TIER (PR 17, default on; ``-noMirror`` off,
``-mirror`` explicit, ``-mirrorEvery N`` thins the cadence): each
snapshot additionally ships every host's shard block to its ring
neighbor via one device-side ppermute, checksummed, so a loss that
DESTROYS the owner's shards still resumes from HBM down the
ring → mirror → disk → abort ladder (a corrupt/stale mirror is
rejected — ``mirror_reject`` event — never installed). On a REAL pod
the CLI today gives bounded detection + an orderly abort (the old
behavior was an indefinite hang); the in-place runtime re-init
(launch.reinit_distributed) is library-level, pending a working
multi-process runtime to validate against (ROADMAP).

CASE CATALOG (cases.py + bc.py, ISSUE 12): ``-case cavity|channel|
cylinder`` runs a named validation workload instead of parsing a
reference config — the case supplies its own SimConfig, per-face
BCTable and initial/obstacle state (``cavity``: lid-driven cavity,
four no-slip walls + moving lid, obstacle-free, also fleet-servable
via ``-fleet B``; ``channel``: Dirichlet inflow / convective outflow
past a fixed cylinder; ``cylinder``: the legacy towed-disk free-slip
case). ``-level N`` overrides the case's validation resolution. The
per-face boundary-condition engine behind it (bc.py BCTable:
free_slip | no_slip(u_wall) | dirichlet_inflow | convective_outflow
per face) is a uniform-family feature; the AMR/forest tier and the
Pallas megakernel tier refuse non-free-slip tables loudly at
construction and the default table is bit-identical to the legacy
free-slip/Neumann box.

The run loop is SUPERVISED (resilience.py): every step's health verdict
rides the diagnostics the step already pulls, a bad step walks the
rewind/escalate/disk-restore/abort ladder, SIGTERM checkpoints at the
next step boundary and exits 0, and every recovery lands in
``<output>/events.jsonl``. Since PR 4 the supervision tax is off the
hot loop: the good-state ring is DEVICE-RESIDENT (HBM copies, no
per-step D2H gather), ``-snapEvery N`` snapshots every N good steps
and replays bit-exactly from the last snapshot on a bad verdict, and
the verdict itself is ONE-STEP-LAGGED on the device-diag paths (step
N+1 dispatches before step N's scalars are pulled — detection latency
1 step, zero blocking per-step syncs; ``-noLag`` restores the eager
verdict). Knobs: ``-noSupervise`` (verdict-only: first bad step aborts
— still with a post-mortem checkpoint, unlike the old inline NaN
check), ``-guardRing K`` (confirmed-snapshot ring depth, default 1),
``-eventLog PATH``. Fault drills: the ``CUP2D_FAULTS`` env var
(faults.py) injects NaNs, wrong-but-finite field corruption, solver
give-ups, mid-save crashes and SIGTERMs on schedule.

TELEMETRY (profiling.py, PR 3) is on by default: one structured record
per step (solver health, dt/umax, kinetic energy + max |∇·u|, AMR
shape, halo comm volume, jit-recompile/device-pull counters, HBM peak,
phase times) streamed to ``<output>/metrics.jsonl`` — zero extra device
syncs, everything rides the step's one existing batched pull. Summarize
with ``python -m cup2d_tpu.post --metrics <path>``. The physics
invariants feed a drift watchdog wired into the recovery ladder
(catches wrong-but-FINITE corruption the isfinite verdict misses).
Knobs: ``-noMetrics``, ``-metricsLog PATH``, ``-noWatchdog``. Windowed
device tracing: ``CUP2D_TRACE=start:stop[:logdir]`` wraps exactly those
steps in a ``jax.profiler`` TensorBoard trace.

THE FLIGHT RECORDER (tracing.py, PR 18) rides the same zero-extra-sync
discipline: span timeline (``<output>/spans.jsonl``, export with
``python -m cup2d_tpu.post --trace``), compile/HBM ledger and serving
latency histograms (both summarized into ``metrics.jsonl`` at exit).
Knobs: ``-noSpans``, ``-spansLog PATH``, ``-noMemLedger``,
``-logRotateMB N`` (size-capped rotation of metrics/clients/spans
JSONL streams — default off), ``CUP2D_SPANS=0|N`` (disable spans /
ring capacity, latched once).
"""

from __future__ import annotations

import os
import sys
import time

from .config import CommandlineParser, SimConfig
from .io import dump_forest, dump_uniform, load_checkpoint, save_checkpoint


from .cache import enable_compilation_cache


def main(argv=None) -> int:
    enable_compilation_cache()
    argv = sys.argv[1:] if argv is None else argv
    p = CommandlineParser(argv)
    case_name = p("case").asString() if p.has("case") else None
    # a -case run gets its SimConfig from the catalog (cases.py), so
    # the reference flag set (-bpdx/-tend/...) is not required on the
    # command line; the case branch below sets cfg = sim.cfg
    cfg = None if case_name is not None else SimConfig.from_argv(argv)
    fleet_n = p("fleet").asInt() if p.has("fleet") else 0
    serve_n = p("serve").asInt() if p.has("serve") else 0
    if serve_n and not fleet_n:
        print("cup2d_tpu: -serve N needs -fleet B (the slot pool it "
              "serves through)", file=sys.stderr)
        return 2
    if serve_n and p.has("restart"):
        print("cup2d_tpu: -serve resumes per-session (admit from "
              "<output>/sessions/<client>), not from a whole-fleet "
              "-restart", file=sys.stderr)
        return 2
    uniform = (fleet_n > 0 or p.has("level") or case_name is not None
               or cfg.level_max <= 1)
    outdir = p("output").asString() if p.has("output") else "."
    ckpt_every = p("checkpointEvery").asInt() if p.has("checkpointEvery") \
        else 0
    max_steps = p("maxSteps").asInt() if p.has("maxSteps") else 10**9
    # size-capped JSONL rotation (metrics/clients/spans) — default off;
    # long serving runs cap each stream at N MB per segment
    rotate_mb = p("logRotateMB").asInt() if p.has("logRotateMB") else None
    os.makedirs(outdir, exist_ok=True)

    from . import faults
    from .profiling import HostCounters, MetricsRecorder, TraceWindow
    from .resilience import EventLog, FleetStepGuard, PhysicsWatchdog, \
        PreemptionGuard, ResilienceAbort, StepGuard, set_event_log

    plan = faults.FaultPlan.from_env()   # CUP2D_FAULTS, latched once
    faults.install(plan)                 # io.py's crash window consults it
    events_path = p("eventLog").asString() if p.has("eventLog") \
        else os.path.join(outdir, "events.jsonl")
    log = EventLog(events_path)
    set_event_log(log)                   # io/launch fallback events
    tracer = TraceWindow.from_env()      # CUP2D_TRACE, latched once

    # multi-device mesh (+ optional multi-process bring-up). The
    # connect budget is argv-latched HERE and passed down — satellite
    # of the elastic work: a scattered env read would be exactly the
    # mid-run-mutation hazard the CUP2D_* latch rule exists for.
    mesh = None
    if p.has("mesh"):
        from .parallel.launch import global_mesh, init_distributed
        from .parallel.mesh import make_mesh
        init_distributed(
            coordinator_address=(p("coordinator").asString()
                                 if p.has("coordinator") else None),
            num_processes=(p("meshHosts").asInt()
                           if p.has("meshHosts") else None),
            process_id=(p("processId").asInt()
                        if p.has("processId") else None),
            expected_processes=(p("meshHosts").asInt()
                                if p.has("meshHosts") else None),
            connect_attempts=(p("connectAttempts").asInt()
                              if p.has("connectAttempts") else 5),
            connect_backoff=(p("connectBackoff").asDouble()
                             if p.has("connectBackoff") else 1.0))
        spec = p("mesh").asString()
        mesh = global_mesh() if spec == "all" else make_mesh(int(spec))
    if p.has("elastic") and (mesh is None or mesh.devices.size < 2):
        print("cup2d_tpu: -elastic needs -mesh with at least 2 devices "
              "(nothing to re-mesh onto otherwise)", file=sys.stderr)
        return 2

    if case_name is not None:
        # validation-case catalog (cases.py): the case supplies its own
        # SimConfig + BCTable + initial/obstacle state; -level overrides
        # the validation resolution, -fleet serves fleet-capable cases
        from .cases import REGISTRY, make_sim
        spec = REGISTRY.get(case_name)
        if spec is None:
            names = ", ".join(c for c in REGISTRY)
            print(f"cup2d_tpu: unknown -case {case_name!r} "
                  f"(catalog: {names})", file=sys.stderr)
            return 2
        kw = {}
        if p.has("level"):
            kw["level"] = p("level").asInt()
        if fleet_n:
            if not spec.fleet_ok:
                print(f"cup2d_tpu: -case {case_name} does not ride the "
                      "fleet slot pool (obstacle cases are solo-driver "
                      "only)", file=sys.stderr)
                return 2
            kw["members"] = fleet_n
        if mesh is not None:
            if case_name != "cavity":
                print(f"cup2d_tpu: -case {case_name} does not combine "
                      "with -mesh (the sharded path is obstacle-free "
                      "only)", file=sys.stderr)
                return 2
            kw["mesh"] = mesh
        sim = make_sim(case_name, **kw)
        cfg = sim.cfg   # the case's config drives dt/dump/end-time below
        # -tend/-tdump still override the case's schedule (smoke runs
        # want short horizons without forking the catalog); the grid
        # and operators are already built from cfg, so only the
        # schedule fields may be overridden post hoc
        if p.has("tend"):
            cfg.end_time = p("tend").asDouble()
        if p.has("tdump"):
            cfg.dump_time = p("tdump").asDouble()
    elif fleet_n:
        if cfg.shapes:
            print("cup2d_tpu: -fleet supports obstacle-free uniform "
                  "runs only (shapes given)", file=sys.stderr)
            return 2
        if mesh is not None:
            print("cup2d_tpu: -fleet has its own placement policy "
                  "(fleet.py) and does not combine with -mesh",
                  file=sys.stderr)
            return 2
        from .fleet import FleetSim
        level = p("level").asInt() if p.has("level") else cfg.level_start
        sim = FleetSim(cfg, level=level, members=fleet_n)
        if not p.has("restart") and not serve_n:
            # obstacle-free zero state would be a trivial run: seed the
            # amplitude-laddered Taylor-Green ensemble (per-member umax
            # -> per-member dt, the no-lockstep contract live). A
            # SERVING run starts empty instead — sessions arrive
            # through the FleetServer queue
            sim.seed_taylor_green()
    elif uniform:
        level = p("level").asInt() if p.has("level") else cfg.level_start
        if mesh is not None:
            if cfg.shapes:
                print("cup2d_tpu: -mesh on the uniform path is "
                      "obstacle-free only (ShardedUniformSim)",
                      file=sys.stderr)
                return 2
            from .parallel.mesh import ShardedUniformSim
            from .uniform import taylor_green_state
            sim = ShardedUniformSim(cfg, mesh, level=level)
            if not p.has("restart"):
                # same rationale as the fleet seed: an obstacle-free
                # zero state is a trivial run
                sim.set_state(taylor_green_state(sim.grid))
        else:
            from .sim import Simulation
            sim = Simulation(cfg, level=level)
    else:
        if mesh is not None:
            from .parallel.forest_mesh import ShardedAMRSim
            sim = ShardedAMRSim(cfg, mesh)
        else:
            from .amr import AMRSim
            sim = AMRSim(cfg)
    if p.has("restart"):
        load_checkpoint(p("restart").asString(), sim)
    if p.has("profile"):
        from .profiling import PhaseTimers
        sim.timers = PhaseTimers()

    if not fleet_n and hasattr(type(sim), "force_log_header"):
        # the obstacle-free sharded driver (ShardedUniformSim) computes
        # no body forces — there is nothing to log, like the fleet
        force_path = os.path.join(outdir, "forces.csv")
        resuming = p.has("restart") and os.path.exists(force_path)
        sim.force_log = open(force_path, "a" if resuming else "w")
        if not resuming:
            sim.force_log.write(type(sim).force_log_header() + "\n")

    if sim.shapes and not p.has("restart"):
        # t=0 only: the chi-blend vel = vel(1-chi) + udef*chi would
        # discard the rigid-motion part of a RESTORED body-interior
        # velocity and silently fork the resumed trajectory (ADVICE.md
        # r1); load_checkpoint already marks the sim initialized.
        sim.initialize()   # so the t=0 dump sees the blended velocity

    def dump(path):
        if fleet_n:
            # one reference-format triplet per member, at the MEMBER's
            # own clock (sim.time is only the fleet min)
            for m in range(sim.members):
                dump_uniform(f"{path}.m{m}", float(sim.times[m]),
                             sim.state.vel[m], sim.grid.h)
        elif uniform:
            dump_uniform(path, sim.time, sim.state.vel, sim.grid.h)
        else:
            sim.sync_fields()
            dump_forest(path, sim.time, sim.forest)

    ckpt_path = os.path.join(outdir, "checkpoint")
    topo = None
    if p.has("elastic"):
        from .resilience import TopologyGuard, dist_initialized
        if not p.has("simHosts") and not dist_initialized():
            # single process without a simulated topology: the "real"
            # heartbeat would watch a 1-host world whose only possible
            # loss is this process itself — a host_exit fault would be
            # misdiagnosed as a pod losing its host. Usage error, not
            # a runtime misdiagnosis.
            print("cup2d_tpu: -elastic on a single-process run needs "
                  "-simHosts H (H >= 2) to stand up a simulated "
                  "topology; real heartbeats need a multi-process "
                  "bring-up (-coordinator/-meshHosts)", file=sys.stderr)
            return 2
        topo = TopologyGuard(
            devices=list(mesh.devices.flat),
            sim_hosts=(p("simHosts").asInt()
                       if p.has("simHosts") else None),
            miss_k=(p("heartbeatMissK").asInt()
                    if p.has("heartbeatMissK") else 3),
            timeout=(p("heartbeatTimeout").asDouble()
                     if p.has("heartbeatTimeout") else 10.0),
            faults=plan, event_log=log)
    # host-redundant mirror tier (PR 17): defaults ON when the elastic
    # machinery is armed over >= 2 (simulated or real) hosts — that is
    # exactly the regime where a host loss is survivable in HBM.
    # -mirror forces it on (still needs a topology), -noMirror off;
    # -mirrorEvery N thins the cadence.
    mirror_hosts = None
    if topo is not None and not p.has("noMirror"):
        if topo.n_hosts >= 2 and (p.has("mirror") or p.has("elastic")):
            mirror_hosts = topo.n_hosts
    guard_cls = FleetStepGuard if fleet_n else StepGuard
    guard = guard_cls(
        sim,
        ring=p("guardRing").asInt() if p.has("guardRing") else 1,
        ckpt_dir=ckpt_path,
        postmortem_dir=os.path.join(outdir, "postmortem"),
        event_log=log,
        faults=plan,
        recover=not p.has("noSupervise"),
        watchdog=None if p.has("noWatchdog") else PhysicsWatchdog(),
        snap_every=p("snapEvery").asInt() if p.has("snapEvery") else 1,
        lag=not p.has("noLag"),
        mirror_hosts=mirror_hosts,
        mirror_every=(p("mirrorEvery").asInt()
                      if p.has("mirrorEvery") else 1),
    )

    # -serve N: continuous-batching serving — N staggered-horizon
    # sessions flow through the B-slot pool (admit/retire/evict churn,
    # zero steady-state recompiles). The server wires the guard's
    # eviction rung (on_member_abort) in its constructor.
    server = None
    if serve_n:
        from .fleet import (FleetRequest, FleetServer, FlowState,
                            taylor_green_fleet)
        serving_lat = None
        if not p.has("noMetrics"):
            # latency histograms ride the server's existing submit/
            # admit/step boundaries — host clocks only
            from .tracing import ServingLatency
            serving_lat = ServingLatency()
        server = FleetServer(
            sim, guard=guard,
            session_dir=os.path.join(outdir, "sessions"),
            event_log=log,
            clients_dir=os.path.join(outdir, "clients"),
            clients_rotate_mb=rotate_mb, latency=serving_lat)
        # the session ladder: Taylor-Green at geometrically decaying
        # amplitudes (per-session umax -> per-session dt) with horizons
        # staggered across [tend/2, tend] so retirements interleave
        # with admissions (real churn, not one synchronized wave)
        ens = taylor_green_fleet(sim.grid, serve_n)
        for i in range(serve_n):
            t_end = cfg.end_time * (0.5 + 0.5 * (i + 1) / serve_n)
            server.submit(FleetRequest(
                client_id=f"s{i:04d}",
                state=FlowState(*(a[i] for a in ens)),
                t_end=t_end))

    # telemetry: on unless -noMetrics; the record rides the step's one
    # existing batched diag pull — under the lagged verdict the record
    # for step N is emitted when its verdict lands (during step N+1's
    # call, or at the final drain), labeled with the step's own
    # step/t/dt from the guard's record. The CALL-scoped host metrics
    # (wall_ms, jit_compiles/device_gets deltas, phase_ms) therefore
    # describe the call that resolved N — i.e. N+1's dispatch plus N's
    # lagged pull — a one-call skew that is CONSISTENT across those
    # fields (a compile spike and its wall cost land on the same row).
    metrics_log = None
    recorder = None
    counters = None
    flight = None
    spans_log = None
    if not p.has("noMetrics"):
        metrics_path = p("metricsLog").asString() if p.has("metricsLog") \
            else os.path.join(outdir, "metrics.jsonl")
        metrics_log = EventLog(metrics_path, rotate_mb=rotate_mb)
        counters = HostCounters().install()
        # flight recorder: span timeline (spans.jsonl, per process),
        # compile/HBM ledger (summarized into metrics.jsonl at exit).
        # Zero new device pulls — the zero-overhead contract is pinned
        # by tests/test_tracing.py (bit-identical, equal device_gets,
        # equal jit_compiles)
        from .tracing import FlightRecorder
        spans_path = p("spansLog").asString() if p.has("spansLog") \
            else os.path.join(outdir, "spans.jsonl")
        spans_log = EventLog(spans_path, rotate_mb=rotate_mb,
                             all_writers=True)
        flight = FlightRecorder.from_env(
            spans=not p.has("noSpans"),
            capture_memory=not p.has("noMemLedger"),
            sink=spans_log).install()
        recorder = MetricsRecorder(sink=metrics_log, counters=counters,
                                   timers=sim.timers, guard=guard,
                                   server=server, flight=flight)
        recorder.prime(sim)

    def record(rec, wall_ms=None):
        if rec is not None and recorder is not None:
            recorder.record_step(step=rec["step"], t=rec["t"],
                                 dt=rec["dt"], diag=rec, sim=sim,
                                 wall_ms=wall_ms)

    def drain():
        # settle every in-flight verdict (before dumps, regrids,
        # checkpoints, preemption saves and at loop exit — all of them
        # read state + clock, and a checkpoint of an unverdicted step
        # would persist a possibly-bad state); recovery may rewind the
        # clock, so callers re-check the loop condition after
        for rec in guard.drain():
            record(rec)

    # SIGTERM = preemption notice: finish the step in flight, write the
    # restart point, exit 0 (the grace window buys a checkpoint, not a
    # corpse). Installed around the loop only — library users keep
    # their own handlers.
    stop = PreemptionGuard().install()

    rc = 0
    try:
        if server is not None:
            # serving loop: refill / fused step / retire each cycle.
            # No -tdump schedule here — a session's artifact is its
            # save-on-retire checkpoint (sessions/<client>), and its
            # telemetry its per-client stream (clients/<client>.jsonl).
            # FleetStepGuard forces the eager verdict (lag=False), so
            # there is never a pending verdict between cycles and
            # admit/retire/evict always land on settled state.
            while ((server.queue or server.active.any())
                   and sim.step_count < max_steps):
                if stop.agree():
                    n_parked = server.park_all()
                    log.emit(event="sigterm_park", step=sim.step_count,
                             parked=n_parked, queued=len(server.queue),
                             signum=stop.signum)
                    print(f"cup2d_tpu: SIGTERM at step "
                          f"{sim.step_count} — {n_parked} live "
                          f"session(s) parked under "
                          f"{os.path.join(outdir, 'sessions')}, "
                          f"{len(server.queue)} still queued, exiting "
                          "cleanly", file=sys.stderr)
                    return 0
                if sim.step_count % 5 == 0:
                    print(f"cup2d_tpu: {sim.step_count:08d} serving "
                          f"{int(server.active.sum())}/{sim.members} "
                          f"slots, queue={len(server.queue)}, "
                          f"retired={server.retired}, "
                          f"evicted={server.evicted}", file=sys.stderr)
                t_step = time.perf_counter()
                rec = server.step()
                if rec is None:
                    break
                record(rec,
                       wall_ms=1e3 * (time.perf_counter() - t_step))
            print(f"cup2d_tpu: served {server.admitted} session(s): "
                  f"{server.retired} retired, {server.evicted} "
                  f"evicted, {len(server.queue)} unserved",
                  file=sys.stderr)
        next_dump = sim.time if cfg.dump_time > 0 else float("inf")
        while server is None:   # the classic (non-serving) run loop
            if not (sim.time < cfg.end_time
                    and sim.step_count < max_steps):
                # loop end — or a lagged NaN clock; the drain settles
                # pending verdicts (recovery rewinds a poisoned clock),
                # then the condition is re-checked. Note the lag-1
                # semantics: the condition reads the settled clock (one
                # step stale on the device-diag paths), so the run may
                # dispatch-and-commit ONE step more than a -noLag run
                # of the same case before stopping — the reference loop
                # itself overshoots tend by up to one dt, and the
                # per-step states remain bit-identical; only the
                # stopping point shifts by <= 1 step.
                if guard.pending:
                    drain()
                    continue
                break
            # agree() is a min-allreduce of the SIGTERM latch on pods
            # (all hosts enter the collective save at the same step —
            # the former ROADMAP pod gap (a)); single-host it is just
            # the local flag. With -elastic the SAME step-boundary
            # collective carries the heartbeat (TopologyGuard — one
            # bounded allgather instead of two).
            if topo is not None:
                beat = topo.step_boundary(stop, sim.step_count)
                if beat.self_lost:
                    # real-mode host_exit fault: die like a lost host
                    # would — hard, immediately, writing nothing (the
                    # survivors' detection drill)
                    os._exit(17)
                if beat.hung:
                    print("cup2d_tpu: heartbeat collective missed its "
                          f"{topo.timeout:.1f}s deadline at step "
                          f"{sim.step_count} — a peer died mid-step. "
                          "The old world's collectives are unusable; "
                          "in-place resume needs a runtime re-init "
                          "(parallel.launch.reinit_distributed, "
                          "orchestrator-driven). Aborting with the "
                          "last checkpoint intact.", file=sys.stderr)
                    return 1
                if beat.lost:
                    if topo.sim_hosts is None:
                        # real pod: detection is bounded, but in-place
                        # resume additionally needs the runtime re-init
                        # (see the hang branch) — orderly abort beats
                        # the pre-elastic indefinite hang
                        print(f"cup2d_tpu: hosts {list(beat.lost)} "
                              "left the program — aborting (in-place "
                              "pod resume pending a validated "
                              "reinit_distributed path, ROADMAP)",
                              file=sys.stderr)
                        return 1
                    # simulated topology: re-mesh the survivors and
                    # resume from the snapshot ring / disk, in place
                    guard.elastic_recover(topo)
                    continue
                stop_now = beat.stop
            else:
                stop_now = stop.agree()
            if stop_now:
                drain()
                save_checkpoint(ckpt_path, sim)
                log.emit(event="sigterm_checkpoint", step=sim.step_count,
                         sim_time=sim.time, path=ckpt_path,
                         signum=stop.signum)
                print(f"cup2d_tpu: SIGTERM at step {sim.step_count} — "
                      f"checkpoint written to {ckpt_path}, exiting "
                      "cleanly", file=sys.stderr)
                return 0
            if sim.step_count % 5 == 0:
                # the clock is settled-through-verdict: one step stale
                # on the lagged paths (cosmetic here)
                print(f"cup2d_tpu: {sim.step_count:08d} t={sim.time:.6f}",
                      file=sys.stderr)
            if cfg.dump_time > 0 and sim.time >= next_dump:
                # catch the schedule up even when dt > tdump (the
                # reference falls permanently behind there,
                # main.cpp:6597-6602); the lagged clock can trigger
                # this one step late — the dump itself must see a
                # settled, verdicted state, and the drain's recovery
                # may REWIND the clock below the schedule (disk-restore
                # rung), in which case this dump is not due after all
                drain()
                if sim.time >= next_dump:
                    while next_dump <= sim.time:
                        next_dump += cfg.dump_time
                    dump(os.path.join(outdir,
                                      f"vel.{sim.step_count:08d}"))
            if not uniform and (sim.step_count <= 10
                                or sim.step_count % cfg.adapt_steps == 0):
                drain()   # never regrid an unverdicted state
                sim.adapt()
            if tracer is not None:
                tracer.maybe_start(sim.step_count)
            t_step = time.perf_counter()
            rec = guard.step()
            if tracer is not None:
                tracer.maybe_stop(sim.step_count)
            record(rec, wall_ms=1e3 * (time.perf_counter() - t_step))
            if ckpt_every and sim.step_count % ckpt_every == 0:
                drain()
                save_checkpoint(ckpt_path, sim)
    except ResilienceAbort as e:
        # the guard already wrote the post-mortem checkpoint, emitted
        # the abort event and closed the force log
        print(f"cup2d_tpu: unrecoverable step failure — {e}",
              file=sys.stderr)
        rc = 1
    finally:
        stop.uninstall()
        if server is not None:
            server.close()   # flush/close the per-client streams
        if tracer is not None:
            tracer.close()   # a window past tend must not leak a trace
        if sim.force_log is not None and not sim.force_log.closed:
            sim.force_log.close()
        if counters is not None:
            counters.uninstall()
        if metrics_log is not None:
            # run-report rows: the serving latency distributions and
            # the compile blame ledger ride the metrics stream so
            # ``post --metrics`` summarizes them with the records
            if server is not None and server.latency is not None:
                metrics_log.emit(event="serving_latency",
                                 **server.latency.report())
            if flight is not None:
                metrics_log.emit(event="compile_ledger",
                                 **flight.ledger_report())
        if flight is not None:
            flight.close()      # flushes the span ring into spans_log
        if spans_log is not None:
            spans_log.close()
        if metrics_log is not None:
            metrics_log.close()
        set_event_log(None)
        log.close()
    if rc:
        return rc

    if not uniform:
        sim.sync_fields()   # leave the slot fields dict current
    if sim.timers is not None:
        from .profiling import throughput
        print(sim.timers.summary(), file=sys.stderr)
        print(f"cup2d_tpu: {throughput(sim)}", file=sys.stderr)
    print(f"cup2d_tpu: done at t={sim.time:.6f} "
          f"after {sim.step_count} steps", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
