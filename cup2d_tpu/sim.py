"""Simulation driver: obstacles + flow on the uniform grid.

Reproduces the reference time step (`/root/reference/main.cpp:6576-7290`)
with the reference's host/device split inverted TPU-style:

host (numpy f64, per step)       device (jit, per step)
---------------------------      -------------------------------------
rigid advection of shapes        SDF/udef window rasterization (gather)
midline kinematics (fish.py)     chi from sdf, integrals, udef de-mean
CoM/d_gm bookkeeping             advection-diffusion RK2
                                 penalization momentum solve (3x3)
                                 implicit penalization velocity update
                                 pressure Poisson (BiCGSTAB) + projection

Two jitted calls per step: ``_rasterize`` (the reference's ongrid device
part, main.cpp:4208-4630) and ``_flow_step`` (the rest of the loop —
advection, penalization momentum solve, shape-shape collision impulses
(main.cpp:6705-6943), projection, main.cpp:6607-7187). Shape count,
midline sizes and window sizes are static, so both compile once. Surface
force diagnostics (main.cpp:7188-7284) run as a third jitted call,
``_forces``, logged per step to the force CSV.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .config import SimConfig
from .models import DiskShape, FishShape
from .ops.collision import merged_overlap_integrals, \
    pairwise_collision_update
from .ops.forces import surface_forces
from .ops.obstacle import (
    chi_from_sdf,
    midline_udef,
    penalization_integrals,
    polygon_sdf,
    scatter_window_max,
    scatter_window_set,
    shape_integrals,
    solve_rigid_momentum,
    window_coords,
)
from .profiling import NULL_TIMERS
from .shapes_host import ShapeHostMixin
from .uniform import FlowState, UniformGrid, pad_scalar


class ObstacleFields(NamedTuple):
    """Per-step device obstacle state (the reference's per-shape Obstacle
    blocks + global chi/tmp grids, main.cpp:3283-3342)."""

    chi: jnp.ndarray      # [Ny, Nx] combined (max over shapes)
    sdf: jnp.ndarray      # [Ny, Nx] combined signed distance
    chi_s: jnp.ndarray    # [S, Ny, Nx]
    sdf_s: jnp.ndarray    # [S, Ny, Nx] per-shape signed distance
    udef_s: jnp.ndarray   # [S, 2, Ny, Nx] de-meaned deformation velocity
    com: jnp.ndarray      # [S, 2] chi-corrected centers of mass
    mass: jnp.ndarray     # [S]
    inertia: jnp.ndarray  # [S]


def make_shapes(cfg: SimConfig) -> list:
    """Build shape objects from the reference-style -shapes string."""
    out = []
    for d in cfg.parse_shapes():
        if d["kind"] == "disk":
            out.append(DiskShape(d["radius"], d["xpos"], d["ypos"]))
        else:
            out.append(FishShape(
                d["length"], d["xpos"], d["ypos"], d["angle"],
                cfg.min_h, period=d["T"],
            ))
    return out


class Simulation(ShapeHostMixin):
    """Uniform-grid simulation with immersed obstacles."""

    def __init__(self, cfg: SimConfig, shapes: Optional[Sequence] = None,
                 level: Optional[int] = None, bc=None):
        self.cfg = cfg
        # bc: per-face BCTable (bc.py, ISSUE 12); None keeps the legacy
        # free-slip box bit-identically (grid-level dispatch)
        self.grid = UniformGrid(cfg, level, bc=bc)
        self.shapes = list(shapes) if shapes is not None else make_shapes(cfg)
        self.case: Optional[str] = None  # case-registry tag (cases.py)
        self.time = 0.0
        self.step_count = 0
        self.state = self.grid.zero_state()
        g = self.grid
        # static window size per shape: the body diagonal plus the 4h
        # safety the reference adds to segment AABBs (main.cpp:4237);
        # clamped per axis so wide-but-short domains (bpdx=2, bpdy=1)
        # keep full x-coverage when the body exceeds the y-extent
        self._wins = []
        for s in self.shapes:
            w = int(np.ceil(1.25 * s.length / g.h)) + 12
            self._wins.append((min(w, g.nx), min(w, g.ny)))
        from . import tracing
        self._rasterize = tracing.named_jit(
            "sim.rasterize", jax.jit(self._rasterize_impl))
        # donate the state (arg 0) so pass-through fields aren't copied
        # every step; obs is NOT donated — _log_forces reads it after
        # the flow step returns
        self._flow_step = tracing.named_jit(
            "sim.flow_step", jax.jit(
                self._flow_step_impl, donate_argnums=(0,),
                static_argnames=("exact_poisson",)),
            variant=("exact_poisson",))
        self._flow_step_empty = tracing.named_jit(
            "sim.flow_step_empty", jax.jit(
                g.step, donate_argnums=(0,),
                static_argnames=("exact_poisson", "obstacle_terms")),
            variant=("exact_poisson",))
        self._forces = tracing.named_jit(
            "sim.forces", jax.jit(self._forces_impl))
        self._dt = tracing.named_jit("sim.dt", jax.jit(g.compute_dt))
        self.compute_forces_every = 1   # 0 disables the diagnostics pass
        self.force_log: Optional[object] = None  # file-like, CSV rows
        self.timers = None              # profiling.PhaseTimers, opt-in
        self._next_dt: Optional[float] = None  # from last step's umax
        # StepGuard's escalation rung forces the exact (tol-0) Poisson
        # solve on a retried step (resilience.py); OR-ed with the
        # reference's first-10-steps override below
        self._force_exact = False
        # lagged-verdict mode (resilience.StepGuard, lag=True): the
        # obstacle-free branch keeps the whole diag — including the dt
        # actually used and the cached dt_next — ON DEVICE, skips its
        # blocking pull and leaves the clock to the guard's lagged
        # verdict, so the device never waits on the host in steady
        # state. The shaped branch ignores this flag: its uvw/CoM pull
        # feeds the HOST kinematics of the next step and is inherently
        # synchronous.
        self.async_diag = False

    @property
    def poisson_mode(self) -> str:
        """Active solve-path latch (telemetry schema v4)."""
        return self.grid.poisson_mode

    @property
    def kernel_tier(self) -> str:
        """Active advection-kernel tier (telemetry schema v6). Since
        ISSUE 16 the value vocabulary carries a BC-token suffix on
        non-default tables — "pallas-fused+bc(<token>)" — so merged
        fleet streams attribute each record to the executable (one per
        table) that produced it; the schema key set is unchanged."""
        return self.grid.kernel_tier

    @property
    def prec_mode(self) -> str:
        """Hot-loop storage precision (telemetry schema v6)."""
        return self.grid.prec_mode

    @property
    def smoother_tier(self) -> str:
        """Pressure-hierarchy smoother tier (telemetry schema v11)."""
        return self.grid.smoother_tier

    @property
    def bc_table(self) -> str:
        """Per-face BC token string (telemetry schema v8)."""
        return self.grid.bc_table

    # ------------------------------------------------------------------
    # device: rasterization + chi + integrals (ongrid, main.cpp:4208-4630)
    # ------------------------------------------------------------------
    def _rasterize_impl(self, inputs):
        g = self.grid
        h = g.h
        dtype = g.dtype
        hsq = h * h
        S = len(self.shapes)

        sdf = jnp.full((g.ny, g.nx), -1.0, dtype=dtype)
        sdf_wins, udef_wins = [], []
        for k in range(S):
            inp = inputs[k]
            wx, wy = self._wins[k]
            x, y = window_coords(inp["ox"], inp["oy"], wx, wy, h, dtype)
            # local origin at the window center for f32 accuracy
            cx = (inp["ox"] + 0.5 * wx).astype(dtype) * h
            cy = (inp["oy"] + 0.5 * wy).astype(dtype) * h
            poly = inp["poly"] - jnp.stack([cx, cy])
            d = polygon_sdf(x - cx, y - cy, poly)
            mid_r = inp["mid_r"] - jnp.stack([cx, cy])
            ud = midline_udef(x - cx, y - cy, mid_r, inp["mid_v"],
                              inp["mid_nor"], inp["mid_vnor"], inp["width"])
            sdf_wins.append(d)
            udef_wins.append(ud)
            sdf = scatter_window_max(sdf, d, inp["oy"], inp["ox"])

        sdf_lab = pad_scalar(sdf, 1)
        chi = jnp.zeros((g.ny, g.nx), dtype=dtype)
        chi_s, sdf_s, udef_s = [], [], []
        coms, masses, inertias = [], [], []
        for k in range(S):
            inp = inputs[k]
            wx, wy = self._wins[k]
            # window + 1 ghost of the combined sdf (padded field indices
            # shift by +1, so (oy, ox) addresses unpadded (oy-1, ox-1))
            lab = jax.lax.dynamic_slice(
                sdf_lab, (inp["oy"], inp["ox"]), (wy + 2, wx + 2))
            chi_w = chi_from_sdf(lab, sdf_wins[k], h)
            x, y = window_coords(inp["ox"], inp["oy"], wx, wy, h, dtype)

            # CoM correction (main.cpp:4468-4487); zero-mass guard for
            # under-resolved bodies
            m0 = jnp.sum(chi_w * hsq)
            dcx = jnp.sum(chi_w * hsq * (x - inp["com"][0]))
            dcy = jnp.sum(chi_w * hsq * (y - inp["com"][1]))
            safe = jnp.where(m0 > 0, m0, 1.0)
            com = inp["com"] + jnp.where(
                m0 > 0, jnp.stack([dcx, dcy]) / safe, 0.0)

            # integrals + udef de-meaning (main.cpp:4488-4560)
            xr = x - com[0]
            yr = y - com[1]
            _, _, m, j, iu, iv, ia = shape_integrals(
                chi_w, udef_wins[k], xr, yr, hsq)
            ud = udef_wins[k] - jnp.stack([iu - ia * yr, iv + ia * xr])

            chi_full = scatter_window_set(
                jnp.zeros((g.ny, g.nx), dtype=dtype), chi_w,
                inp["oy"], inp["ox"])
            # background sentinel must fail the surface-band gate
            # own_sdf > -4h at EVERY level's h (forces.py); -extent does,
            # -1.0 does not once h >= 0.25 (ADVICE.md r1)
            sdf_full = scatter_window_set(
                jnp.full((g.ny, g.nx), -float(self.cfg.extent),
                         dtype=dtype), sdf_wins[k],
                inp["oy"], inp["ox"])
            udef_full = scatter_window_set(
                jnp.zeros((2, g.ny, g.nx), dtype=dtype), ud,
                inp["oy"], inp["ox"])
            chi = jnp.maximum(chi, chi_full)
            chi_s.append(chi_full)
            sdf_s.append(sdf_full)
            udef_s.append(udef_full)
            coms.append(com)
            masses.append(m)
            inertias.append(j)

        return ObstacleFields(
            chi=chi, sdf=sdf,
            chi_s=jnp.stack(chi_s), sdf_s=jnp.stack(sdf_s),
            udef_s=jnp.stack(udef_s),
            com=jnp.stack(coms), mass=jnp.stack(masses),
            inertia=jnp.stack(inertias),
        )

    # ------------------------------------------------------------------
    # device: one flow step (main.cpp:6607-7187)
    # ------------------------------------------------------------------
    def _flow_step_impl(self, state: FlowState, obs: ObstacleFields,
                        prescribed_uvw, dt, exact_poisson=False):
        g = self.grid
        cfg = self.cfg
        h = g.h
        S = len(self.shapes)
        x, y = g.cell_centers()
        x = jnp.asarray(x, dtype=g.dtype)
        y = jnp.asarray(y, dtype=g.dtype)

        vel = g.advect_heun(state.vel, dt)

        # rigid momentum solve per shape (main.cpp:6643-6704)
        uvw = []
        for k in range(S):
            if self.shapes[k].free:
                xr = x - obs.com[k, 0]
                yr = y - obs.com[k, 1]
                sums = penalization_integrals(
                    vel, obs.chi_s[k], obs.udef_s[k], xr, yr,
                    cfg.lam * dt, h * h)
                uvw.append(solve_rigid_momentum(*sums))
            else:
                uvw.append(prescribed_uvw[k])
        uvw = jnp.stack(uvw) if S else jnp.zeros((0, 3), g.dtype)

        # shape-shape collisions (main.cpp:6705-6943): chi-overlap
        # integrals per shape (merged over opponents, like the
        # reference's collisions[i] struct), then pairwise e=1 impulses
        # applied sequentially in pair order
        if S > 1:
            colls = merged_overlap_integrals(
                obs.chi_s, obs.sdf_s, obs.udef_s, uvw, obs.com, x, y)
            lengths = jnp.asarray(
                [s.length for s in self.shapes], g.dtype)
            uvw = pairwise_collision_update(
                colls, uvw, obs.mass, obs.inertia, obs.com, lengths)
            # prescribed-motion shapes are immovable: restore them
            for k in range(S):
                if not self.shapes[k].free:
                    uvw = uvw.at[k].set(prescribed_uvw[k])

        # implicit penalization update, winner shape per cell
        # (main.cpp:6944-6979)
        if S:
            win = jnp.argmax(obs.chi_s, axis=0)
            us = jnp.zeros_like(vel)
            for k in range(S):
                xr = x - obs.com[k, 0]
                yr = y - obs.com[k, 1]
                usk = jnp.stack([
                    uvw[k, 0] - uvw[k, 2] * yr + obs.udef_s[k, 0],
                    uvw[k, 1] + uvw[k, 2] * xr + obs.udef_s[k, 1],
                ])
                us = jnp.where(win == k, usk, us)
            alpha = jnp.where(
                obs.chi > 0.5, 1.0 / (1.0 + cfg.lam * dt), 1.0)
            vel = alpha * vel + (1.0 - alpha) * us

            udef = self._combined_udef(obs)
        else:
            us = jnp.zeros_like(vel)
            udef = jnp.zeros_like(vel)

        vel, pres, res, div_linf = g.project(
            vel, state.pres, obs.chi, udef, dt, exact_poisson)

        new_state = state._replace(vel=vel, pres=pres, chi=obs.chi,
                                   us=us, udef=udef)
        return new_state, uvw, g.step_diag(vel, pres, res, div_linf,
                                           exact=exact_poisson)

    # ------------------------------------------------------------------
    # device: surface force diagnostics (main.cpp:7188-7284)
    # ------------------------------------------------------------------
    def _forces_impl(self, state: FlowState, obs: ObstacleFields, uvw):
        g = self.grid
        out = []
        for k in range(len(self.shapes)):
            out.append(surface_forces(
                state.vel, state.pres, obs.chi, obs.sdf,
                obs.udef_s[k], obs.sdf_s[k], obs.com[k], uvw[k],
                self.cfg.nu, g.h))
        return out

    def _log_forces(self, obs, uvw):
        self._record_forces(self._forces(self.state, obs, uvw))

    # ------------------------------------------------------------------
    # host driver
    # ------------------------------------------------------------------
    def _shape_inputs(self):
        g = self.grid
        out = []
        for k, s in enumerate(self.shapes):
            wx, wy = self._wins[k]
            ox = int(np.clip(round(s.com[0] / g.h) - wx // 2, 0, g.nx - wx))
            oy = int(np.clip(round(s.com[1] / g.h) - wy // 2, 0, g.ny - wy))
            mid_r, mid_v, mid_nor, mid_vnor = s.midline_comp_frame()
            dt_ = g.dtype
            out.append({
                "ox": jnp.asarray(ox, jnp.int32),
                "oy": jnp.asarray(oy, jnp.int32),
                "poly": jnp.asarray(s.surface_polygon(), dtype=dt_),
                "mid_r": jnp.asarray(mid_r, dtype=dt_),
                "mid_v": jnp.asarray(mid_v, dtype=dt_),
                "mid_nor": jnp.asarray(mid_nor, dtype=dt_),
                "mid_vnor": jnp.asarray(mid_vnor, dtype=dt_),
                "width": jnp.asarray(s.width, dtype=dt_),
                "com": jnp.asarray(s.com, dtype=dt_),
            })
        return out

    def initialize(self):
        """Initial velocity := chi-blended deformation velocity
        (main.cpp:6546-6575): u = u (1 - chi) + udef chi."""
        if not self.shapes:
            self._initialized = True
            return
        for s in self.shapes:
            s.advect(0.0, self.cfg.extents)
            s.midline(self.time)
        obs = self._rasterize(self._shape_inputs())
        self._sync_shape_scalars(obs)
        udef = self._combined_udef(obs)
        vel = self.state.vel * (1.0 - obs.chi) + udef * obs.chi
        self.state = self.state._replace(vel=vel, chi=obs.chi)
        self._next_dt = None   # the blend rewrote vel; cached dt stale
        self._initialized = True

    @staticmethod
    def _combined_udef(obs: ObstacleFields) -> jnp.ndarray:
        """Deformation-velocity field for the pressure RHS and the
        initial blend: sum over shapes at cells where that shape's chi
        ties-or-wins the combined chi (main.cpp:6980-7006; ties sum)."""
        return jnp.sum(
            jnp.where((obs.chi_s >= obs.chi)[:, None], obs.udef_s, 0.0),
            axis=0)

    def step_once(self, dt: Optional[float] = None):
        g = self.grid
        cfg = self.cfg
        if not self.shapes:
            # obstacle-free: plain uniform step (no rasterization pass)
            tm = self.timers or NULL_TIMERS
            if dt is None:
                if self._next_dt is not None:
                    # host float on the sync path; under async_diag the
                    # cached dt_next is a DEVICE scalar fed straight
                    # back into the dispatch — no host round trip
                    dt = self._next_dt
                else:
                    with tm.phase("dt"):
                        dt = float(self._dt(self.state.vel))
            exact = self.step_count < 10 or self._force_exact
            dt_dev = jnp.asarray(dt, g.dtype)
            if self.async_diag:
                self.state, diag = self._flow_step_empty(
                    self.state, dt_dev,
                    exact_poisson=exact, obstacle_terms=False)
                diag = dict(diag)
                diag["dt"] = dt_dev          # the lagged clock's source
                self._next_dt = diag["dt_next"]
                # time deliberately NOT advanced (no host value for dt
                # exists yet); sim.step_count stays exact — it is pure
                # host arithmetic. Phase fences are skipped: a fence is
                # a host sync, the thing this mode removes.
                self.step_count += 1
                return diag
            with tm.phase("flow"):
                self.state, diag = self._flow_step_empty(
                    self.state, dt_dev,
                    exact_poisson=exact, obstacle_terms=False)
                # ONE batched pull of the whole diag dict (same single
                # transfer that used to fetch dt_next alone) — the
                # health verdict then reads pure host scalars for free
                diag = jax.device_get(diag)
                # the EXACT dt used, for the guard's replay record —
                # reconstructing it as time-after minus time-before
                # rounds differently by an ulp (review PR 4)
                diag["dt"] = float(dt)
                self._next_dt = float(diag["dt_next"])
                tm.fence("flow", self.state)
            self.time += dt
            self.step_count += 1
            return diag
        if not getattr(self, "_initialized", False):
            self.initialize()
        tm = self.timers or NULL_TIMERS
        if dt is None:
            if self._next_dt is not None:
                dt = min(self._next_dt, self._kinematic_dt_cap())
            else:
                with tm.phase("dt"):
                    dt = min(float(self._dt(self.state.vel)),
                             self._kinematic_dt_cap())

        # ongrid host part (main.cpp:3992-4207)
        with tm.phase("kinematics"):
            for s in self.shapes:
                s.advect(dt, cfg.extents)
                s.midline(self.time)

        with tm.phase("rasterize"):
            obs = self._rasterize(self._shape_inputs())
            self._sync_shape_scalars(obs)
            # fence the field outputs too (the scalar pull above only
            # proves the scalars landed): device raster time must land
            # in THIS phase, not in whoever synchronizes next
            tm.fence("rasterize", obs)

        prescribed = jnp.asarray(
            [[s.u, s.v, s.omega] for s in self.shapes], dtype=g.dtype
        ) if self.shapes else jnp.zeros((0, 3), g.dtype)
        exact = self.step_count < 10 or self._force_exact
        with tm.phase("flow"):
            self.state, uvw, diag = self._flow_step(
                self.state, obs, prescribed,
                jnp.asarray(dt, g.dtype), exact_poisson=exact)
            # the whole diag dict rides the ONE existing batched pull
            # (previously dt_next alone): the health verdict and the
            # driver's umax read then cost no further transfers
            uvw_np, diag = jax.device_get((uvw, diag))
            uvw_np = np.asarray(uvw_np, dtype=np.float64)
            diag["dt"] = float(dt)    # exact replay record (see above)
            self._next_dt = float(diag["dt_next"])
            # the scalar pull alone does not prove the donated state
            # landed; charge the field compute to "flow", not to the
            # next phase that happens to touch it
            tm.fence("flow", self.state)
        for k, s in enumerate(self.shapes):
            if s.free:
                s.u, s.v, s.omega = uvw_np[k]

        if self.shapes and self.compute_forces_every and \
                self.step_count % self.compute_forces_every == 0:
            with tm.phase("forces"):
                self._log_forces(obs, uvw)

        self.time += dt
        self.step_count += 1
        return diag

    def run(self, tend: float, max_steps: int = 10**9):
        diag = {}
        while self.time < tend and self.step_count < max_steps:
            diag = self.step_once()
        return diag
